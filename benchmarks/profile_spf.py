"""Decompose the headline SPF kernel time on real hardware.

VERDICT r2 item 1: 619 ms p50 with no profile. This harness answers:
  (a) how many relax sweeps does the 100k-node solve run?
  (b) what does ONE sweep of the XLA dense relax cost (ms, implied GB/s)?
  (c) does the Pallas VMEM kernel compile/run on the real chip, and what
      does one of its sweeps cost?
  (d) where does the time go (jax.profiler trace, optional)?

Run:  python benchmarks/profile_spf.py [--trace /tmp/spf_trace]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

N_NODES = 100_000
AVG_DEGREE = 20


def sync(x) -> float:
    """Force device completion (axon tunnel: block_until_ready returns
    early; fetching a scalar is the reliable sync)."""
    return float(x)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default=None, help="xprof trace dir")
    ap.add_argument("--nodes", type=int, default=N_NODES)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--skip-pallas", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from openr_tpu.ops.spf import (
        INF_DIST,
        batched_sssp_dense,
        build_dense_tables,
        pad_batch,
    )
    from openr_tpu.utils import topogen

    dev = jax.devices()[0]
    print(f"# device: {dev} platform={dev.platform}")

    edge_src, edge_dst, edge_metric, vp, n, e = topogen.erdos_renyi_csr(
        args.nodes, avg_degree=AVG_DEGREE, seed=0, max_metric=64
    )
    nbr, wgt = build_dense_tables(edge_src, edge_dst, edge_metric, vp)
    print(f"# graph: V={n} (padded {vp}) E={e} D={nbr.shape[1]}")

    me = 0
    valid = edge_metric < int(INF_DIST)
    nbrs = np.unique(edge_dst[(edge_src == me) & valid])
    b = pad_batch(min(1 + len(nbrs), args.batch))
    roots = np.full(b, me, dtype=np.int32)
    roots[1 : 1 + min(len(nbrs), b - 1)] = nbrs[: b - 1]

    d_nbr = jnp.asarray(nbr)
    d_wgt = jnp.asarray(wgt)
    d_over = jnp.asarray(np.zeros(vp, dtype=bool))
    d_roots = jnp.asarray(roots)

    # ---- (a) sweep count ------------------------------------------------
    @jax.jit
    def solve_with_iters(roots):
        num_nodes = d_nbr.shape[0]
        bb = roots.shape[0]
        dist = jnp.full((num_nodes, bb), INF_DIST, jnp.int32)
        dist = dist.at[roots, jnp.arange(bb)].set(0)

        def relax(state):
            dist, _c, it = state
            d = dist[d_nbr]
            cand = jnp.where(
                d < INF_DIST,
                jnp.minimum(d + d_wgt[:, :, None], INF_DIST),
                INF_DIST,
            )
            new = jnp.minimum(cand.min(axis=1), dist)
            return new, jnp.any(new < dist), it + 1

        def cond(state):
            return state[1] & (state[2] < num_nodes)

        dist, _, iters = jax.lax.while_loop(
            cond, relax, (dist, jnp.bool_(True), 0)
        )
        return dist.sum(), iters

    t0 = time.perf_counter()
    s, iters = solve_with_iters(d_roots)
    s = sync(s)
    compile_and_run = time.perf_counter() - t0
    iters = int(iters)
    print(f"# sweeps to fixpoint: {iters} (first run incl compile: "
          f"{compile_and_run*1e3:.0f} ms)")

    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        s, _ = solve_with_iters(d_roots)
        sync(s)
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    full_ms = times[len(times) // 2]
    print(f"# full solve (while_loop): p50 {full_ms:.1f} ms over 5")

    # ---- (b) one XLA sweep ---------------------------------------------
    @jax.jit
    def one_sweep(dist):
        d = dist[d_nbr]
        cand = jnp.where(
            d < INF_DIST,
            jnp.minimum(d + d_wgt[:, :, None], INF_DIST),
            INF_DIST,
        )
        new = jnp.minimum(cand.min(axis=1), dist)
        return new

    dist0 = jnp.full((vp, b), np.int32(INF_DIST), jnp.int32)
    dist0 = dist0.at[d_roots, jnp.arange(b)].set(0)
    w = one_sweep(dist0)
    sync(w.sum())
    times = []
    for _ in range(10):
        t0 = time.perf_counter()
        w = one_sweep(dist0)
        sync(w.sum())
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    sweep_ms = times[len(times) // 2]
    gathered_bytes = vp * nbr.shape[1] * b * 4
    print(
        f"# one XLA dense sweep: p50 {sweep_ms:.2f} ms "
        f"(gather output {gathered_bytes/1e9:.2f} GB → "
        f"{gathered_bytes/1e9/(sweep_ms/1e3):.0f} GB/s implied)"
    )
    print(f"# sweeps×sweep = {iters * sweep_ms:.1f} ms vs full {full_ms:.1f}")

    # ---- (c) pallas sweep ----------------------------------------------
    if not args.skip_pallas:
        try:
            from openr_tpu.ops.spf_pallas import _relax_once, pick_tile

            tile = pick_tile(vp, b, nbr.shape[1], want=256)
            print(f"# pallas tile: {tile}")
            over_t = jnp.zeros_like(d_nbr, dtype=bool)
            t0 = time.perf_counter()
            nd, ch = _relax_once(
                d_nbr, d_wgt, over_t, d_roots, dist0, tile, False, False
            )
            sync(ch)
            print(f"# pallas compile+run: {(time.perf_counter()-t0)*1e3:.0f} ms")
            # correctness vs XLA sweep
            ok = bool((nd == w).all())
            print(f"# pallas sweep == xla sweep: {ok}")
            times = []
            for _ in range(10):
                t0 = time.perf_counter()
                nd, ch = _relax_once(
                    d_nbr, d_wgt, over_t, d_roots, dist0, tile, False, False
                )
                sync(ch)
                times.append((time.perf_counter() - t0) * 1e3)
            times.sort()
            p_ms = times[len(times) // 2]
            print(
                f"# one pallas sweep: p50 {p_ms:.2f} ms "
                f"({gathered_bytes/1e9/(p_ms/1e3):.0f} GB/s implied)"
            )
        except Exception as ex:  # noqa: BLE001
            print(f"# pallas FAILED: {type(ex).__name__}: "
                  f"{str(ex).splitlines()[0][:300]}")

    # ---- (d) trace ------------------------------------------------------
    if args.trace:
        with jax.profiler.trace(args.trace):
            for _ in range(3):
                s, _ = solve_with_iters(d_roots)
                sync(s)
        print(f"# trace written to {args.trace}")


if __name__ == "__main__":
    main()
