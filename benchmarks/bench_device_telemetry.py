"""Device-telemetry smoke (`ci.sh` lane): the kernel cost ledger must
capture a cost/memory row for every canonical jitted kernel entry point
on the CPU backend, telemetry must export through ctrl, and the capture
path must add ZERO steady-state compiles (docs/Monitor.md "Device
telemetry").

Exercises each canonical entry point the way its production consumer
does — the split RIB solve via ``TpuSpfSolver.compute_routes``, the
batched kernels via ``_solve_dist`` table forcing, the sharded kernel
via a 2x2 mesh solver, and the election / KSP / Pallas wrappers with
production-shaped small inputs — then warms the compile ledger and
re-runs everything: any post-warmup XLA compile (including one caused
by the telemetry captures themselves) exits 1.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# the sharded section needs a multi-device CPU mesh: force the virtual
# device count BEFORE jax initializes (same dance as __graft_entry__)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

#: every canonical jitted kernel entry point must own a captured row
EXPECTED_KERNELS = (
    "batched_sssp_split_rib",   # fused split RIB solve (production path)
    "batched_sssp_split",       # batched split kernel (_solve_dist)
    "batched_sssp_dense",       # r2 dense kernel
    "batched_sssp",             # edge-list fallback kernel
    "first_hop_matrix",         # ECMP identity (non-split paths)
    "sharded_sssp_split",       # mesh-sharded split kernel
    "_elect_seg",               # device election segmented reductions
    "_ksp_edge_disjoint_dense_jit",  # k-shortest-paths kernel
    "_relax_once",              # pallas relax sweep (interpret on cpu)
)


def _fail(msg: str) -> None:
    print(f"DEVICE-TELEMETRY SMOKE FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def _run_kernels() -> None:
    """One call through every canonical entry point (compiles on the
    first pass, pure cache hits on the steady-state pass)."""
    import jax
    import jax.numpy as jnp

    from openr_tpu.decision.spf_backend import TpuSpfSolver
    from openr_tpu.ops.ksp import build_ksp_blocked, ksp_edge_disjoint_dense
    from openr_tpu.ops.spf_pallas import batched_sssp_pallas
    from openr_tpu.parallel import make_mesh
    from openr_tpu.utils.topogen import erdos_renyi_lsdb

    ls, ps, csr = erdos_renyi_lsdb(96, avg_degree=6, seed=3, max_metric=16)

    # production split RIB solve (batched_sssp_split_rib)
    tpu = TpuSpfSolver(native_rib="off")
    tpu.compute_routes(ls, ps, "node-0")

    # batched kernels via the dispatch seam each table kind uses
    roots = np.arange(8, dtype=np.int32) % csr.num_nodes
    tpu._solve_dist(csr, roots)  # split
    dense = TpuSpfSolver(use_dense=True, native_rib="off")
    fh_roots = np.arange(8, dtype=np.int32) % csr.num_nodes
    dense.solve(ls, "node-0")  # dense + first_hop_matrix
    edge = TpuSpfSolver(use_dense=False, native_rib="off")
    edge._solve_dist(csr, fh_roots)  # edge-list kernel

    # sharded split kernel over a 2x2 CPU mesh
    mesh = make_mesh(
        n_sources=2, n_graph=2, devices=jax.devices("cpu")[:4]
    )
    sharded = TpuSpfSolver(native_rib="off", mesh=mesh)
    b16 = np.arange(16, dtype=np.int32) % csr.num_nodes
    sharded._solve_dist(csr, b16)

    # device election (segmented reductions) on a tiny 2-advertiser
    # anycast matrix — the dispatch-threshold route is covered by
    # tests; the smoke wants the kernel row
    from openr_tpu.decision.election import MultiTable
    from openr_tpu.types.network import IpPrefix

    t = MultiTable(
        prefixes=[IpPrefix.make("10.9.0.0/32")],
        indptr=np.array([0, 2], np.int64),
        seg=np.zeros(2, np.int64),
        adv=np.array([1, 2], np.int64),
        known=np.ones(2, bool),
        rank=np.array([0, 1], np.int64),
        entries=[None, None],
        names=["node-1", "node-2"],
    )
    from openr_tpu.ops.election import elect_multi_device

    d_vec = np.arange(csr.padded_nodes, dtype=np.int64) + 1
    reach = np.ones(csr.padded_nodes, bool)
    elect_multi_device(t, d_vec, reach, 0, dev_cache={}, gen=0)

    # KSP kernel through its canonicalizing wrapper
    nbr, wgt = csr.dense_tables()
    blocked = build_ksp_blocked(nbr, csr.node_overloaded, 0)
    dests = np.arange(4, dtype=np.int32) % csr.num_nodes
    ksp_edge_disjoint_dense(
        nbr, wgt, blocked, 0, dests, k=2, max_hops=csr.padded_nodes
    )

    # Pallas relax sweep (interpret mode on cpu)
    batched_sssp_pallas(
        jnp.asarray(nbr), jnp.asarray(wgt),
        jnp.asarray(csr.node_overloaded),
        jnp.asarray(np.arange(4, dtype=np.int32) % csr.num_nodes),
        has_overloads=False,
    )


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true")
    ap.parse_args()

    from openr_tpu.monitor import compile_ledger
    from openr_tpu.monitor import device as device_telemetry

    led = compile_ledger.install()
    import jax

    if jax.default_backend() != "cpu":
        _fail(f"lane must run on cpu, got {jax.default_backend()}")

    _run_kernels()

    rows = device_telemetry.kernel_rows()
    missing = [k for k in EXPECTED_KERNELS if k not in rows]
    if missing:
        _fail(f"no cost row captured for: {missing} (have {sorted(rows)})")
    bad = [
        k
        for k in EXPECTED_KERNELS
        if rows[k].error is not None
        or rows[k].flops <= 0
        or rows[k].bytes_accessed <= 0
    ]
    if bad:
        detail = {k: rows[k].to_jsonable() for k in bad}
        _fail(f"degenerate cost rows: {detail}")

    # steady state: the SAME calls again — every kernel is a jit cache
    # hit and every telemetry observe() is a dict probe; any compile
    # (including one a capture would cause) fails the lane
    led.mark_warm()
    _run_kernels()
    steady = led.compiles_since_warm()
    if steady:
        _fail(f"steady-state compiles after warmup: {steady}")

    # ctrl export: a live node's get_device_telemetry must serve the
    # process-wide rows joined with its span stats, HBM degraded on cpu
    import asyncio

    from openr_tpu.emulator import Cluster
    from openr_tpu.rpc import RpcClient

    async def ctrl_check() -> dict:
        c = Cluster.from_edges([("a", "b")], enable_ctrl=True)
        await c.start()
        try:
            await c.wait_converged(timeout=60)
            cli = RpcClient(port=c.nodes["a"].ctrl.port)
            await cli.connect()
            try:
                return await cli.call("get_device_telemetry", {})
            finally:
                await cli.close()
        finally:
            await c.stop()

    res = asyncio.run(ctrl_check())
    served = {k["fn"] for k in res.get("kernels", [])}
    if not set(EXPECTED_KERNELS) <= served:
        _fail(
            f"ctrl get_device_telemetry missing kernels: "
            f"{set(EXPECTED_KERNELS) - served}"
        )
    if res.get("hbm_available") is not False or res.get("devices"):
        _fail(
            "cpu backend must degrade hbm telemetry "
            f"(got hbm_available={res.get('hbm_available')}, "
            f"devices={res.get('devices')})"
        )

    print(
        f"device-telemetry smoke ok: {len(rows)} kernel cost rows "
        f"({', '.join(sorted(k for k in EXPECTED_KERNELS))}), "
        f"0 steady-state compiles, ctrl export ok, hbm degraded on cpu"
    )


if __name__ == "__main__":
    main()
