"""Million-prefix data-plane bench: the prefix ramp (10k → 100k → 1M).

Measures the full production pipeline per rung — solve → vectorized
election → RIB assembly → diff → delta-native FIB programming — and the
phase split the ROADMAP's million-prefix item asks for:

  routes_per_sec   total routes / p50 of a steady-state full rebuild
                   cycle (compute_routes + diff + Fib fold/program) —
                   the same methodology as BENCH_r0x's `routes_per_sec`
                   (warm caches; the cold build is reported separately)
  election_ms      the solver's measured election phase (view fetch +
                   reachability/class masks + multi-advertiser matrix
                   election) — per-phase timers, NOT a subtraction
  assembly_ms      entry construction + class-dict reuse
  diff_ms          group-aware RouteDatabase diff of the warm rebuild
  fib_*            delta program pass + the O(1)-idle assertion
  churn            scoped advertiser-flip churn rounds over a fixed
                   pool: per-round latency, routes/sec through the
                   scoped path, and an RSS watermark across rounds
  scalar baseline  the per-prefix scalar oracle loop (vectorize=False)
                   on the same host — the speedup denominator AND the
                   byte-parity gate (unicast + MPLS equality)

--smoke runs one CI-sized rung and exits 1 unless parity holds, the
vectorized pipeline beats the scalar baseline ≥ 5x, zero steady-state
XLA compiles landed (PR 7 ledger), and the idle FIB pass stayed O(1).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import resource
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

def _rss_mb() -> float:
    from openr_tpu.watchdog.watchdog import _current_rss_mb

    got = _current_rss_mb()
    return float(got) if got is not None else 0.0


def _peak_rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def measure_prefix_ramp(
    prefix_counts=(10_000, 100_000, 1_000_000),
    nodes: int = 2048,
    avg_degree: int = 8,
    anycast_every: int = 200,
    iters: int = 4,
    churn_rounds: int = 3,
    churn_pool: int = 256,
    parity_max: int | None = None,
    scalar_max: int | None = None,
    seed: int = 0,
) -> dict:
    """Run the ramp; returns the JSON row. Heavy host work only — the
    solve itself is the configured jax backend (cpu in CI).

    ``parity_max`` / ``scalar_max`` cap the rung size for the scalar
    oracle comparison (None = always run; the scalar loop is the very
    baseline this pipeline replaces, so at 1M it costs ~tens of
    seconds — affordable once per committed row, skippable in CI)."""
    from openr_tpu.config import Config, NodeConfig
    from openr_tpu.decision import oracle
    from openr_tpu.decision.spf_backend import TpuSpfSolver
    from openr_tpu.fib import Fib, MockFibHandler
    from openr_tpu.messaging import ReplicateQueue
    from openr_tpu.monitor import Counters, compile_ledger
    from openr_tpu.types.routes import (
        RouteUpdate,
        RouteUpdateType,
        diff_route_dbs,
    )
    from openr_tpu.types.topology import PrefixDatabase
    from openr_tpu.utils.topogen import erdos_renyi_lsdb, ramp_prefix_state

    led = compile_ledger.install()
    ls, _ps0, csr = erdos_renyi_lsdb(
        nodes, avg_degree=avg_degree, seed=seed, max_metric=16
    )
    names = list(csr.node_names)
    me = names[0]
    solver = TpuSpfSolver(native_rib="off")
    row: dict = {
        "metric": "prefix_dataplane_ramp",
        "nodes": csr.num_nodes,
        "directed_edges": csr.num_edges,
        "anycast_every": anycast_every,
        "rungs": [],
    }

    async def _fib_cycle(fib, upd):
        fib._fold_update(upd)
        fib._have_rib = True
        t0 = time.perf_counter()
        await fib._program_once()
        return (time.perf_counter() - t0) * 1e3

    for n_prefixes in prefix_counts:
        r: dict = {"prefixes": n_prefixes}
        t0 = time.perf_counter()
        ps = ramp_prefix_state(names, n_prefixes, anycast_every=anycast_every)
        r["prefix_build_ms"] = round((time.perf_counter() - t0) * 1e3, 1)

        # ---- cold build (includes view construction + jit warmup) ----
        t0 = time.perf_counter()
        rdb, art = solver.compute_routes(ls, ps, me, return_artifact=True)
        r["cold_build_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        n_routes = len(rdb.unicast_routes) + len(rdb.mpls_routes)
        r["routes"] = n_routes

        cfg = Config(NodeConfig(node_name=me))
        routes_q = ReplicateQueue(name="routes")
        handler = MockFibHandler()
        fib = Fib(
            cfg, routes_q.get_reader(), handler, counters=Counters()
        )

        async def rung_body():
            # first RIB: FULL_SYNC program (the O(table) path, once)
            t0 = time.perf_counter()
            await _fib_cycle(
                fib,
                RouteUpdate(
                    type=RouteUpdateType.FULL_SYNC,
                    unicast_to_update=rdb.unicast_routes,
                    mpls_to_update=rdb.mpls_routes,
                ),
            )
            r["fib_full_sync_ms"] = round((time.perf_counter() - t0) * 1e3, 1)

            # one warm rebuild to settle every cache, then mark the
            # ledger: steady-state cycles must be pure jit-cache hits
            solver.compute_routes(ls, ps, me)
            led.mark_warm()

            # ---- steady-state full rebuild cycles --------------------
            cycles = []
            diffs = []
            fibs = []
            prev = rdb
            for _ in range(iters):
                c0 = time.perf_counter()
                new = solver.compute_routes(ls, ps, me)
                c1 = time.perf_counter()
                upd = diff_route_dbs(prev, new)
                c2 = time.perf_counter()
                fib_ms = await _fib_cycle(fib, upd)
                cycles.append((time.perf_counter() - c0) * 1e3)
                diffs.append((c2 - c1) * 1e3)
                fibs.append(fib_ms)
                prev = new
            cycles.sort()
            p50 = cycles[len(cycles) // 2]
            r["rebuild_p50_ms"] = round(p50, 1)
            r["diff_ms"] = round(sorted(diffs)[len(diffs) // 2], 2)
            r["fib_idle_pass_ms"] = round(sorted(fibs)[len(fibs) // 2], 3)
            r["routes_per_sec"] = round(n_routes / (p50 / 1e3), 1)
            r["election_ms"] = round(
                solver.last_phase_ms.get("election", 0.0), 2
            )
            r["assembly_ms"] = round(
                solver.last_phase_ms.get("assembly", 0.0), 2
            )
            r["mpls_ms"] = round(solver.last_phase_ms.get("mpls", 0.0), 2)
            r["nexthop_groups"] = len(solver._nh_intern)
            # idle FIB pass O(1) witness: the steady cycles above had
            # EMPTY deltas, so the delta book never grew
            r["fib_scan_routes"] = (
                fib.counters.get("fib.program_scan_routes") or 0
            )

            # ---- scoped churn rounds ---------------------------------
            pool = list(rdb.unicast_routes)[:churn_pool]
            name_idx = {n: i for i, n in enumerate(names)}
            churn = {"rounds": [], "pool": len(pool)}
            rss0 = None
            cur = prev
            art_now = art
            for rnd in range(churn_rounds):
                c0 = time.perf_counter()
                touched = set()
                for k, p in enumerate(pool):
                    per = ps.prefixes.get(p)
                    if not per:
                        continue
                    old_node = next(iter(per))
                    entry = per[old_node]
                    new_node = names[
                        (name_idx[old_node] + 1) % len(names)
                    ]
                    if new_node == me:
                        new_node = names[1]
                    ps.withdraw(old_node, p)
                    ps.update_prefix_db(
                        PrefixDatabase(
                            this_node_name=new_node,
                            prefix_entries=(entry,),
                        )
                    )
                    touched.add(p)
                entries = solver.assemble_prefix_routes(
                    art_now, ps, touched
                )
                new = type(cur)(this_node_name=me)
                new.unicast_routes = dict(cur.unicast_routes)
                new.mpls_routes = cur.mpls_routes
                for p in touched:
                    e = entries.get(p)
                    if e is None:
                        new.unicast_routes.pop(p, None)
                    else:
                        new.unicast_routes[p] = e
                upd = diff_route_dbs(
                    cur, new, prefix_scope=touched, label_scope=()
                )
                await _fib_cycle(fib, upd)
                ms = (time.perf_counter() - c0) * 1e3
                cur = new
                rss = _rss_mb()
                churn["rounds"].append(
                    {
                        "ms": round(ms, 2),
                        "touched": len(touched),
                        "programmed": len(upd.unicast_to_update)
                        + len(upd.unicast_to_delete),
                        "rss_mb": round(rss, 1),
                    }
                )
                if rnd == 0:
                    rss0 = rss
            churn["rss_growth_mb"] = round(
                (churn["rounds"][-1]["rss_mb"] - rss0) if rss0 else 0.0, 1
            )
            churn["routes_per_sec"] = round(
                sum(x["touched"] for x in churn["rounds"])
                / max(
                    sum(x["ms"] for x in churn["rounds"]) / 1e3, 1e-9
                ),
                1,
            )
            r["churn"] = churn

            steady = led.compiles_since_warm()
            r["steady_state_compiles"] = sum(steady.values())
            if steady:
                r["steady_state_fns"] = sorted(steady)
            led.reset_warm()
            r["fib_routes_programmed"] = (
                fib.counters.get("fib.routes_programmed") or 0
            )
            r["fib_program_batches"] = (
                fib.counters.get("fib.program_batches") or 0
            )

        asyncio.run(rung_body())

        # ---- scalar oracle baseline + byte-parity gate ---------------
        if scalar_max is None or n_prefixes <= scalar_max:
            t0 = time.perf_counter()
            sc = oracle.compute_routes(ls, ps, me, vectorize=False)
            scalar_ms = (time.perf_counter() - t0) * 1e3
            r["scalar_oracle_ms"] = round(scalar_ms, 1)
            r["scalar_routes_per_sec"] = round(
                n_routes / (scalar_ms / 1e3), 1
            )
            r["speedup_vs_scalar"] = round(
                scalar_ms / max(r["rebuild_p50_ms"], 1e-9), 1
            )
            if parity_max is None or n_prefixes <= parity_max:
                # NOTE: churn above moved advertisers, so compare a
                # fresh vectorized build against the scalar one — both
                # see the same post-churn PrefixState
                fresh = solver.compute_routes(ls, ps, me)
                ok = (
                    fresh.unicast_routes == sc.unicast_routes
                    and fresh.mpls_routes == sc.mpls_routes
                )
                r["parity"] = "ok" if ok else "MISMATCH"
        r["peak_rss_mb"] = round(_peak_rss_mb(), 1)
        row["rungs"].append(r)
    return row


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--prefixes", type=int, nargs="*", default=None)
    ap.add_argument("--nodes", type=int, default=2048)
    ap.add_argument("--iters", type=int, default=4)
    ap.add_argument("--anycast-every", type=int, default=200)
    ap.add_argument(
        "--scalar-max", type=int, default=None,
        help="skip the scalar baseline above this rung size",
    )
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    counts = tuple(args.prefixes) if args.prefixes else (
        (100_000,) if args.smoke else (10_000, 100_000, 1_000_000)
    )
    row = measure_prefix_ramp(
        prefix_counts=counts,
        nodes=args.nodes,
        iters=args.iters,
        anycast_every=args.anycast_every,
        scalar_max=args.scalar_max,
    )
    print(json.dumps(row))
    if not args.smoke:
        return 0
    rc = 0
    for r in row["rungs"]:
        if r.get("parity") != "ok":
            print(f"# SMOKE FAIL: parity {r.get('parity')!r} at "
                  f"{r['prefixes']} prefixes", file=sys.stderr)
            rc = 1
        if r.get("speedup_vs_scalar", 0) < 5.0:
            print(
                f"# SMOKE FAIL: speedup_vs_scalar "
                f"{r.get('speedup_vs_scalar')} < 5x at {r['prefixes']}",
                file=sys.stderr,
            )
            rc = 1
        if r.get("steady_state_compiles", 0) != 0:
            print(
                f"# SMOKE FAIL: {r['steady_state_compiles']} steady-state "
                f"compiles ({r.get('steady_state_fns')})", file=sys.stderr,
            )
            rc = 1
        if r.get("fib_scan_routes", 0) != 0:
            print(
                f"# SMOKE FAIL: idle FIB passes scanned "
                f"{r['fib_scan_routes']} routes (delta book not O(1))",
                file=sys.stderr,
            )
            rc = 1
    if rc == 0:
        print("# prefix-scale smoke ok", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
