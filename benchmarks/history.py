"""Bench-history sentinel: an append-only trajectory of bench.py runs.

The bench trajectory has so far been point-in-time JSON artifacts
(BENCH_r0x.json) committed by hand — there is no machine-readable
history a regression check can read. This module gives every
``bench.py`` run a one-line JSONL record in ``BENCH_HISTORY.jsonl``:

  * the bench's emitted row (metric/value/detail) verbatim,
  * the compile-ledger per-fn snapshot and the device-telemetry kernel
    cost rows (docs/Monitor.md "Device telemetry") at end of run,
  * a **host fingerprint** (platform / machine / python / jax /
    backend / cpu count) — comparisons only ever happen between runs
    with the SAME fingerprint, because a CPU-fallback laptop row and a
    real-TPU row are different experiments.

``--check`` compares the newest row's headline metrics against the
median of all PRIOR same-fingerprint rows and flags >25% regressions
(latency metrics up, throughput metrics down). The ci.sh lane runs it
warn-only: bench variance on burstable CI hosts is real, so the
sentinel's job is to make a drifting trajectory loud, not to block.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
HISTORY_PATH = REPO_ROOT / "BENCH_HISTORY.jsonl"

#: headline metrics compared by --check: name -> direction
#: ("lower" = regression when the value RISES, "higher" = when it falls)
HEADLINE_METRICS: dict[str, str] = {
    "value": "lower",  # the headline solve p50 (ms)
    "convergence_p50_ms": "lower",
    "prefix_churn_p50_ms": "lower",
    "topo_churn_p50_ms": "lower",
    "prefix_routes_per_sec": "higher",
    # steady-state work ledger (docs/Monitor.md "Work ledger"): a rising
    # touched/delta ratio on a delta-proportional stage means someone
    # reintroduced a full-table walk. merge and redistribute are
    # delta-native since ISSUE 17 (delta merge book + redistribution
    # entry books; BENCH_WORK_r02.json pins the baseline — ratios ~2
    # and ~1 instead of the r01-era ~10^4), so their ratios no longer
    # drift with table size: ANY sustained rise here is a reintroduced
    # O(routes) walk and trips the sentinel
    "work_merge_ratio": "lower",
    "work_redistribute_ratio": "lower",
    "work_election_ratio": "lower",
    "work_fib_ratio": "lower",
}

DEFAULT_TOLERANCE = 0.25


def host_fingerprint() -> dict:
    """The same-host / same-backend identity comparisons key on.
    Node name is included deliberately: two hosts with identical specs
    still have different background load profiles."""
    import platform

    fp = {
        "node": platform.node(),
        "machine": platform.machine(),
        "system": platform.system(),
        "python": platform.python_version(),
        "cpus": os.cpu_count(),
    }
    try:
        import jax

        fp["jax"] = jax.__version__
        fp["backend"] = jax.default_backend()
    except Exception:  # noqa: BLE001 — fingerprint works without a backend
        fp["jax"] = None
        fp["backend"] = None
    return fp


def fingerprint_key(fp: dict) -> str:
    import hashlib

    blob = json.dumps(fp, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def append_row(
    row: dict,
    compiles: dict | None = None,
    kernel_cost: dict | None = None,
    path: Path | str | None = None,
) -> dict:
    """Append one bench run's record; returns the record. Best-effort
    caller contract: bench.py wraps this in try/except so a read-only
    checkout can never fail a measurement."""
    p = Path(path) if path is not None else HISTORY_PATH
    fp = host_fingerprint()
    rec = {
        "ts": time.time(),
        "fingerprint": fp,
        "fp_key": fingerprint_key(fp),
        "row": row,
        "compiles": compiles or {},
        "kernel_cost": kernel_cost or {},
    }
    with open(p, "a") as f:
        f.write(json.dumps(rec, default=str) + "\n")
    return rec


def load_history(path: Path | str | None = None) -> list[dict]:
    p = Path(path) if path is not None else HISTORY_PATH
    if not p.exists():
        return []
    out = []
    with open(p) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # a torn tail line must not kill the check
    return out


def _median(vals: list[float]) -> float:
    # the shared exact nearest-rank percentile (monitor/fleet.py) —
    # the one definition flood_trace / convergence / fleet tables use
    from openr_tpu.monitor.fleet import percentile

    return percentile(vals, 0.5)


def _metric_value(rec: dict, metric: str) -> float | None:
    v = rec.get("row", {}).get(metric)
    if isinstance(v, (int, float)) and v == v:  # non-None, non-NaN
        return float(v)
    return None


def check_history(
    records: list[dict], tolerance: float = DEFAULT_TOLERANCE
) -> list[str]:
    """Compare the NEWEST record's headline metrics vs the median of all
    prior records sharing its fingerprint AND metric name (degraded
    runs rename the metric, so cpu_fallback rows never gate real-TPU
    ones). Returns human-readable warnings; empty = clean. Pure over
    the loaded records — testable without files."""
    if len(records) < 2:
        return []
    latest = records[-1]
    key = latest.get("fp_key")
    name = latest.get("row", {}).get("metric")
    prior = [
        r
        for r in records[:-1]
        if r.get("fp_key") == key and r.get("row", {}).get("metric") == name
    ]
    if not prior:
        return []
    warnings: list[str] = []
    for metric, direction in HEADLINE_METRICS.items():
        cur = _metric_value(latest, metric)
        if cur is None:
            continue
        base_vals = [
            v
            for v in (_metric_value(r, metric) for r in prior)
            if v is not None
        ]
        if not base_vals:
            continue
        base = _median(base_vals)
        if base <= 0:
            continue
        ratio = cur / base
        regressed = (
            ratio > 1 + tolerance
            if direction == "lower"
            else ratio < 1 - tolerance
        )
        if regressed:
            warnings.append(
                f"{metric}: {cur:g} vs median {base:g} of {len(base_vals)} "
                f"prior same-fingerprint run(s) "
                f"({(ratio - 1) * 100:+.1f}%, tolerance "
                f"{tolerance * 100:.0f}%)"
            )
    return warnings


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--check",
        action="store_true",
        help="compare the newest row vs prior same-fingerprint medians",
    )
    ap.add_argument("--path", default=None, help="history file override")
    ap.add_argument(
        "--tolerance", type=float, default=DEFAULT_TOLERANCE,
        help="relative regression threshold (default 0.25)",
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="exit 2 on regression (default: warn-only, exit 0)",
    )
    args = ap.parse_args(argv)
    if not args.check:
        ap.print_help()
        return 0
    records = load_history(args.path)
    if len(records) < 2:
        print(
            f"bench-history: {len(records)} record(s) — nothing to "
            "compare yet"
        )
        return 0
    warnings = check_history(records, tolerance=args.tolerance)
    if not warnings:
        fp = records[-1].get("fp_key", "?")
        print(
            f"bench-history: newest row within tolerance "
            f"({len(records)} records, fingerprint {fp})"
        )
        return 0
    for w in warnings:
        print(f"bench-history REGRESSION: {w}", file=sys.stderr)
    return 2 if args.strict else 0


if __name__ == "__main__":
    sys.exit(main())
