#!/bin/bash
# Probe the axon/TPU tunnel every ~3 min; append one line per probe to
# /tmp/tunnel_watch.log. A probe is a subprocess jax.devices() with a hard
# timeout (backend init HANGS, not errors, when the tunnel is wedged —
# bench.py._probe_default_backend rationale). Run in the background for the
# whole session so intermittent recovery windows (observed r3: tunnel came
# back twice) are caught within minutes.
LOG=${1:-/tmp/tunnel_watch.log}
INTERVAL=${2:-180}
while true; do
  t0=$(date +%s)
  out=$(timeout 45 python -u -c "
import jax, numpy as np, time
d = jax.devices()[0]
import jax.numpy as jnp
x = jnp.ones((128, 128))
t = time.perf_counter()
y = np.asarray(x @ x)
print(d.platform, d, round((time.perf_counter()-t)*1e3, 1), 'ms')
" 2>&1 | tail -1)
  rc=$?
  t1=$(date +%s)
  if [ $rc -eq 0 ]; then
    echo "$(date -u +%H:%M:%S) UP   ($((t1-t0))s) $out" >> "$LOG"
  else
    echo "$(date -u +%H:%M:%S) DOWN (rc=$rc, $((t1-t0))s)" >> "$LOG"
  fi
  sleep "$INTERVAL"
done
