#!/bin/bash
# Probe the axon/TPU tunnel every ~3 min; append one line per probe to the
# log. A probe is a subprocess jax.devices() + one real dispatch with a hard
# timeout (backend init HANGS, not errors, when the tunnel is wedged —
# bench.py._probe_default_backend rationale). Run in the background for the
# whole session so intermittent recovery windows (observed r3: tunnel came
# back twice) are caught within minutes.
#
# On a DOWN→UP transition, runs $ON_UP (if set) ONCE per transition — wire
# it to `benchmarks/validate_session.py; python bench.py` so a recovery
# window is spent measuring, not noticed after the fact.
#
# Probe timeout: OPENR_BENCH_PROBE_TIMEOUT (default 45 s here vs bench.py's
# 30 s — the watcher can afford to wait longer than the bench slot; a
# tunnel that inits in 30-45 s still logs UP here and the ON_UP bench run
# re-probes with its own budget). `timeout -k` sends SIGKILL 10 s after
# SIGTERM because a probe stuck in native TPU-init code ignores SIGTERM.
LOG=${1:-/tmp/tunnel_watch.log}
INTERVAL=${2:-180}
PROBE_T=${OPENR_BENCH_PROBE_TIMEOUT:-45}
was_up=0
while true; do
  t0=$(date +%s)
  out=$(timeout -k 10 "$PROBE_T" python -u -c "
import jax, numpy as np, time
d = jax.devices()[0]
import jax.numpy as jnp
x = jnp.ones((128, 128))
t = time.perf_counter()
y = np.asarray(x @ x)
print(d.platform, d, round((time.perf_counter()-t)*1e3, 1), 'ms')
" 2>&1)
  rc=$?
  t1=$(date +%s)
  last=$(printf '%s' "$out" | tail -1)
  if [ "$rc" -eq 0 ]; then
    echo "$(date -u +%H:%M:%S) UP   ($((t1-t0))s) $last" >> "$LOG"
    if [ "$was_up" -eq 0 ] && [ -n "$ON_UP" ]; then
      echo "$(date -u +%H:%M:%S) ON_UP hook firing: $ON_UP" >> "$LOG"
      bash -c "$ON_UP" >> "$LOG" 2>&1
    fi
    was_up=1
  else
    # keep the probe's own error tail: rc 124/137 = timeout (SIGTERM /
    # SIGKILL), anything else is an import/device error worth reading
    echo "$(date -u +%H:%M:%S) DOWN (rc=$rc, $((t1-t0))s) $last" >> "$LOG"
    was_up=0
  fi
  sleep "$INTERVAL"
done
