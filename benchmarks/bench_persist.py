"""Crash-recovery bench: journal engine micro-bench + warm-boot smoke.

Two halves, one CI lane (docs/Persist.md):

  * **micro** — the journal engine alone: append+fsync-batched write
    rate and cold replay rate over a synthetic book workload. The row
    lands in BENCH_HISTORY.jsonl via benchmarks/history.py, so the
    warn-only sentinel flags drift of the durable-write hot path.
  * **smoke** — a 16-node multi-process fat-tree pod (real sockets,
    real SIGKILL) with persistence on: snapshot the victim's durable
    book digests at quiescence, arm a torn write, drive doomed churn
    at the victim and real churn at a survivor, announce GR, SIGKILL,
    restart, then demand
      - the full cross-process invariant suite,
      - byte parity of the recovered books vs the pre-crash snapshot
        plus zero withdrawal window (proc_invariants.check_persist_recovery),
      - boot-time reconciliation proportional to the genuine
        desired-vs-durable diff (work.persist_replay.* counters,
        bound k*delta + floor — a full-reprogram regression trips it),
      - zero steady-state XLA compiles across the whole cycle.

Run: python benchmarks/bench_persist.py --smoke
Prints one JSON document (bench.py contract: metric/value/unit/
vs_baseline/detail); exit 0/1.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
sys.path.insert(0, str(Path(__file__).resolve().parent))

#: persist_replay acceptance bound — same family as the work ledger's
#: steady bound: touched <= K * delta + FLOOR (docs/Monitor.md)
_REPLAY_K = 8
_REPLAY_FLOOR = 64


def run_micro(n_records: int = 20_000) -> dict:
    """Journal append + cold-replay rates, engine only (no cluster)."""
    from openr_tpu.persist.journal import (
        Journal,
        JournalRecord,
        OP_SET,
        load_journal,
    )

    d = tempfile.mkdtemp(prefix="openr-persist-micro-")
    path = os.path.join(d, "journal.bin")
    try:
        j = Journal(path)
        recs = [
            JournalRecord(
                "bench", OP_SET, b"k%d" % (i % 4096), b"v%d" % i
            )
            for i in range(n_records)
        ]
        t0 = time.perf_counter()
        for r in recs:
            j.append(r)
        j.sync()
        append_s = time.perf_counter() - t0
        size = j.size
        j.close()

        t0 = time.perf_counter()
        replayed, torn = load_journal(path)
        replay_s = time.perf_counter() - t0
        assert len(replayed) == n_records and torn == 0
        return {
            "records": n_records,
            "journal_bytes": size,
            "append_us_per_record": round(append_s / n_records * 1e6, 3),
            "appends_per_sec": round(n_records / append_s, 1),
            "replay_us_per_record": round(replay_s / n_records * 1e6, 3),
            "replays_per_sec": round(n_records / replay_s, 1),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


async def run_smoke(args) -> dict:
    """CI lane: crash-consistent warm boot across a real process crash
    on a 16-node fat-tree pod, under an injected torn write."""
    from bench_cluster import _family_links, _fleet_sum

    from openr_tpu.emulator import proc_invariants
    from openr_tpu.emulator.procs import ProcCluster

    base = args.workdir or tempfile.mkdtemp(prefix="openr-persist-smoke-")
    links = _family_links("fat_tree_pod", 16, args.seed)
    cluster = ProcCluster(
        links, base, prefixes_per_node=args.smoke_prefixes,
        # survivors' hold must outlive the victim's re-exec window or
        # zero-withdrawal is unsatisfiable by construction
        spark_overrides={
            "hold_time_ms": 120_000,
            "graceful_restart_time_ms": 120_000,
        },
    )
    victim = sorted(cluster.nodes)[-1]  # a ToR, not a core
    survivor = sorted(cluster.nodes)[0]
    replay = f"bench_persist --smoke seed={args.seed}"
    try:
        t0 = time.monotonic()
        await cluster.start()
        await proc_invariants.wait_quiescent(
            cluster, timeout_s=120, context=f"{replay} cold"
        )
        cold = time.monotonic() - t0
        await proc_invariants.mark_fleet_warm(cluster)
        compiles0 = await _fleet_sum(cluster, "jax.compiles.total")

        pre = await proc_invariants.snapshot_persist(cluster, victim)
        if not pre["books"]:
            raise AssertionError(
                f"{victim} has no durable books at quiescence ({replay})"
            )

        # torn write armed, then doomed churn AT the victim: applied in
        # memory, flooded, but never durable — the crashed incarnation
        # must not resurrect any of it
        res = await cluster.inject_disk_fault(victim, "torn", at=5)
        if not res.get("ok"):
            raise AssertionError(f"fault arm failed: {res} ({replay})")
        await cluster.call(victim, "advertise_prefixes", {
            "prefixes": [f"10.96.66.{i}/32" for i in range(8)],
        })
        await cluster.call(victim, "spark_announce_restart")
        await cluster.crash_node(victim)  # SIGKILL

        # real churn at a survivor WHILE the victim is down: its warm
        # boot must reconcile exactly this delta on top of the durable
        # table (the persist_replay proportionality gate below)
        await cluster.call(survivor, "advertise_prefixes", {
            "prefixes": [f"10.96.77.{i}/32" for i in range(8)],
        })
        await asyncio.sleep(1.0)
        await cluster.restart_node(victim)
        await proc_invariants.wait_quiescent(
            cluster, timeout_s=120, context=f"{replay} warm boot"
        )

        violations = await proc_invariants.check_persist_recovery(
            cluster, pre
        )
        if violations:
            lines = "; ".join(str(v) for v in violations)
            raise AssertionError(
                f"crash-recovery invariant: {lines} ({replay})"
            )

        status = await cluster.get_persist_status(victim)
        rec = status.get("recovery") or {}
        if rec.get("truncated_bytes", 0) <= 0:
            raise AssertionError(
                f"torn write never bit: recovery {rec} ({replay})"
            )

        c = await cluster.call(
            victim, "get_counters", {"prefix": "work.persist_replay"}
        )
        touched = c.get("work.persist_replay.touched", 0)
        delta = c.get("work.persist_replay.delta", 0)
        if touched > _REPLAY_K * delta + _REPLAY_FLOOR:
            raise AssertionError(
                f"persist_replay reconciliation not delta-proportional: "
                f"touched {touched} vs delta {delta} "
                f"(bound {_REPLAY_K}*delta+{_REPLAY_FLOOR}) ({replay})"
            )

        compiles1 = await _fleet_sum(cluster, "jax.compiles.total")
        if compiles1 != compiles0:
            raise AssertionError(
                f"steady-state crash recovery compiled: jax.compiles."
                f"total {compiles0} -> {compiles1} ({replay})"
            )
        return {
            "nodes": len(cluster.nodes),
            "cold_converge_s": round(cold, 2),
            "victim": victim,
            "recovered_books": len(pre["books"]),
            "recovered_truncated_bytes": int(rec["truncated_bytes"]),
            "persist_replay_touched": int(touched),
            "persist_replay_delta": int(delta),
            "steady_compiles": int(compiles1 - compiles0),
            "invariants": "ok",
            "replay": replay,
        }
    finally:
        await cluster.stop()
        if not args.keep:
            shutil.rmtree(base, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(prog="bench_persist")
    ap.add_argument("--smoke", action="store_true",
                    help="also run the 16-node crash-recovery smoke")
    ap.add_argument("--micro-records", type=int, default=20_000)
    ap.add_argument("--smoke-prefixes", type=int, default=8)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--workdir", default=None)
    ap.add_argument(
        "--keep", action="store_true",
        help="keep the smoke workdir (configs + per-node logs)",
    )
    ap.add_argument("--out", default=None, help="also write JSON here")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    micro = run_micro(args.micro_records)
    result = {
        "metric": "persist_journal_append_us",
        "value": micro["append_us_per_record"],
        "unit": "us/record",
        "vs_baseline": None,
        "detail": {"micro": micro},
    }
    if args.smoke:
        try:
            result["detail"]["smoke"] = asyncio.run(run_smoke(args))
        except AssertionError as e:
            print(f"FAIL: {e}", file=sys.stderr)
            return 1
    try:
        import history

        history.append_row(result)
    except Exception as e:  # noqa: BLE001 — sentinel is best-effort
        print(f"history append skipped: {e}", file=sys.stderr)
    doc = json.dumps(result, indent=2)
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
