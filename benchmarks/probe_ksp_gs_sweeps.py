"""Sweep-count probe behind the KSP Gauss-Seidel negative result.

Counts fixpoint sweeps of the config-4 ring-of-rings SSSP under plain
Jacobi, forward Gauss-Seidel chunking (gs=4/8/16), and
alternating-direction chunking. Measured: 73 / 71 / 69 — chunk order
cannot beat the hop-limited dependency chain (a boundary only helps
when the frontier is AT it). Full analysis:
docs/spf_kernel_profile.md, "Negative result #2".
"""

from pathlib import Path
import os
import sys

REPO = str(Path(__file__).resolve().parent.parent)
sys.path.insert(0, REPO)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import importlib.util
spec = importlib.util.spec_from_file_location("bkl", REPO + "/benchmarks/bench_ksp_lfa.py")
m = importlib.util.module_from_spec(spec)
import types
sys.modules["bkl"] = m
# exec only the topology builder by importing module without main
src = open(REPO + "/benchmarks/bench_ksp_lfa.py").read()
ns = {}
ns["__file__"] = REPO + "/benchmarks/bench_ksp_lfa.py"
exec(compile(src.split("def main(")[0], "bkl", "exec"), ns)
dbs = ns["build_backbone"](128, 16)
from openr_tpu.decision.linkstate import LinkState
ls = LinkState()
for d in dbs: ls.update_adjacency_db(d)
csr = ls.to_csr()
from openr_tpu.ops.spf import build_dense_tables, INF_DIST
from openr_tpu.ops.ksp import build_ksp_blocked, _UNROLL_MAX_W
nbr, wgt = build_dense_tables(csr.edge_src, csr.edge_dst, csr.edge_metric, csr.padded_nodes)
print("tables:", nbr.shape)
n, width = nbr.shape
blocked = build_ksp_blocked(nbr, csr.node_overloaded, 0)
b = 8
dests = np.arange(1, 1 + b, dtype=np.int32) * 100

def sweeps(gs):
    csz = n // gs
    dist = jnp.full((n, b), INF_DIST, jnp.int32).at[0, :].set(0)
    usable = (~jnp.asarray(blocked)[:, :, None]) & jnp.broadcast_to(jnp.asarray(wgt)[:, :, None] < INF_DIST, (n, width, b))
    nbrj, wgtj = jnp.asarray(nbr), jnp.asarray(wgt)
    it = 0
    while True:
        dd = dist
        if gs == 1:
            acc = jnp.full((n, b), INF_DIST, jnp.int32)
            for col in range(width):
                g = dd[nbrj[:, col]]
                c = jnp.where(usable[:, col, :] & (g < INF_DIST), jnp.minimum(g + wgtj[:, col][:, None], INF_DIST), INF_DIST)
                acc = jnp.minimum(acc, c)
            new = jnp.minimum(acc, dd)
        else:
            new = dd
            for ci in range(gs):
                o = ci * csz
                acc = jnp.full((csz, b), INF_DIST, jnp.int32)
                for col in range(width):
                    g = new[nbrj[o:o+csz, col]]
                    c = jnp.where(usable[o:o+csz, col, :] & (g < INF_DIST), jnp.minimum(g + wgtj[o:o+csz, col][:, None], INF_DIST), INF_DIST)
                    acc = jnp.minimum(acc, c)
                new = new.at[o:o+csz].set(jnp.minimum(new[o:o+csz], acc))
        it += 1
        if not bool(jnp.any(new < dist)):
            break
        dist = new
        if it > n: break
    return it

for gs in (1, 4, 8, 16):
    print(f"gs={gs:2d}: {sweeps(gs)} sweeps")

def sweeps_alt(gs):
    csz = n // gs
    dist = jnp.full((n, b), INF_DIST, jnp.int32).at[0, :].set(0)
    usable = (~jnp.asarray(blocked)[:, :, None]) & jnp.broadcast_to(jnp.asarray(wgt)[:, :, None] < INF_DIST, (n, width, b))
    nbrj, wgtj = jnp.asarray(nbr), jnp.asarray(wgt)
    it = 0
    while True:
        dd = dist
        order = range(gs) if it % 2 == 0 else range(gs - 1, -1, -1)
        new = dd
        for ci in order:
            o = ci * csz
            acc = jnp.full((csz, b), INF_DIST, jnp.int32)
            for col in range(width):
                g = new[nbrj[o:o+csz, col]]
                c = jnp.where(usable[o:o+csz, col, :] & (g < INF_DIST), jnp.minimum(g + wgtj[o:o+csz, col][:, None], INF_DIST), INF_DIST)
                acc = jnp.minimum(acc, c)
            new = new.at[o:o+csz].set(jnp.minimum(new[o:o+csz], acc))
        it += 1
        if not bool(jnp.any(new < dist)):
            break
        dist = new
        if it > n: break
    return it

for gs in (4, 8, 16):
    print(f"alt gs={gs:2d}: {sweeps_alt(gs)} sweeps")
