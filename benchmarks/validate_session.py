"""One-shot real-chip validation for the session's kernel work.

Run when the axon tunnel is healthy:  python benchmarks/validate_session.py
Each row is flushed as it lands, most valuable first (the tunnel can
wedge mid-run — round-5 postmortem), so a partial run is still evidence:
  1. fused production solve wall p50 at 100k (tpu.solve: GS kernel +
     packed ~0.8 MB transfer) — the headline quantity;
  5. in-run oracle spot check (3 roots vs native C++ Dijkstra) —
     host+native-side, printed immediately after the headline so every
     later timing carries an already-printed oracle row;
  4. warm full-RIB p50 (solve + assembly with the entry/class caches);
  4b. hop-count-regime solve p50 (uniform metrics — same compiled
     kernel, ~5-8 sweeps; the north-star regime, docs/scaling.md §3);
  2. pure-kernel p50 via scalar drain (compare: 287 ms pre-GS);
  3. B=256 all-sources solve (compare: 505.6 ms).
(Row labels keep their historic numbers; order is window economics.)
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from openr_tpu.decision.spf_backend import TpuSpfSolver
from openr_tpu.utils.topogen import erdos_renyi_lsdb


def p50(fn, n=7, warm=2):
    for _ in range(warm):
        fn()
    vals = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        vals.append(time.perf_counter() - t0)
    vals.sort()
    return vals[len(vals) // 2] * 1e3


def main() -> None:
    import bench

    # single-chip serialization with the driver's bench run; always
    # yieldable — kill privilege is reserved for bench.py itself
    bench.acquire_bench_lock(yieldable=True)

    import jax

    print(f"# device: {jax.devices()[0]}", flush=True)
    ls, ps, csr = erdos_renyi_lsdb(100_000, avg_degree=20, seed=0, max_metric=64)
    tpu = TpuSpfSolver(native_rib="off")

    t = p50(lambda: tpu.solve(ls, "node-0"))
    print(f"1. fused solve wall p50      : {t:8.1f} ms", flush=True)

    my_id = csr.name_to_id["node-0"]

    # oracle spot check FIRST (window economics, round-5 postmortem:
    # the tunnel can wedge mid-run; this check is host+native-side
    # apart from one solve, so run it while the window is known-alive
    # — every later timing then carries an already-printed oracle row)
    from openr_tpu.ops.native_spf import OutCsr, native_available

    solved = tpu.solve(ls, "node-0")
    _csr_s, dist, fh, nbr_ids, _ = solved
    if native_available():
        oc = OutCsr.from_arrays(
            csr.edge_src, csr.edge_dst, csr.edge_metric, csr.padded_nodes
        )
        ok = True
        full = np.asarray(dist)
        for col, r in enumerate([my_id] + [int(x) for x in nbr_ids[:2]]):
            ref = oc.dijkstra(r)
            m = min(len(ref), full.shape[0])
            ok &= bool((ref[:m] == full[:m, col]).all())
        print(f"5. oracle (3 roots)          : {'ok' if ok else 'MISMATCH'}",
              flush=True)
    else:
        print("5. oracle: native lib not built", flush=True)

    def full_rib():
        return tpu.compute_routes(ls, ps, "node-0")

    t = p50(full_rib, n=5, warm=2)
    print(f"4. warm full RIB p50         : {t:8.1f} ms", flush=True)

    # hop-count metric regime (Open/R default; same table shapes → the
    # SAME compiled kernel, ~5-8 sweeps instead of ~19): the regime the
    # <10 ms north star is reachable in on v5e-4 (docs/scaling.md §3)
    ls_hop, _ps_hop, _csr_hop = erdos_renyi_lsdb(
        100_000, avg_degree=20, seed=0, max_metric=1
    )
    tpu.solve(ls_hop, "node-0")  # upload + warm
    t = p50(lambda: tpu.solve(ls_hop, "node-0"), n=5, warm=1)
    print(f"4b. hop-regime solve wall p50 : {t:8.1f} ms  "
          "(projected ~40 pre-d-loop)", flush=True)

    import jax.numpy as jnp

    dev = tpu._device_arrays(csr, "split")
    from openr_tpu.ops.spf_split import batched_sssp_split

    roots = np.full(32, my_id, np.int32)

    def solve_scalar():
        out = batched_sssp_split(
            dev["base_nbr"], dev["base_wgt"], dev["ov_ids"], dev["ov_nbr"],
            dev["ov_wgt"], dev["out_nbr"], dev["over"], jnp.asarray(roots),
            has_overloads=False,
        )
        return float(jnp.asarray(out[0, 0]))

    t = p50(solve_scalar)
    print(f"2. GS kernel p50 (scalar)    : {t:8.1f} ms  (pre-GS: 287)", flush=True)

    b256 = np.arange(256, dtype=np.int32) % csr.num_nodes

    def solve_b256():
        d = tpu._solve_dist(csr, b256)
        return float(np.asarray(d[:, 0]).sum())

    t = p50(solve_b256, n=3, warm=1)
    print(f"3. B=256 solve p50           : {t:8.1f} ms  (r3s1: 505.6)", flush=True)


if __name__ == "__main__":
    main()
