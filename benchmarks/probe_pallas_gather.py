"""Probe Mosaic dynamic_gather SPF-sweep formulations on real TPU.

Mosaic constraint (jax 0.9 lowering.py:_gather_lowering_rule): 2D only,
indices.shape == input.shape, out[i,j] = in[idx[i,j], j] (dims=[0]) or
out[i,j] = in[i, idx[i,j]] (dims=[1]). So a full SPF relax sweep is D
same-shape gathers accumulated with min:

  B1: for d in 0..D-1:  acc = min(acc, dist[nbr[:,d], :] + wgt[:,d,None])
  B2: lane-packed ×4: dist tiled to [VP, 4B] so each gather moves 128
      lanes (full VPU width) and D/4 gathers suffice.

Each variant is one pallas_call over the whole VMEM-resident arrays.
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

VP = 131072
B = 32
D = 64
INF = np.int32(1 << 30)

rng = np.random.default_rng(0)
dist_h = rng.integers(0, 1 << 20, size=(VP, B), dtype=np.int32)
nbr_h = rng.integers(0, VP, size=(VP, D), dtype=np.int32)
wgt_h = rng.integers(1, 64, size=(VP, D), dtype=np.int32)
dist = jnp.asarray(dist_h)
nbr = jnp.asarray(nbr_h)
wgt = jnp.asarray(wgt_h)

ref = np.minimum(
    (dist_h[nbr_h.reshape(-1)].reshape(VP, D, B).astype(np.int64)
     + wgt_h[:, :, None]).min(axis=1),
    dist_h,
).astype(np.int32)
ref_sum = int(np.int32(ref.astype(np.int64).sum() & 0xFFFFFFFF))


def sync(x):
    return int(x)


def bench(name, fn, *args):
    try:
        out = fn(*args)
        out.block_until_ready()
        s = int(np.int32(sync(out.sum()) & 0xFFFFFFFF))
        tag = "ok" if s == ref_sum else f"WRONG sum {s} != {ref_sum}"
        times = []
        for _ in range(8):
            t0 = time.perf_counter()
            out = fn(*args)
            sync(out.sum())
            times.append((time.perf_counter() - t0) * 1e3)
        times.sort()
        p50 = times[len(times) // 2]
        gb = VP * D * B * 4 / 1e9  # logical gathered bytes
        print(f"  {name}: p50 {p50:7.2f} ms "
              f"({gb/(p50/1e3):6.0f} GB/s eff)  [{tag}]")
    except Exception as e:  # noqa: BLE001
        lines = str(e).splitlines() or [repr(e)]
        print(f"  {name}: FAIL {type(e).__name__}: {lines[0][:160]}")
        for line in lines[1:4]:
            print(f"      {line[:160]}")


# ---------------- B1: d-loop of [VP, B] gathers --------------------------
def k_b1(nbr_ref, wgt_ref, dist_ref, out_ref):
    d_arr = dist_ref[:]
    acc = d_arr
    for d in range(D):
        idx = jnp.broadcast_to(nbr_ref[:, d][:, None], (VP, B))
        g = jnp.take_along_axis(d_arr, idx, axis=0)
        acc = jnp.minimum(acc, g + wgt_ref[:, d][:, None])
    out_ref[:] = acc


@jax.jit
def sweep_b1(nbr, wgt, dist):
    return pl.pallas_call(
        k_b1,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((VP, B), jnp.int32),
    )(nbr, wgt, dist)


# ---------------- B2: lane-packed 4× ------------------------------------
def k_b2(nbr_ref, wgt_ref, dist_ref, out_ref):
    d_arr = dist_ref[:]
    wide = jnp.concatenate([d_arr, d_arr, d_arr, d_arr], axis=1)  # [VP, 4B]
    acc = jnp.full((VP, 4 * B), INF, jnp.int32)
    for d0 in range(0, D, 4):
        idx = jnp.concatenate(
            [
                jnp.broadcast_to(nbr_ref[:, d0 + k][:, None], (VP, B))
                for k in range(4)
            ],
            axis=1,
        )
        w = jnp.concatenate(
            [
                jnp.broadcast_to(wgt_ref[:, d0 + k][:, None], (VP, B))
                for k in range(4)
            ],
            axis=1,
        )
        g = jnp.take_along_axis(wide, idx, axis=0)
        acc = jnp.minimum(acc, g + w)
    a = jnp.minimum(
        jnp.minimum(acc[:, 0:B], acc[:, B : 2 * B]),
        jnp.minimum(acc[:, 2 * B : 3 * B], acc[:, 3 * B :]),
    )
    out_ref[:] = jnp.minimum(a, d_arr)


@jax.jit
def sweep_b2(nbr, wgt, dist):
    return pl.pallas_call(
        k_b2,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((VP, B), jnp.int32),
    )(nbr, wgt, dist)


# ---------------- X: XLA reference sweep --------------------------------
@jax.jit
def sweep_xla(nbr, wgt, dist):
    d = dist[nbr]
    cand = jnp.minimum(d + wgt[:, :, None], INF)
    return jnp.minimum(cand.min(axis=1), dist)


print(f"# device: {jax.devices()[0]}  VP={VP} D={D} B={B}")
bench("X  xla sweep   ", sweep_xla, nbr, wgt, dist)
bench("B1 d-loop 32ln ", sweep_b1, nbr, wgt, dist)
bench("B2 packed 128ln", sweep_b2, nbr, wgt, dist)
