#!/bin/bash
# Window playbook: everything to measure when the tunnel comes up,
# most valuable first, each step individually time-boxed — a mid-window
# wedge still leaves every earlier artifact on disk.
#
# Wire as the ON_UP hook of tunnel_watch.sh / tunnel_standby.sh:
#   ON_UP='bash benchmarks/on_up_measure.sh' ...
# Steps (all yieldable to the driver's own bench slot via the bench.py
# lock protocol):
#   1. bench.py            — the headline row (sidecar-salvaged on wedge)
#   2. bench_ksp_lfa 10k   — BASELINE config 4 on-chip (verdict ask)
#   3. bench_fleet k=16    — all-nodes batch amortization, the TPU's win
#   4. validate_session    — scalar-drain kernel p50 + B=256 extras
set -u
cd "$(dirname "$0")/.."
ts=$(date -u +%H%M)
L=benchmarks/logs
mkdir -p "$L"

# Cross-process once-per-window dedup: BOTH detectors may latch a
# DOWN->UP transition for the same window (each other's probes hang
# against a running chain and reset the sibling's latch), so the chain
# itself refuses to start within COOLDOWN of the last start. mkdir is
# the atomic claim; a stale claim older than COOLDOWN is taken over.
COOLDOWN=${ONUP_COOLDOWN_S:-2700}
CLAIM="$L/onup_claim"
now=$(date +%s)
if [ -d "$CLAIM" ]; then
  last=$(stat -c %Y "$CLAIM" 2>/dev/null || echo 0)
  if [ $((now - last)) -lt "$COOLDOWN" ]; then
    echo "[$(date -u +%H:%M:%S)] on_up_measure deduped (last chain started $((now - last))s ago < ${COOLDOWN}s cooldown)"
    exit 0
  fi
  rmdir "$CLAIM" 2>/dev/null || rm -rf "$CLAIM"
fi
if ! mkdir "$CLAIM" 2>/dev/null; then
  echo "[$(date -u +%H:%M:%S)] on_up_measure deduped (concurrent chain holds the claim)"
  exit 0
fi

export OPENR_BENCH_YIELDABLE=1
# the lock-wait budget must exceed the largest step timeout, or an
# equal-priority contender would "proceed unserialized" mid-window
export OPENR_BENCH_LOCK_WAIT=${OPENR_BENCH_LOCK_WAIT:-3000}
echo "[$(date -u +%H:%M:%S)] on_up_measure start (ts=$ts)"
timeout -k 30 2400 python bench.py \
  > "$L/bench_onup_${ts}.out" 2>&1
rc=$?
echo "[$(date -u +%H:%M:%S)] bench.py done rc=$rc"
timeout -k 30 1200 python benchmarks/bench_ksp_lfa.py \
  --rings 626 --ring-size 16 \
  > "$L/ksp_onup_${ts}.out" 2>&1
rc=$?
echo "[$(date -u +%H:%M:%S)] bench_ksp_lfa done rc=$rc"
timeout -k 30 900 python benchmarks/bench_fleet.py --k 16 \
  > "$L/fleet_onup_${ts}.out" 2>&1
rc=$?
echo "[$(date -u +%H:%M:%S)] bench_fleet done rc=$rc"
timeout -k 30 1200 python benchmarks/validate_session.py \
  > "$L/validate_onup_${ts}.out" 2>&1
rc=$?
echo "[$(date -u +%H:%M:%S)] validate_session done rc=$rc"
