"""Fleet RIB rebuild: all nodes' routes from one batched device solve.

BASELINE configs 1-2 measure one node's rebuild; an emulator (or any
what-if analysis over a fabric) needs EVERY node's RIB. The reference
shape is N sequential solver runs; the TPU shape is one batched solve
(decision/fleet.py) + N host assemblies. This harness reports both, so
the batch amortization is a measured number rather than a claim.

Run: python benchmarks/bench_fleet.py [--k 16] [--backend cpu]
Prints one JSON line (same contract as bench.py).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=16, help="fat-tree k")
    ap.add_argument("--sample", type=int, default=8,
                    help="per-node solver sample size for the baseline")
    ap.add_argument("--backend", choices=("auto", "cpu"), default="auto")
    args = ap.parse_args()
    if args.backend == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        # real-chip run: serialize against the driver's bench slot;
        # always yieldable — an auxiliary harness must never kill a
        # live measurement (bench.py lock protocol)
        import bench

        bench.acquire_bench_lock(yieldable=True)

    from openr_tpu.decision.fleet import compute_fleet_ribs
    from openr_tpu.decision.linkstate import LinkState, PrefixState
    from openr_tpu.decision.spf_backend import TpuSpfSolver
    from openr_tpu.utils import topogen

    adj_dbs, prefix_dbs = topogen.fat_tree(args.k, metric=10)
    ls, ps = LinkState(), PrefixState()
    for db in adj_dbs:
        ls.update_adjacency_db(db)
    for db in prefix_dbs:
        ps.update_prefix_db(db)
    n = len(adj_dbs)

    solver = TpuSpfSolver(native_rib="off")
    compute_fleet_ribs(ls, ps, nodes=[ls.nodes[0]], solver=solver)  # warm

    t0 = time.perf_counter()
    fleet = compute_fleet_ribs(ls, ps, solver=solver)
    fleet_ms = (time.perf_counter() - t0) * 1e3
    n_routes = sum(
        len(r.unicast_routes) + len(r.mpls_routes) for r in fleet.values()
    )

    # per-node baseline (sampled): the reference shape — one solver run
    # per node
    rng = np.random.default_rng(0)
    sample = [
        ls.nodes[i]
        for i in rng.choice(n, size=min(args.sample, n), replace=False)
    ]
    per = TpuSpfSolver(native_rib="off")
    for node in sample:  # warm EVERY sampled batch shape (degree
        per.compute_routes(ls, ps, node)  # classes jit separately)
    t0 = time.perf_counter()
    for node in sample:
        per.compute_routes(ls, ps, node)
    per_node_ms = (time.perf_counter() - t0) * 1e3 / len(sample)

    print(
        json.dumps(
            {
                "metric": "fleet_full_rib_rebuild_ms",
                "value": round(fleet_ms, 3),
                "unit": "ms",
                "vs_baseline": round(per_node_ms * n / fleet_ms, 2),
                "detail": {
                    "nodes": n,
                    "routes": n_routes,
                    "routes_per_sec": round(
                        n_routes / (fleet_ms / 1e3), 1
                    ),
                    "per_node_solver_ms": round(per_node_ms, 3),
                    "per_node_extrapolated_ms": round(per_node_ms * n, 1),
                    "speedup_vs_per_node": round(
                        per_node_ms * n / fleet_ms, 2
                    ),
                    "backend": _backend(),
                },
            }
        )
    )


def _backend() -> str:
    import jax

    return jax.default_backend()


if __name__ == "__main__":
    main()
