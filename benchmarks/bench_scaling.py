"""Strong-scaling table for `sharded_sssp_split` on the virtual CPU mesh.

Usage:  python benchmarks/bench_scaling.py [n_nodes] [batch]

Measures the FLAGSHIP sharded solve (parallel/sharded_spf.py) at mesh
sizes 1/2/4/8 in both factorization families on one fixed graph:

  * sources-only  (S×1): roots sharded, no in-sweep collective;
  * graph-sharded (1×G): table rows sharded, one tiled all_gather per
    sweep over the graph axis (the ICI frontier exchange).

HONESTY NOTE (printed into the output): this host has ONE physical
core, and `--xla_force_host_platform_device_count` devices are threads
sharing it — wall-clock here CANNOT show parallel speedup. What the
table DOES measure is (a) correctness of every mesh program at every
size (each factorization is a different SPMD program), and (b) the
*sharding overhead*: wall(N devices) / wall(1 device) with compute
serialized is exactly the partition + collective overhead factor the
real-chip speedup has to beat. The v5e-4 projection combines that
overhead with the measured single-chip sweep rate (docs/
spf_kernel_profile.md) — see docs/scaling.md for the derivation.

Each row: mesh, wall p50 of 3 warm solves, per-device gathered rows per
sweep (the quantity that scales), bytes all-gathered per sweep.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_DEV = 8
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={N_DEV}"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from openr_tpu.ops.spf_split import build_split_tables  # noqa: E402
from openr_tpu.parallel import make_mesh, sharded_sssp_split  # noqa: E402
from openr_tpu.utils import topogen  # noqa: E402


def main() -> None:
    n_nodes = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    b = int(sys.argv[2]) if len(sys.argv) > 2 else 32
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001
        pass
    devs = jax.devices("cpu")
    assert len(devs) >= N_DEV, devs

    es, ed, em, _vp, nn, ne = topogen.erdos_renyi_csr(
        n_nodes, avg_degree=20, seed=0, max_metric=64
    )
    t = build_split_tables(es, ed, em, nn)
    vp, w = t["base_nbr"].shape
    args = (
        jnp.asarray(t["base_nbr"]), jnp.asarray(t["base_wgt"]),
        jnp.asarray(t["ov_ids"]), jnp.asarray(t["ov_nbr"]),
        jnp.asarray(t["ov_wgt"]), jnp.asarray(np.zeros(vp, bool)),
    )
    roots = jnp.asarray(np.arange(b, dtype=np.int32) % nn)
    print(
        f"# host cores: {os.cpu_count()} — virtual devices share them; "
        "wall ratios measure SHARDING OVERHEAD, not speedup (see "
        "module docstring)"
    )
    print(f"# graph: {nn} nodes / {ne} directed edges, vp={vp}, "
          f"W={w}, B={b}")

    rows = []
    meshes = [("sources", s, 1) for s in (1, 2, 4, 8) if b % s == 0]
    meshes += [("graph", 1, g) for g in (2, 4, 8) if vp % g == 0]
    ref = None
    for fam, s, g in meshes:
        mesh = make_mesh(n_sources=s, n_graph=g, devices=devs[: s * g])
        def solve():
            return sharded_sssp_split(*args, roots, mesh)
        d = np.asarray(solve())  # compile + run
        if ref is None:
            ref = d
        else:
            assert (d == ref).all(), f"mesh {s}x{g} distances diverge"
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(solve())
            times.append((time.perf_counter() - t0) * 1e3)
        times.sort()
        p50 = times[1]
        per_dev_rows = vp // g * w
        gathered_mb = (
            0.0 if g == 1 else vp * (b // s) * 4 / 1e6
        )  # all_gather output per sweep per device
        rows.append({
            "mesh": f"{s}x{g}", "family": fam, "devices": s * g,
            "wall_p50_ms": round(p50, 1),
            "per_dev_gather_rows_per_sweep": per_dev_rows,
            "allgather_mb_per_sweep": round(gathered_mb, 2),
        })
        print(json.dumps(rows[-1]), flush=True)

    base = next(r for r in rows if r["devices"] == 1)
    print("\n| mesh | devices | wall p50 (ms) | vs 1-dev | per-dev gather "
          "rows/sweep | all-gather MB/sweep |")
    print("|---|---|---|---|---|---|")
    for r in rows:
        print(
            f"| {r['mesh']} ({r['family']}) | {r['devices']} | "
            f"{r['wall_p50_ms']} | "
            f"{r['wall_p50_ms'] / base['wall_p50_ms']:.2f}x | "
            f"{r['per_dev_gather_rows_per_sweep']:,} | "
            f"{r['allgather_mb_per_sweep']} |"
        )


if __name__ == "__main__":
    main()
