"""Micro-probes for SPF kernel v3 design choices (v5e).

  1. d-loop gather with 1/2/4 independent min-chains (ILP)
  2. batch width B=8/16/32 effect on the d-loop gather
  3. degree-bucketed sweep: realistic widths (half nodes D=32, rest D=16/64)
  4. sparse-tail round: compact frontier (sort VP keys) + small gather +
     sort-based scatter — the cleanup-phase building block
"""

from __future__ import annotations

import gc
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import functools

import jax
import jax.numpy as jnp
import numpy as np

rng = np.random.default_rng(0)
K = 12
VP = 100352
D = 64

def _leaf(out):
    return float(jax.tree_util.tree_leaves(out)[0].reshape(-1)[0])


def timed(fn, *args, n=4):
    out = fn(*args)
    jax.block_until_ready(out)
    _leaf(out)
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        _leaf(out)
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return times[len(times) // 2]


def bench(name, make_body, init, rows):
    try:
        @functools.partial(jax.jit, static_argnames=("k",))
        def run(init, k):
            return jax.lax.fori_loop(0, k, lambda i, c: make_body(c), init)

        t1 = timed(lambda a: run(a, 1), init)
        tk = timed(lambda a: run(a, K), init)
        per = (tk - t1) / (K - 1)
        rate = rows / (per / 1e3) / 1e9 if per > 0.005 else float("inf")
        print(f"  {name:44s} {per:8.2f} ms   {rate:6.3f} Grows/s")
    except Exception as e:  # noqa: BLE001
        lines = [l for l in str(e).splitlines() if l.strip()] or [repr(e)]
        print(f"  {name:44s} FAIL {lines[0][:140]}")
    finally:
        gc.collect()


print(f"# device: {jax.devices()[0]}")

nbr_h = rng.integers(0, VP, size=(VP, D), dtype=np.int32)
wgt_h = rng.integers(1, 64, size=(VP, D), dtype=np.int32)
nbr = jnp.asarray(nbr_h)
wgt = jnp.asarray(wgt_h)
INF = np.int32(1 << 30)


def mk_dloop(nchains, b):
    dist0 = jnp.asarray(
        rng.integers(0, 1 << 20, size=(VP, b), dtype=np.int32)
    )

    def body(c):
        dist, = c
        accs = [dist] + [
            jnp.full((VP, b), INF, jnp.int32) for _ in range(nchains - 1)
        ]
        for d in range(D):
            g = dist[nbr[:, d]]
            cand = g + wgt[:, d][:, None]
            i = d % nchains
            accs[i] = jnp.minimum(accs[i], cand)
        acc = accs[0]
        for a in accs[1:]:
            acc = jnp.minimum(acc, a)
        return (jnp.minimum(acc, INF),)

    return body, (dist0,)


for nch, b in ((2, 32),):
    body, init = mk_dloop(nch, b)
    bench(f"d-loop B={b} chains={nch}", body, init, VP * D)


# ---- bucketed: 50% of nodes D=16, 35% D=32, 15% D=64 -------------------
splits = [(int(VP * 0.5) // 512 * 512, 16),
          (int(VP * 0.35) // 512 * 512, 32)]
splits.append((VP - sum(s for s, _ in splits), 64))
tabs = []
off = 0
for cnt, dd in splits:
    tabs.append((
        jnp.asarray(rng.integers(0, VP, size=(cnt, dd), dtype=np.int32)),
        jnp.asarray(rng.integers(1, 64, size=(cnt, dd), dtype=np.int32)),
        off,
    ))
    off += cnt
rows_bucketed = sum(cnt * dd for cnt, dd in splits)


def body_bucket(c):
    dist, = c
    outs = []
    for tnbr, twgt, _o in tabs:
        cnt, dd = tnbr.shape
        acc = jnp.full((cnt, 32), INF, jnp.int32)
        acc2 = jnp.full((cnt, 32), INF, jnp.int32)
        for d in range(dd):
            g = dist[tnbr[:, d]]
            cand = g + twgt[:, d][:, None]
            if d % 2 == 0:
                acc = jnp.minimum(acc, cand)
            else:
                acc2 = jnp.minimum(acc2, cand)
        outs.append(jnp.minimum(acc, acc2))
    new = jnp.concatenate(outs, axis=0)
    return (jnp.minimum(new, dist),)


dist0 = jnp.asarray(rng.integers(0, 1 << 20, size=(VP, 32), dtype=np.int32))
bench(f"bucketed sweep ({rows_bucketed/1e6:.1f}M rows)", body_bucket,
      (dist0,), rows_bucketed)


# ---- sparse tail round --------------------------------------------------
# frontier: ~2k changed nodes; compact via top_k on changed mask, gather
# their out-rows (Dout=64), sort (dst,cand), segment-min via sorted ids
FMAX = 4096
out_nbr = jnp.asarray(rng.integers(0, VP, size=(VP, D), dtype=np.int32))
out_wgt = jnp.asarray(rng.integers(1, 64, size=(VP, D), dtype=np.int32))


def body_sparse(c):
    dist, changed = c  # changed: [VP] bool mask (~2k true)
    # compact: key = (not-changed)<<20 | id  -> sort -> first FMAX
    key = jnp.where(changed, 0, 1 << 20) + jnp.arange(VP, dtype=jnp.int32)
    ids = jnp.sort(key)[:FMAX] & ((1 << 20) - 1)
    fnbr = out_nbr[ids]          # [FMAX, D] gather
    fwgt = out_wgt[ids]
    fdist = dist[ids]            # [FMAX, B]
    cand = fdist[:, :1] + fwgt   # [FMAX, D] (B=1 tail for probe)
    flat_dst = fnbr.reshape(-1)
    flat_val = cand.reshape(-1)
    ks, vs = jax.lax.sort([flat_dst, flat_val], num_keys=1)
    upd = jax.ops.segment_min(
        vs, ks, num_segments=VP, indices_are_sorted=True
    )
    nd = jnp.minimum(dist, upd[:, None])
    return (nd, changed != (nd[:, 0] < dist[:, 0]))


ch0 = jnp.asarray(rng.random(VP) < 0.02)
bench(f"sparse round F={FMAX} (gather+sort+segmin)", body_sparse,
      (dist0, ch0), FMAX * D)


# ---- scatter via scatter_min with unique-ish small input ----------------
def body_sc(c):
    dist, changed = c
    key = jnp.where(changed, 0, 1 << 20) + jnp.arange(VP, dtype=jnp.int32)
    ids = jnp.sort(key)[:FMAX] & ((1 << 20) - 1)
    fnbr = out_nbr[ids]
    fwgt = out_wgt[ids]
    fdist = dist[ids]
    cand = fdist[:, :1] + fwgt
    upd = jax.ops.segment_min(
        cand.reshape(-1), fnbr.reshape(-1), num_segments=VP
    )
    nd = jnp.minimum(dist, upd[:, None])
    return (nd, changed != (nd[:, 0] < dist[:, 0]))


bench(f"sparse round F={FMAX} (unsorted segmin)", body_sc,
      (dist0, ch0), FMAX * D)
