"""KvStore flood throughput under churn (rate limiter + coalescing).

Drives one store pair at a target key-update rate and reports what the
flood limiter put on the wire: messages sent, keys coalesced, max
pending-queue depth, backpressure drops, and time-to-convergence after
the churn stops.

Run: python benchmarks/bench_kvstore_flood.py [--updates-per-sec 1000]
     [--keys 100] [--seconds 5]
Prints one JSON line (same contract as bench.py).

reference analogue: openr/kvstore/tests/KvStoreBenchmark.cpp † (flood
fan-out measurement); the rate limiter mirrors KvStore.cpp's
floodLimiter_ + pending-publication buffering †.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


async def churn(updates_per_sec: int, n_keys: int, seconds: float) -> dict:
    from openr_tpu.config import Config
    from openr_tpu.kvstore import InProcKvTransport, KvStore
    from openr_tpu.kvstore.kvstore import PeerSpec
    from openr_tpu.messaging import ReplicateQueue
    from openr_tpu.monitor import Counters
    from openr_tpu.types.kvstore import Value

    t = InProcKvTransport()
    stores, counters = {}, {}
    for name in ("a", "b"):
        q = ReplicateQueue(name=f"{name}.pubs")
        c = Counters()
        s = KvStore(Config.default(name), t, q, counters=c)
        t.register(name, s)
        stores[name], counters[name] = s, c
        await s.start()
    stores["a"].add_peer_sync(PeerSpec(node_name="b"))
    stores["b"].add_peer_sync(PeerSpec(node_name="a"))
    await asyncio.sleep(0.1)

    peer = stores["a"].peers[("0", "b")]
    loop = asyncio.get_event_loop()
    batch = max(1, updates_per_sec // 100)  # 10ms pacing quantum
    total, ver, max_depth = 0, 0, 0
    t0 = loop.time()
    while loop.time() - t0 < seconds:
        ver += 1
        for i in range(batch):
            k = f"k{(total + i) % n_keys}"
            stores["a"].set_key(
                "0",
                k,
                Value(
                    version=ver, originator_id="a", value=b"x" * 64
                ).with_hash(),
            )
        total += batch
        max_depth = max(max_depth, len(peer.pending_keys))
        await asyncio.sleep(max(0.0, (total / updates_per_sec) - (loop.time() - t0)))
    churn_elapsed = loop.time() - t0

    # convergence: b holds the same (version, hash) for every key as a
    tc0 = loop.time()
    db_a = stores["a"].dbs["0"]
    while True:
        db_b = stores["b"].dbs["0"]
        if all(
            (vb := db_b.kv.get(k)) is not None
            and (vb.version, vb.hash) == (va.version, va.hash)
            for k, va in db_a.kv.items()
        ):
            break
        if loop.time() - tc0 > 30:
            raise TimeoutError("never converged")
        await asyncio.sleep(0.005)
    converge_ms = (loop.time() - tc0) * 1e3

    ca = counters["a"]
    out = {
        "updates_pushed": total,
        "updates_per_sec": round(total / churn_elapsed, 1),
        "floods_sent": ca.get("kvstore.floods_sent"),
        "keys_coalesced": ca.get("kvstore.flood_keys_coalesced"),
        "rate_limited_waits": ca.get("kvstore.floods_rate_limited"),
        "backpressure_drops": ca.get("kvstore.flood_backpressure_drops"),
        "max_pending_depth": max_depth,
        "pending_cap": stores["a"].config.node.kvstore.flood_pending_max_keys,
        "converge_after_churn_ms": round(converge_ms, 1),
    }
    for s in stores.values():
        await s.stop()
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--updates-per-sec", type=int, default=1000)
    ap.add_argument("--keys", type=int, default=100)
    ap.add_argument("--seconds", type=float, default=5.0)
    args = ap.parse_args()

    t0 = time.perf_counter()
    detail = asyncio.new_event_loop().run_until_complete(
        churn(args.updates_per_sec, args.keys, args.seconds)
    )
    detail["wall_s"] = round(time.perf_counter() - t0, 2)
    print(
        json.dumps(
            {
                "metric": "kvstore_flood_churn_converge_ms",
                "value": detail["converge_after_churn_ms"],
                "unit": "ms",
                "vs_baseline": None,
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
