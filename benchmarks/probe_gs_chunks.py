"""Probe: Gauss-Seidel chunked sweeps + gather form for the v4 kernel.

A Jacobi sweep needs ~26 iterations (weighted hop depth). Chunked
Gauss-Seidel relaxes row-chunks sequentially within a sweep, each chunk
seeing the chunks before it — alternating sweep direction halves the
count again. Same gathered rows per sweep, fewer sweeps. Measures
sweeps-to-fixpoint and wall time per (chunks, direction) config, plus
the d-loop gather form inside chunks.
"""

from __future__ import annotations

import functools
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from openr_tpu.decision.spf_backend import TpuSpfSolver
from openr_tpu.utils.topogen import erdos_renyi_lsdb

N = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
INF = np.int32(1 << 30)

print(f"# device: {jax.devices()[0]}")
ls, ps, csr = erdos_renyi_lsdb(N, avg_degree=20, seed=0, max_metric=64)
tpu = TpuSpfSolver(native_rib="off")
dev = tpu._device_arrays(csr, "split")
vp = dev["base_nbr"].shape[0]
W = dev["base_wgt"].shape[1]
b = 32
rng = np.random.default_rng(1)
roots_h = rng.integers(0, N, size=b).astype(np.int32)
roots = jnp.asarray(roots_h)

base_nbr, base_wgt = dev["base_nbr"], dev["base_wgt"]
ov_ids, ov_nbr, ov_wgt = dev["ov_ids"], dev["ov_nbr"], dev["ov_wgt"]


def relax_block(dist, nbr, wgt):
    g = dist[nbr]
    return jnp.where(
        g < INF, jnp.minimum(g + wgt[:, :, None], INF), INF
    ).min(axis=1)


def relax_block_dloop(dist, nbr, wgt):
    acc = jnp.full((nbr.shape[0], dist.shape[1]), INF, jnp.int32)
    for d in range(nbr.shape[1]):
        g = dist[nbr[:, d]]
        cand = jnp.where(g < INF, jnp.minimum(g + wgt[:, d][:, None], INF), INF)
        acc = jnp.minimum(acc, cand)
    return acc


@functools.partial(jax.jit, static_argnames=("chunks", "alternate", "dloop"))
def solve_gs(roots, chunks, alternate, dloop):
    dist = jnp.full((vp, b), INF, jnp.int32)
    dist = dist.at[roots, jnp.arange(b)].set(0)
    csz = vp // chunks
    rb = relax_block_dloop if dloop else relax_block

    def sweep(state):
        dist, it, _ = state
        before = dist

        def chunk_body(c, dist):
            idx = jax.lax.cond(
                alternate & (it % 2 == 1),
                lambda: (chunks - 1 - c) * csz,
                lambda: c * csz,
            )
            nbr = jax.lax.dynamic_slice(base_nbr, (idx, 0), (csz, W))
            wgt = jax.lax.dynamic_slice(base_wgt, (idx, 0), (csz, W))
            new = rb(dist, nbr, wgt)
            cur = jax.lax.dynamic_slice(dist, (idx, 0), (csz, b))
            return jax.lax.dynamic_update_slice(
                dist, jnp.minimum(new, cur), (idx, 0)
            )

        dist = jax.lax.fori_loop(0, chunks, chunk_body, dist)
        ov_new = relax_block(dist, ov_nbr, ov_wgt)
        dist = dist.at[ov_ids].min(ov_new)
        return dist, it + 1, jnp.any(dist < before)

    def cond(state):
        _, it, changed = state
        return changed & (it < 200)

    dist, sweeps, _ = jax.lax.while_loop(
        cond, sweep, (dist, jnp.int32(0), jnp.bool_(True))
    )
    return dist, sweeps


ref = None
for chunks, alternate, dloop in [
    (1, False, False),
    (2, True, False),
    (4, False, False),
    (4, True, False),
    (8, True, False),
    (16, True, False),
    (4, True, True),
    (8, True, True),
]:
    try:
        out, sw = solve_gs(roots, chunks, alternate, dloop)
        out.block_until_ready()
        ts = []
        for _ in range(4):
            t0 = time.perf_counter()
            out, sw = solve_gs(roots, chunks, alternate, dloop)
            s = int(jnp.asarray(sw))
            ts.append((time.perf_counter() - t0) * 1e3)
        ts.sort()
        o = np.asarray(out[:, 0])
        if ref is None:
            ref = o
        okay = "ok" if (o == ref).all() else "MISMATCH"
        print(f"  chunks={chunks:3d} alt={int(alternate)} dloop={int(dloop)}"
              f"  sweeps={s:3d}  p50 {ts[len(ts)//2]:8.2f} ms  {okay}")
    except Exception as e:  # noqa: BLE001
        print(f"  chunks={chunks} alt={alternate} dloop={dloop} FAIL "
              f"{str(e).splitlines()[0][:120]}")
