"""Decompose the headline solve's 1047 ms on the real chip.

Separates: pure v3 kernel time (scalar materialization), dense-sweep
count vs tail behavior, first_hop_matrix dispatch, host transfer of the
[vp, B] distance matrix, and RIB assembly. Run on the TPU.
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from openr_tpu.decision.spf_backend import TpuSpfSolver
from openr_tpu.ops.spf import first_hop_matrix
from openr_tpu.ops.spf_split import batched_sssp_split
from openr_tpu.utils.topogen import erdos_renyi_lsdb

N = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000

print(f"# device: {jax.devices()[0]}")
ls, ps, csr = erdos_renyi_lsdb(N, avg_degree=20, seed=0, max_metric=64)
tpu = TpuSpfSolver(native_rib="off")
dev = tpu._device_arrays(csr, "split")
vp = dev["base_nbr"].shape[0]
print(f"# vp={vp} W={dev['base_wgt'].shape[1]} Go={dev['ov_nbr'].shape[0]} "
      f"Wo={dev['ov_nbr'].shape[1]} Wout={dev['out_nbr'].shape[1]}")

my_id = csr.name_to_id["node-0"]
nbr_ids = sorted(d for (s, d) in csr.adj_details if s == my_id)
b = 32
roots_h = np.full(b, my_id, dtype=np.int32)
roots_h[1 : 1 + len(nbr_ids)] = nbr_ids[: b - 1]
roots = jnp.asarray(roots_h)


def timeit(label, fn, n=5):
    fn()  # warm
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append((time.perf_counter() - t0) * 1e3)
    ts.sort()
    print(f"  {label:45s} p50 {ts[len(ts)//2]:9.2f} ms  (min {ts[0]:.2f})")
    return ts[len(ts) // 2]


def solve():
    return batched_sssp_split(
        dev["base_nbr"], dev["base_wgt"], dev["ov_ids"], dev["ov_nbr"],
        dev["ov_wgt"], dev["out_nbr"], dev["over"], roots,
        has_overloads=False,
    )


# 1. pure kernel, scalar materialization
timeit("v3 solve B=32 (scalar drain)",
       lambda: float(jnp.asarray(solve()[0, 0])))

# 2. kernel + full host transfer of [vp, 32] i32
t_all = timeit("v3 solve B=32 + np.asarray full dist",
               lambda: np.asarray(solve()))

# 3. transfer alone (solve cached? no - rerun but transfer separately)
d = solve()
d.block_until_ready()
timeit("np.asarray([vp,32] i32) transfer only", lambda: np.asarray(d))
timeit("device_get via jax.device_get", lambda: jax.device_get(d))

# 4. first_hop_matrix dispatch on top
nbr_ids_p = np.full(b - 1, vp - 1, dtype=np.int32)
nbr_ids_p[: len(nbr_ids)] = nbr_ids[: b - 1]
nbr_metric = np.full(b - 1, 1, dtype=np.int32)
nbr_over = np.zeros(b - 1, dtype=bool)
fh_args = (jnp.asarray(nbr_metric), jnp.asarray(nbr_ids_p),
           jnp.asarray(nbr_over))
timeit("first_hop_matrix (on cached dist) + asarray",
       lambda: np.asarray(first_hop_matrix(d, *fh_args)))

# 5. sweep-count diagnostics: dense-only variants via tail knobs
for thr in (0, 1024, 8192, 32768):
    def run(thr=thr):
        out = batched_sssp_split(
            dev["base_nbr"], dev["base_wgt"], dev["ov_ids"], dev["ov_nbr"],
            dev["ov_wgt"], dev["out_nbr"], dev["over"], roots,
            has_overloads=False, tail_threshold=thr,
            tail_cap=max(8192, thr * 2), tail_rounds_cap=64,
        )
        return float(jnp.asarray(out[0, 0]))
    timeit(f"v3 solve tail_threshold={thr}", run, n=3)

# 6. per-sweep cost: K extra dense sweeps via a fori_loop probe
import functools


@functools.partial(jax.jit, static_argnames=("k",))
def dense_k(dist0, k):
    def sweep(_, dist):
        g = dist[dev["base_nbr"]]
        cand = jnp.where(
            g < np.int32(1 << 30),
            jnp.minimum(g + dev["base_wgt"][:, :, None], np.int32(1 << 30)),
            np.int32(1 << 30),
        )
        return jnp.minimum(cand.min(axis=1), dist)
    return jax.lax.fori_loop(0, k, sweep, dist0)


dist0 = jnp.full((vp, b), np.int32(1 << 30), jnp.int32)
dist0 = dist0.at[roots, jnp.arange(b)].set(0)
t1 = timeit("dense sweeps k=1", lambda: float(jnp.asarray(
    dense_k(dist0, 1)[0, 0])), n=3)
t13 = timeit("dense sweeps k=13", lambda: float(jnp.asarray(
    dense_k(dist0, 13)[0, 0])), n=3)
per = (t13 - t1) / 12
rows = vp * dev["base_wgt"].shape[1]
print(f"  -> per-sweep {per:.2f} ms, {rows/1e6:.2f} M rows/sweep, "
      f"{rows/per/1e6:.3f} G rows/s")
