"""Grid-search gather formulations for the SPF relax sweep (v5e).

The relax needs g[v,d,b] = dist[nbr[v,d], b] at VP*D rows/sweep. XLA's
gather measured ~0.1-0.35 Grows/s; this probe searches formulations for
a faster one. All probes K-iterate in-jit with data deps (tunnel ~85ms).
"""

from __future__ import annotations

import gc
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import functools

import jax
import jax.numpy as jnp
import numpy as np

rng = np.random.default_rng(0)
K = 12
VP = 100352  # 100k padded to multiple of 512 (not pow2 — 23% smaller)
D = 64
B = 32


def _leaf(out):
    return float(jax.tree_util.tree_leaves(out)[0].reshape(-1)[0])


def timed(fn, *args, n=4):
    out = fn(*args)
    jax.block_until_ready(out)
    _leaf(out)
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        _leaf(out)
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return times[len(times) // 2]


def bench(name, make_body, init, rows):
    try:
        @functools.partial(jax.jit, static_argnames=("k",))
        def run(init, k):
            return jax.lax.fori_loop(0, k, lambda i, c: make_body(c), init)

        t1 = timed(lambda a: run(a, 1), init)
        tk = timed(lambda a: run(a, K), init)
        per = (tk - t1) / (K - 1)
        rate = rows / (per / 1e3) / 1e9 if per > 0.005 else float("inf")
        print(f"  {name:40s} per-sweep {per:8.2f} ms   {rate:6.3f} Grows/s")
    except Exception as e:  # noqa: BLE001
        lines = [l for l in str(e).splitlines() if l.strip()] or [repr(e)]
        print(f"  {name:40s} FAIL {lines[0][:140]}")
    finally:
        gc.collect()


print(f"# device: {jax.devices()[0]}  VP={VP} D={D} B={B}")

nbr_h = rng.integers(0, VP, size=(VP, D), dtype=np.int32)
wgt_h = rng.integers(1, 64, size=(VP, D), dtype=np.int32)
dist_h = rng.integers(0, 1 << 20, size=(VP, B), dtype=np.int32)
nbr = jnp.asarray(nbr_h)
wgt = jnp.asarray(wgt_h)
INF = np.int32(1 << 30)
ROWS = VP * D


def dep(new, dist):
    """Cheap data dep: keep iterating on new dist."""
    return jnp.minimum(new, dist)


# ---- A: current form: 2D-idx gather [VP, D] -> [VP, D, B] ----
def body_a(c):
    dist, = c
    g = dist[nbr]  # [VP, D, B]
    cand = jnp.minimum(g + wgt[:, :, None], INF)
    return (dep(cand.min(axis=1), dist),)


bench("A  2D-idx gather", body_a, (jnp.asarray(dist_h),), ROWS)


# ---- B: flat-idx gather ----
nbr_flat = jnp.asarray(nbr_h.reshape(-1))


def body_b(c):
    dist, = c
    g = dist[nbr_flat].reshape(VP, D, B)
    cand = jnp.minimum(g + wgt[:, :, None], INF)
    return (dep(cand.min(axis=1), dist),)


bench("B  flat-idx gather", body_b, (jnp.asarray(dist_h),), ROWS)


# ---- C: d-loop of 64 column gathers ----
def body_c(c):
    dist, = c
    acc = dist
    for d in range(D):
        g = dist[nbr[:, d]]  # [VP, B]
        acc = jnp.minimum(acc, g + wgt[:, d][:, None])
    return (acc,)


bench("C  d-loop 64 gathers", body_c, (jnp.asarray(dist_h),), ROWS)


# ---- D: chunked rows (8 chunks) ----
CH = 8


def body_d(c):
    dist, = c
    outs = []
    for i in range(CH):
        sl = slice(i * VP // CH, (i + 1) * VP // CH)
        g = dist[nbr[sl]]  # [VP/CH, D, B]
        cand = jnp.minimum(g + wgt[sl][:, :, None], INF)
        outs.append(cand.min(axis=1))
    return (dep(jnp.concatenate(outs, axis=0), dist),)


bench("D  8-chunk gather", body_d, (jnp.asarray(dist_h),), ROWS)


# ---- E: transposed table, lane gather ----
distT_h = np.ascontiguousarray(dist_h.T)  # [B, VP]


def body_e(c):
    distT, = c
    g = jnp.take(distT, nbr_flat, axis=1)  # [B, VP*D]
    g = g.reshape(B, VP, D)
    cand = jnp.minimum(g + wgt.T[None, :, :].transpose(0, 2, 1)[0][None], INF) if False else jnp.minimum(g + wgt[None, :, :], INF)
    new = cand.min(axis=2)  # [B, VP]
    return (jnp.minimum(new, distT),)


bench("E  lane-gather (T)", body_e, (jnp.asarray(distT_h),), ROWS)


# ---- F: i16 distances ----
dist16_h = (dist_h & 0x7FFF).astype(np.int16)


def body_f(c):
    dist, = c
    g = dist[nbr]
    cand = jnp.minimum(
        g.astype(jnp.int32) + wgt[:, :, None], np.int32(0x7FFF)
    ).astype(jnp.int16)
    return (dep(cand.min(axis=1), dist),)


bench("F  i16 gather", body_f, (jnp.asarray(dist16_h),), ROWS)


# ---- G: one-hot int8 MXU per src-block (128-wide), limb-split ----
# dist [VP, B] viewed as [NBLK, 128, B]; static one-hot per (dst-slot,
# src-block) is huge; instead simulate cost with random one-hots:
# out = sum_k onehot_k @ dist_blk_k via dot_general batched matmul.
NBLK = VP // 128
SLOTS_PER_BLK = (VP * D) // NBLK  # 8.4M slots spread over 784 blocks ~ 8192


def body_g(c):
    dist, oh = c
    # dist [NBLK, 128, B] ; oh [NBLK, SLOTS, 128] int8 -> batched matmul
    d3 = dist.reshape(NBLK, 128, B)
    lo = (d3 & 0x7FFF).astype(jnp.bfloat16)
    hi = (d3 >> 15).astype(jnp.bfloat16)
    glo = jax.lax.dot_general(
        oh, lo, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    ghi = jax.lax.dot_general(
        oh, hi, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    g = (ghi.astype(jnp.int32) << 15) + glo.astype(jnp.int32)
    new = g.reshape(NBLK, SLOTS_PER_BLK, B).min(axis=1)  # fake reduce
    d_new = jnp.broadcast_to(new[:, None, :], (NBLK, 128, B)).reshape(VP, B)
    return (jnp.minimum(dist, d_new), oh)


oh_h = np.zeros((NBLK, SLOTS_PER_BLK, 128), dtype=np.int8)
oh_h[:, :, 0] = 1
bench("G  onehot bf16 MXU (batched)", body_g,
      (jnp.asarray(dist_h), jnp.asarray(oh_h)), ROWS)


# ---- H: sort-based relax: src-major cand + sort by dst + seg-scan ----
# static src-major edge list: dst ids per (src-major) slot
dst_of_slot_h = rng.integers(0, VP, size=(2 * 1024 * 1024,), dtype=np.int32)
dst_sorted_h = np.sort(dst_of_slot_h)
E2 = dst_of_slot_h.shape[0]


def body_h(c):
    dist, = c
    # cand gen: free (use dist col 0 + const); sort (dst, cand) pairs
    cand = dist[: E2 // B].reshape(-1)[:E2] + 1  # fake, elementwise
    key = jnp.asarray(dst_sorted_h)  # already sorted: best case
    ks, vs = jax.lax.sort([key, cand], num_keys=1)
    # segmented min via associative scan on runs? approximate with sort
    # by (dst, val): min is first of each run; emulate extraction cost:
    first = jnp.concatenate(
        [jnp.ones((1,), bool), ks[1:] != ks[:-1]]
    )
    upd = jnp.where(first, vs, INF)
    new = jax.ops.segment_min(
        upd, ks, num_segments=VP, indices_are_sorted=True
    )
    return (jnp.minimum(dist, new[:, None]),)


bench("H  sort+segmin (E=2.1M, B=1)", body_h, (jnp.asarray(dist_h),), E2)
