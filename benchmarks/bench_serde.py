"""Serde micro-bench: encode/decode ns per Publication, both codecs.

Measures the two wire codecs from openr_tpu.types.serde on a
representative KvStore flood Publication (one adjacency database + two
prefix databases as Value payloads — the shape every link-flap flood
carries): canonical JSON (`to_wire`/`from_wire`, the legacy framing)
vs compact binary (`to_wire_bin`/`from_wire_bin`, docs/Wire.md), plus
the wire sizes. The flood path encodes ONCE per publication
(serialize-once fan-out) — this bench is the per-encode cost that
amortization multiplies.

Run: python benchmarks/bench_serde.py [--iters 2000] [--adjacencies 8]
Prints one JSON line (same contract as bench.py).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def build_publication(n_adj: int):
    from openr_tpu.types.kvstore import Publication, Value
    from openr_tpu.types.network import IpPrefix
    from openr_tpu.types.serde import to_wire
    from openr_tpu.types.topology import (
        Adjacency,
        AdjacencyDatabase,
        PrefixDatabase,
        PrefixEntry,
    )

    adj = AdjacencyDatabase(
        this_node_name="node-17",
        adjacencies=tuple(
            Adjacency(
                other_node_name=f"node-{i}",
                if_name=f"if-node-17-node-{i}",
                other_if_name=f"if-node-{i}-node-17",
                metric=10 + i,
                adj_label=50000 + i,
            )
            for i in range(n_adj)
        ),
        node_label=117,
        area="0",
    )
    key_vals = {
        "adj:node-17": Value(
            version=7, originator_id="node-17", value=to_wire(adj)
        ).with_hash()
    }
    for i in range(2):
        pdb = PrefixDatabase(
            this_node_name="node-17",
            prefix_entries=(
                PrefixEntry(prefix=IpPrefix(prefix=f"10.0.{i}.1/32")),
            ),
            area="0",
        )
        key_vals[f"prefix:node-17:0:10.0.{i}.1/32"] = Value(
            version=3, originator_id="node-17", value=to_wire(pdb)
        ).with_hash()
    return Publication(
        area="0", key_vals=key_vals, node_ids=["node-17", "node-3"]
    )


def _time_ns(fn, iters: int) -> float:
    # warmup: build codec closures / jit nothing — pure python here
    for _ in range(max(10, iters // 20)):
        fn()
    t0 = time.perf_counter_ns()
    for _ in range(iters):
        fn()
    return (time.perf_counter_ns() - t0) / iters


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=2000)
    ap.add_argument("--adjacencies", type=int, default=8)
    args = ap.parse_args()

    from openr_tpu.types.kvstore import Publication
    from openr_tpu.types.serde import (
        from_wire,
        from_wire_bin,
        to_wire,
        to_wire_bin,
    )

    pub = build_publication(args.adjacencies)
    wire_json = to_wire(pub)
    wire_bin = to_wire_bin(pub)
    assert from_wire_bin(wire_bin, Publication) == from_wire(
        wire_json, Publication
    )

    detail = {
        "iters": args.iters,
        "adjacencies": args.adjacencies,
        "json_bytes": len(wire_json),
        "bin_bytes": len(wire_bin),
        "size_ratio": round(len(wire_json) / len(wire_bin), 2),
        "json_encode_ns": round(_time_ns(lambda: to_wire(pub), args.iters)),
        "json_decode_ns": round(
            _time_ns(lambda: from_wire(wire_json, Publication), args.iters)
        ),
        "bin_encode_ns": round(
            _time_ns(lambda: to_wire_bin(pub), args.iters)
        ),
        "bin_decode_ns": round(
            _time_ns(lambda: from_wire_bin(wire_bin, Publication), args.iters)
        ),
    }
    detail["encode_speedup"] = round(
        detail["json_encode_ns"] / max(detail["bin_encode_ns"], 1), 2
    )
    detail["decode_speedup"] = round(
        detail["json_decode_ns"] / max(detail["bin_decode_ns"], 1), 2
    )
    print(
        json.dumps(
            {
                "metric": "serde_bin_encode_ns",
                "value": detail["bin_encode_ns"],
                "unit": "ns",
                "vs_baseline": None,
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
