"""Step-mode churn rebuild profile (BASELINE config 5 protocol note).

The live harness (bench_churn.py) measures flap→RIB latency through the
real event loop — which on a 1-core bench host makes the RECOMPUTE
numbers move ~2x with host weather, because the flap generator, the
drainer and the solver thread all contend for the same core (round-3
verdict). This harness isolates the recompute pipeline: flaps are
pre-generated, then injected in fixed-size batches and the rebuild body
(decode → apply+snapshot → compute+diff) is driven SYNCHRONOUSLY and
timed per stage — no event loop, no generator contention, no timer
jitter. This is the protocol for the config-5 "steady-state recompute"
row; the live harness remains the protocol for flap→RIB latency.

Usage: python benchmarks/profile_churn_rebuild.py [--nodes 1280]
         [--flaps-per-cycle 40] [--cycles 50] [--profile]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1280)
    ap.add_argument("--flaps-per-cycle", type=int, default=40)
    ap.add_argument("--cycles", type=int, default=50)
    ap.add_argument(
        "--profile", action="store_true",
        help="cProfile the compute+diff stage and print the top 25",
    )
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import dataclasses

    from benchmarks.bench_churn import build_decision
    from openr_tpu.utils import topogen

    k = max(4, int(round((args.nodes * 4 / 5) ** 0.5 / 2)) * 2)
    adj_dbs, prefix_dbs = topogen.fat_tree(k, metric=10)
    dec, pubs, routes, pub_for = build_decision(adj_dbs, prefix_dbs)

    # first full rebuild (compile + cold caches) outside the timing
    dec._drain_pending()
    states = dec._snapshot_states()
    dec.rib, _ = dec._compute_and_diff(states)

    rng = np.random.default_rng(7)
    adj_dbs = list(adj_dbs)
    versions = {db.this_node_name: 1 for db in adj_dbs}
    warm_cycles = 3
    total = args.flaps_per_cycle * (args.cycles + warm_cycles)
    pregen = []
    for _ in range(total):
        i = int(rng.integers(0, len(adj_dbs)))
        db = adj_dbs[i]
        j = int(rng.integers(0, len(db.adjacencies)))
        new_adjs = list(db.adjacencies)
        a = new_adjs[j]
        new_adjs[j] = dataclasses.replace(
            a, metric=int(rng.integers(1, 64))
        )
        db = dataclasses.replace(db, adjacencies=tuple(new_adjs))
        adj_dbs[i] = db
        versions[db.this_node_name] += 1
        pregen.append(pub_for(db, version=versions[db.this_node_name]))

    stages: dict[str, list[float]] = {
        "decode": [], "apply_snapshot": [], "compute_diff": [],
        "total": [],
    }
    prof = None
    if args.profile:
        import cProfile

        prof = cProfile.Profile()
    # warm cycles so caches (entry/class dicts) reach steady state
    n = 0
    for cyc in range(args.cycles + warm_cycles):
        for _ in range(args.flaps_per_cycle):
            if n >= total:
                break
            dec.process_publication(pregen[n])
            n += 1
        t0 = time.perf_counter()
        batch = dict(dec._pending_kvs)
        decoded = dec._decode_batch(batch)
        t1 = time.perf_counter()
        dec._drain_pending(decoded)
        states = dec._snapshot_states()
        t2 = time.perf_counter()
        if prof is not None and cyc >= warm_cycles:
            prof.enable()
        new_rib, update = dec._compute_and_diff(states)
        if prof is not None and cyc >= warm_cycles:
            prof.disable()
        t3 = time.perf_counter()
        dec.rib = new_rib
        if cyc < warm_cycles:
            continue
        stages["decode"].append((t1 - t0) * 1e3)
        stages["apply_snapshot"].append((t2 - t1) * 1e3)
        stages["compute_diff"].append((t3 - t2) * 1e3)
        stages["total"].append((t3 - t0) * 1e3)

    out = {
        "metric": "churn_stepmode_recompute_p50_ms",
        "value": round(float(np.percentile(stages["total"], 50)), 2),
        "unit": "ms",
        "vs_baseline": None,
        "detail": {
            "config": 5,
            "protocol": "step-mode (synchronous rebuild; no event loop)",
            "nodes": len(adj_dbs),
            "flaps_per_cycle": args.flaps_per_cycle,
            "cycles": args.cycles,
            "p99_ms": round(float(np.percentile(stages["total"], 99)), 2),
            "stage_p50_ms": {
                kk: round(float(np.percentile(v, 50)), 2)
                for kk, v in stages.items()
            },
            "decode_stats": dict(dec.decode_stats),
        },
    }
    print(json.dumps(out))
    if prof is not None:
        import pstats

        pstats.Stats(prof).sort_stats("cumulative").print_stats(25)


if __name__ == "__main__":
    main()
