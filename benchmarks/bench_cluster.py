"""Multi-process cluster scaling bench — real sockets, real crashes.

Every node is its own ``python -m openr_tpu`` interpreter (emulator/
procs.py): Spark discovery over real UDP, KvStore flooding over real
TCP with the negotiated binary codec, all observation over ctrl RPC.
Each rung of the curve runs a SEEDED kill-storm (hard SIGKILL + real
re-exec restarts) and one partition/heal round (socket-level drop
rules), then must pass the full cross-process invariant suite
(emulator/proc_invariants.py) — the numbers only count if the fleet
is provably coherent afterwards. Any failure message embeds the
ChaosPlan replay seed and a flight-recorder gather from every
surviving process.

Modes:
  --smoke   16-node fat-tree pod, one SIGKILL + restart, one
            partition/heal, invariants + zero-steady-compile counter
            assert over ctrl. CI lane; exit 0/1.
  --curve   sizes x topology families -> BENCH_CLUSTER.json with
            convergence_p50_ms and floods/sec per rung.

Run: python benchmarks/bench_cluster.py --smoke
     python benchmarks/bench_cluster.py --curve --sizes 8,16,32 \
         --families fat_tree_pod,wan_like --prefixes-total 100000

Prints one JSON document (bench.py contract: metric/value/unit/
vs_baseline/detail).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

#: (k, pods) per fat-tree rung — exact node counts only, so the curve's
#: x axis is honest: n = (k/2)^2 cores + pods*(k/2 agg + k/2 tor)
_FAT_TREE_RUNGS = {
    8: (4, 1),
    16: (4, 3),
    24: (8, 1),
    32: (8, 2),
    64: (8, 6),
}


def _family_links(family: str, n: int, seed: int):
    """Topology-family edges for an n-node rung, as LinkSpec list."""
    from openr_tpu.emulator.cluster import LinkSpec
    from openr_tpu.utils import topogen

    if family == "fat_tree_pod":
        if n not in _FAT_TREE_RUNGS:
            raise SystemExit(
                f"fat_tree_pod has no exact {n}-node shape; "
                f"pick from {sorted(_FAT_TREE_RUNGS)}"
            )
        k, pods = _FAT_TREE_RUNGS[n]
        adj, _ = topogen.fat_tree_pod(k=k, pods=pods)
    elif family == "wan_like":
        adj, _ = topogen.wan_like(n, seed=seed)
    elif family == "hub_and_spoke":
        hubs = max(2, n // 8)
        adj, _ = topogen.hub_and_spoke(hubs=hubs, spokes=n - hubs)
    else:
        raise SystemExit(f"unknown topology family {family!r}")
    return [LinkSpec(a=a, b=b) for a, b in topogen.edges_of(adj)]


async def _fleet_sum(cluster, key: str) -> float:
    agg = await cluster.fleet_counters(key)
    row = agg.get(key)
    return row["sum"] if row else 0.0


async def _fleet_p50(cluster, key: str) -> float | None:
    agg = await cluster.fleet_counters(key)
    row = agg.get(key)
    return round(row["p50"], 3) if row else None


async def _run_rung(
    family: str,
    n: int,
    *,
    seed: int,
    prefixes_per_node: int,
    workdir: str,
    storm_s: float,
    quiesce_s: float,
) -> dict:
    """One curve rung: spawn n processes, converge, seeded kill-storm +
    partition/heal, quiesce through the full invariant suite, report."""
    from openr_tpu.emulator import chaos, proc_invariants
    from openr_tpu.emulator.procs import ProcCluster

    links = _family_links(family, n, seed)
    cluster = ProcCluster(
        links, workdir, prefixes_per_node=prefixes_per_node
    )
    plan = chaos.ChaosPlan(seed=seed)
    replay = (
        f"bench_cluster --curve family={family} n={n} seed={seed} "
        f"({plan.replay_hint()})"
    )
    try:
        t0 = time.monotonic()
        await cluster.start()
        spawn_s = time.monotonic() - t0
        await cluster.wait_converged(timeout=60 + 3 * n)
        cold_converge_s = time.monotonic() - t0
        await proc_invariants.mark_fleet_warm(cluster)

        floods0 = await _fleet_sum(cluster, "kvstore.floods_sent")
        compiles0 = await _fleet_sum(cluster, "jax.compiles.total")

        # seeded storm: flaps + >=1 hard kill (with scheduled restart)
        # + >=1 partition/heal, all over real process boundaries
        events = cluster.make_storm(
            plan,
            duration_s=storm_s,
            n_flaps=max(2, n // 8),
            n_crashes=max(1, n // 16),
            n_partitions=1,
            heal_after_s=min(2.0, storm_s / 3),
        )
        t1 = time.monotonic()
        await chaos.run_schedule(cluster, plan, events)
        await proc_invariants.wait_quiescent(
            cluster, timeout_s=quiesce_s + 2 * n, context=replay
        )
        churn_elapsed = time.monotonic() - t1

        floods1 = await _fleet_sum(cluster, "kvstore.floods_sent")
        compiles1 = await _fleet_sum(cluster, "jax.compiles.total")
        if compiles1 != compiles0:
            raise AssertionError(
                f"steady-state churn compiled: jax.compiles.total "
                f"{compiles0} -> {compiles1} (replay: {replay})"
            )
        reconnects = await _fleet_sum(cluster, "kvstore.peer_reconnects")
        return {
            "family": family,
            "nodes": n,
            "links": len(links),
            "processes": n,
            "prefixes_per_node": prefixes_per_node,
            "prefixes_total": prefixes_per_node * n,
            "spawn_s": round(spawn_s, 2),
            "cold_converge_s": round(cold_converge_s, 2),
            "storm_events": len(events),
            "storm_kills": sum(1 for e in events if e.kind == "crash"),
            "storm_partitions": sum(
                1 for e in events if e.kind == "partition"
            ),
            "churn_elapsed_s": round(churn_elapsed, 2),
            "floods_sent": int(floods1 - floods0),
            "floods_per_sec": round(
                (floods1 - floods0) / max(churn_elapsed, 1e-9), 1
            ),
            "convergence_p50_ms": await _fleet_p50(
                cluster, "monitor.convergence_ms.p50"
            ),
            "convergence_p99_ms": await _fleet_p50(
                cluster, "monitor.convergence_ms.p99"
            ),
            "peer_reconnects": int(reconnects),
            "steady_compiles": int(compiles1 - compiles0),
            "invariants": "ok",
            "replay": replay,
        }
    finally:
        await cluster.stop()


async def run_curve(args) -> dict:
    sizes = [int(s) for s in args.sizes.split(",")]
    families = args.families.split(",")
    base = args.workdir or tempfile.mkdtemp(prefix="openr-cluster-")
    out: dict[str, dict] = {}
    for family in families:
        out[family] = {}
        for n in sizes:
            wd = os.path.join(base, f"{family}-{n}")
            print(
                f"== {family} n={n} "
                f"({args.prefixes_total // n} prefixes/node)",
                file=sys.stderr,
            )
            rung = await _run_rung(
                family,
                n,
                seed=args.seed,
                prefixes_per_node=args.prefixes_total // n,
                workdir=wd,
                storm_s=args.storm_s,
                quiesce_s=args.quiesce_s,
            )
            out[family][str(n)] = rung
            print(
                f"   converge p50 {rung['convergence_p50_ms']} ms, "
                f"{rung['floods_per_sec']} floods/s, "
                f"{rung['storm_kills']} kills, invariants ok",
                file=sys.stderr,
            )
            if not args.keep:
                shutil.rmtree(wd, ignore_errors=True)
    top_family = families[0]
    top = out[top_family][str(max(sizes))]
    return {
        "metric": "cluster_convergence_p50_ms",
        "value": top["convergence_p50_ms"],
        "unit": "ms",
        "vs_baseline": None,
        "detail": {
            "harness": "multi-process (one interpreter per node, real "
            "UDP/TCP/ctrl sockets, SIGKILL crashes, re-exec restarts)",
            "host_cores": os.cpu_count(),
            "sizes": sizes,
            "families": out,
            "seed": args.seed,
            "invariants": "ok",
            "note": "per-rung seeded kill-storm + partition/heal, "
            "then the full cross-process invariant suite (kvstore "
            "digest identity, FIB/oracle parity, no stuck state, "
            "counter identities, queue bounds, work ratios) before "
            "any number is recorded; rung sizes are bounded by the "
            f"host's {os.cpu_count()} core(s) — every added process "
            "multiplies scheduler oversubscription, not network load",
        },
    }


async def run_smoke(args) -> dict:
    """CI lane: 16-node fat-tree pod over real sockets; one SIGKILL +
    restart, one partition/heal, full invariants, zero-steady-compile.
    Fails loudly with the flight-dump path on any violation."""
    from openr_tpu.emulator import proc_invariants
    from openr_tpu.emulator.procs import ProcCluster

    base = args.workdir or tempfile.mkdtemp(prefix="openr-cluster-smoke-")
    links = _family_links("fat_tree_pod", 16, args.seed)
    cluster = ProcCluster(
        links, base, prefixes_per_node=args.smoke_prefixes
    )
    victim = sorted(cluster.nodes)[-1]  # a ToR, not a core
    replay = f"bench_cluster --smoke seed={args.seed}"
    try:
        t0 = time.monotonic()
        await cluster.start()
        await cluster.wait_converged(timeout=90)
        cold = time.monotonic() - t0
        await proc_invariants.mark_fleet_warm(cluster)
        compiles0 = await _fleet_sum(cluster, "jax.compiles.total")

        # 1. hard crash + real restart
        await cluster.crash_node(victim)
        await asyncio.sleep(2.0)
        await cluster.restart_node(victim)
        await proc_invariants.wait_quiescent(
            cluster, timeout_s=90, context=f"{replay} kill={victim}"
        )

        # 2. partition core+pod0 from the rest, heal
        names = sorted(cluster.nodes)
        cut = len(names) // 2
        await cluster.partition([names[:cut], names[cut:]])
        await asyncio.sleep(2.0)
        await cluster.heal_partition()
        await proc_invariants.wait_quiescent(
            cluster, timeout_s=90, context=f"{replay} partition"
        )

        compiles1 = await _fleet_sum(cluster, "jax.compiles.total")
        if compiles1 != compiles0:
            raise AssertionError(
                f"steady-state chaos compiled: jax.compiles.total "
                f"{compiles0} -> {compiles1} ({replay})"
            )
        floods = await _fleet_sum(cluster, "kvstore.floods_sent")
        return {
            "metric": "cluster_smoke",
            "value": 1.0,
            "unit": "pass",
            "vs_baseline": None,
            "detail": {
                "nodes": len(cluster.nodes),
                "links": len(links),
                "cold_converge_s": round(cold, 2),
                "sigkill_restart": victim,
                "partition_heal": "halves",
                "floods_sent": int(floods),
                "steady_compiles": int(compiles1 - compiles0),
                "invariants": "ok",
                "replay": replay,
            },
        }
    finally:
        await cluster.stop()
        if not args.keep:
            shutil.rmtree(base, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(prog="bench_cluster")
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--smoke", action="store_true")
    mode.add_argument("--curve", action="store_true")
    ap.add_argument("--sizes", default="8,16,32")
    ap.add_argument(
        "--families", default="fat_tree_pod,wan_like",
        help="comma list: fat_tree_pod | wan_like | hub_and_spoke",
    )
    ap.add_argument(
        "--prefixes-total", type=int, default=100_000,
        help="churn payload spread across the fleet (per-node share)",
    )
    ap.add_argument("--smoke-prefixes", type=int, default=8)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--storm-s", type=float, default=6.0)
    ap.add_argument("--quiesce-s", type=float, default=60.0)
    ap.add_argument("--workdir", default=None)
    ap.add_argument(
        "--keep", action="store_true",
        help="keep per-rung workdirs (configs + per-node logs)",
    )
    ap.add_argument("--out", default=None, help="also write JSON here")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        result = asyncio.run(
            run_smoke(args) if args.smoke else run_curve(args)
        )
    except AssertionError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        return 1
    doc = json.dumps(result, indent=2)
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
