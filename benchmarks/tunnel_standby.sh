#!/bin/bash
# Hot-standby tunnel detector, complementing tunnel_watch.sh's poller.
#
# A child process sits in jax backend init, which HANGS while the axon
# tunnel is down and completes within seconds once it recovers — so if
# the plugin's init retries its connection, detection latency is ~0
# instead of the poller's ~interval. The child exits immediately after
# ONE confirmed dispatch: holding an initialized backend would block
# every other client's init on the single chip (observed 2026-07-31:
# a probe hangs while another process holds the tunnel).
#
# Unknown plugin semantics guarded against: an init that began while
# the tunnel was down may never notice a recovery, so the hanging child
# is recycled every STANDBY_MAXWAIT seconds (default 240) — worst-case
# detection stays bounded and the polling watcher remains the backstop.
# If init completes but the first dispatch wedges, the same timeout
# reaps it.
#
# On a DOWN->UP transition, runs $ON_UP ONCE per transition (same latch
# contract as tunnel_watch.sh); the measurement commands inside it
# should set OPENR_BENCH_YIELDABLE=1 so the driver's own bench slot can
# take the chip over (bench.py lock protocol).
LOG=${1:-benchmarks/logs/tunnel_standby.log}
MAXWAIT=${STANDBY_MAXWAIT:-240}
mkdir -p "$(dirname "$LOG")"
was_up=0
while true; do
  t0=$(date +%s)
  # the probe REPORTS its own platform via exit code (3 = resolved to
  # the cpu fallback, not a live tunnel) — string-matching merged
  # stdout/stderr is unreliable when warnings trail the result line
  out=$(timeout -k 10 "$MAXWAIT" python -u -c "
import sys, time
t0 = time.time()
import jax
d = jax.devices()[0]
if d.platform == 'cpu':
    sys.exit(3)
import jax.numpy as jnp
import numpy as np
x = jnp.ones((128, 128))
y = np.asarray(x @ x)  # one real dispatch, host-materialized
print(f'{d.platform} {d} init+dispatch {time.time()-t0:.1f}s')
" 2>&1)
  rc=$?
  t1=$(date +%s)
  last=$(printf '%s' "$out" | tail -1)
  if [ "$rc" -eq 0 ]; then
    if [ "$was_up" -eq 0 ]; then
      echo "$(date -u +%H:%M:%S) UP-DETECTED after $((t1-t0))s in init-wait: $last" >> "$LOG"
      if [ -n "$ON_UP" ]; then
        echo "$(date -u +%H:%M:%S) standby ON_UP firing" >> "$LOG"
        bash -c "$ON_UP" >> "$LOG" 2>&1
        echo "$(date -u +%H:%M:%S) standby ON_UP done" >> "$LOG"
      fi
    fi
    was_up=1
    sleep 120  # still up; re-confirm occasionally without stacking clients
  elif [ "$rc" -eq 3 ]; then
    # jax fell back to the cpu backend instead of hanging — the fast
    # exit would otherwise busy-spin a jax import every ~10 s; poll at
    # the watcher's cadence instead
    echo "$(date -u +%H:%M:%S) cpu-fallback cycle ($((t1-t0))s) — not a live tunnel" >> "$LOG"
    was_up=0
    sleep 180
  else
    # rc 124/137 = still down (init never returned); anything else is
    # an import/device error worth reading in the tail
    echo "$(date -u +%H:%M:%S) still-down cycle (rc=$rc, $((t1-t0))s) $last" >> "$LOG"
    was_up=0
    sleep 5
  fi
done
