"""Batch-width scaling family for the v3 split kernel on the real chip.

The gather-bound relax is rows-bound, so widening B amortizes sweeps
over more sources at near-constant cost until the [VP, B] state and the
W per-column gathers saturate HBM. This probe measures the real curve
(B = 32..512 at 100k/2.2M) to anchor docs/scaling.md's all-sources and
v5e-4 numbers with hardware rows instead of the B=256 single point.
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import numpy as np

from openr_tpu.decision.spf_backend import TpuSpfSolver
from openr_tpu.utils.topogen import erdos_renyi_lsdb

N = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
ITERS = int(os.environ.get("BFAM_ITERS", "4"))
FAMILY = (32, 64, 128, 256, 512)

print(f"# device: {jax.devices()[0].device_kind}  N={N}", flush=True)
ls, ps, csr0 = erdos_renyi_lsdb(N, avg_degree=22, seed=0, max_metric=64)
tpu = TpuSpfSolver(native_rib="off")
csr = ls.to_csr()

for b in FAMILY:
    roots = np.arange(b, dtype=np.int32) % csr.num_nodes
    try:
        dist = tpu._solve_dist(csr, roots)  # compile + warm
        float(np.asarray(dist[:, 0]).sum())
        times = []
        for _ in range(ITERS):
            t0 = time.perf_counter()
            dist = tpu._solve_dist(csr, roots)
            float(np.asarray(dist[:, 0]).sum())
            times.append((time.perf_counter() - t0) * 1e3)
        p50 = float(np.percentile(times, 50))
        print(
            f"  B={b:4d}  solve p50 {p50:8.1f} ms  (min {min(times):7.1f})"
            f"  {b / (p50 / 1e3):7.1f} sources/s",
            flush=True,
        )
    except Exception as e:  # OOM at the wide end is a result, not a crash
        print(f"  B={b:4d}  FAILED: {type(e).__name__}: {e}", flush=True)
        break
