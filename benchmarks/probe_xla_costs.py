"""Cost model probes for the SPF kernel redesign (v5e, real chip).

The axon tunnel costs ~85 ms per dispatch round-trip, so every probe
runs K in-jit iterations (lax.fori_loop with a data dependency between
iterations to defeat CSE/DCE) and reports (tK - t1) / (K - 1).
Arrays are freed between probes to stay inside HBM.
"""

from __future__ import annotations

import gc
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import functools

import jax
import jax.numpy as jnp
import numpy as np

rng = np.random.default_rng(0)
K = 16


def _leaf(out):
    leaves = jax.tree_util.tree_leaves(out)
    return float(jnp.asarray(leaves[0]).reshape(-1)[0])


def timed(fn, *args, n=5):
    out = fn(*args)
    jax.block_until_ready(out)
    _leaf(out)
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        _leaf(out)
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return times[len(times) // 2]


def bench(name, make_body, init, unit_count, unit="rows"):
    try:
        @functools.partial(jax.jit, static_argnames=("k",))
        def run(init, k):
            return jax.lax.fori_loop(0, k, lambda i, c: make_body(c), init)

        t1 = timed(lambda a: run(a, 1), init)
        tk = timed(lambda a: run(a, K), init)
        per = (tk - t1) / (K - 1)
        if per <= 0.005:
            print(f"  {name:46s} per-iter <0.01 ms (t1={t1:.1f} tK={tk:.1f})")
            return
        rate = unit_count / (per / 1e3) / 1e9
        print(f"  {name:46s} per-iter {per:8.2f} ms   {rate:7.3f} G{unit}/s")
    except Exception as e:  # noqa: BLE001
        lines = [l for l in str(e).splitlines() if l.strip()] or [repr(e)]
        print(f"  {name:46s} FAIL {lines[0][:120]}")
    finally:
        gc.collect()


print(f"# device: {jax.devices()[0]}  (K={K} in-jit iters, tunnel-corrected)")

VP = 131072
D = 64


def probe_gather_width(bw, m):
    tbl = jnp.asarray(rng.integers(0, 1 << 20, size=(VP, bw), dtype=np.int32))
    idx0 = jnp.asarray(rng.integers(0, VP, size=(m,), dtype=np.int32))
    acc0 = jnp.full((m, bw), np.int32(1 << 30), jnp.int32)

    def body(c):
        idx, acc = c
        g = tbl[idx]
        acc = jnp.minimum(acc, g)
        idx = (idx + acc[:, 0]) & (VP - 1)
        return (idx, acc)

    bench(f"gather [{VP}x{bw}] x {m/1e6:.1f}M rows", body, (idx0, acc0), m)


probe_gather_width(1, 1 << 23)
probe_gather_width(8, 1 << 23)
probe_gather_width(32, 1 << 22)
probe_gather_width(128, 1 << 20)


def probe_gather_rows(m):
    tbl = jnp.asarray(rng.integers(0, 1 << 20, size=(VP, 32), dtype=np.int32))
    idx0 = jnp.asarray(rng.integers(0, VP, size=(m,), dtype=np.int32))
    acc0 = jnp.full((m, 32), np.int32(1 << 30), jnp.int32)

    def body(c):
        idx, acc = c
        g = tbl[idx]
        acc = jnp.minimum(acc, g)
        idx = (idx + acc[:, 0]) & (VP - 1)
        return (idx, acc)

    bench(f"gather [{VP}x32] x {m/1e6:.2f}M rows", body, (idx0, acc0), m)


probe_gather_rows(1 << 18)
probe_gather_rows(1 << 20)


def probe_small_table():
    small = 1 << 14
    m = 1 << 20
    tbl = jnp.asarray(
        rng.integers(0, 1 << 20, size=(small, 32), dtype=np.int32)
    )
    idx0 = jnp.asarray(rng.integers(0, small, size=(m,), dtype=np.int32))
    acc0 = jnp.full((m, 32), np.int32(1 << 30), jnp.int32)

    def body(c):
        idx, acc = c
        g = tbl[idx]
        acc = jnp.minimum(acc, g)
        idx = (idx + acc[:, 0]) & (small - 1)
        return (idx, acc)

    bench(f"gather [{small}x32] x 1.0M rows", body, (idx0, acc0), m)


probe_small_table()


def probe_taa():
    dist0 = jnp.asarray(
        rng.integers(0, 1 << 20, size=(VP, 32), dtype=np.int32)
    )
    ptr0 = jnp.asarray(rng.integers(0, VP, size=(VP, 32), dtype=np.int32))

    def body(c):
        ptr, d = c
        g = jnp.take_along_axis(d, ptr, axis=0)
        d = jnp.minimum(d, g)
        ptr = (ptr + d) & (VP - 1)
        return (ptr, d)

    bench(f"take_along_axis [{VP}x32] 4.2M elem", body, (ptr0, dist0),
          VP * 32, unit="elems")


probe_taa()


def probe_seg(sorted_, width):
    E = 2 * 1024 * 1024
    if width == 1:
        vals0 = jnp.asarray(
            rng.integers(0, 1 << 20, size=(E,), dtype=np.int32)
        )
        accv = jnp.full((VP,), np.int32(1 << 30), jnp.int32)
    else:
        vals0 = jnp.asarray(
            rng.integers(0, 1 << 20, size=(E, width), dtype=np.int32)
        )
        accv = jnp.full((VP, width), np.int32(1 << 30), jnp.int32)
    ids = rng.integers(0, VP, size=(E,), dtype=np.int32)
    if sorted_:
        ids = np.sort(ids)
    seg = jnp.asarray(ids)

    def body(c):
        vals, acc = c
        r = jax.ops.segment_min(
            vals, seg, num_segments=VP, indices_are_sorted=sorted_
        )
        acc = jnp.minimum(acc, r)
        if width == 1:
            vals = vals + acc[0]
        else:
            vals = vals + acc[:1, :]
        return (vals, acc)

    tag = "sorted" if sorted_ else "unsort"
    bench(f"segment_min {tag} [2.1M x {width}]", body, (vals0, accv),
          E)


probe_seg(True, 32)
probe_seg(False, 32)
probe_seg(False, 1)
probe_seg(True, 1)


def probe_sort(m, kv):
    keys0 = jnp.asarray(rng.integers(0, 1 << 30, size=(m,), dtype=np.int32))
    if kv:
        pay0 = jnp.asarray(
            rng.integers(0, 1 << 30, size=(m,), dtype=np.int32)
        )

        def body(c):
            k, p, acc = c
            ks, ps = jax.lax.sort([k, p], num_keys=1)
            acc = jnp.minimum(acc, ks[0] + ps[0])
            return (k ^ acc, p, acc)

        bench(f"sort_kv {m/1e6:.1f}M i32", body,
              (keys0, pay0, jnp.int32(1 << 30)), m, unit="keys")
    else:
        def body(c):
            k, acc = c
            s = jnp.sort(k)
            acc = jnp.minimum(acc, s[0])
            return (k ^ acc, acc)

        bench(f"sort {m/1e6:.1f}M i32", body, (keys0, jnp.int32(1 << 30)),
              m, unit="keys")


probe_sort(1 << 20, False)
probe_sort(1 << 23, False)
probe_sort(1 << 21, True)


def probe_ew():
    a0 = jnp.asarray(rng.integers(0, 1 << 20, size=(VP, D), dtype=np.int32))
    b0 = jnp.asarray(rng.integers(0, 1 << 20, size=(VP, D), dtype=np.int32))

    def body(c):
        a, b = c
        return (jnp.minimum(a + 1, b), a)

    bench(f"elementwise min+add [{VP}x{D}] 8.4M", body, (a0, b0), VP * D,
          unit="elems")


probe_ew()
