"""Per-stage timing of TpuSpfSolver.solve's fused split path at 100k.

The live-chip decomposition (benchmarks/logs/decomp_tpu_0345.out) shows
pure kernel p50 206 ms but the headline solve p50 335 ms; this probe
splits the remaining ~130 ms between: host prep (to_csr, neighbor
metric scan), the fused dispatch + scalar drain, the packed-buffer
device→host transfer, and unpack_rib_buffer.
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from openr_tpu.common.constants import METRIC_MAX
from openr_tpu.decision.spf_backend import TpuSpfSolver
from openr_tpu.ops.spf import pad_batch
from openr_tpu.ops.spf_split import batched_sssp_split_rib, unpack_rib_buffer
from openr_tpu.utils.topogen import erdos_renyi_lsdb

N = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
ITERS = int(os.environ.get("STAGE_ITERS", "8"))

print(f"# device: {jax.devices()[0].device_kind}", flush=True)
ls, ps, csr = erdos_renyi_lsdb(N, avg_degree=22, seed=0, max_metric=64)
tpu = TpuSpfSolver(native_rib="off")

# warm everything once through the public entry
tpu.solve(ls, "node-0")


def p50(xs):
    return float(np.percentile(xs, 50))


rows: dict[str, list[float]] = {}


def rec(k, ms):
    rows.setdefault(k, []).append(ms)


for it in range(ITERS):
    t0 = time.perf_counter()
    csr = ls.to_csr()
    my_id = csr.name_to_id["node-0"]
    nbr_ids = sorted(d for (s, d) in csr.adj_details if s == my_id)
    n = len(nbr_ids)
    b = pad_batch(1 + n)
    nbr_metric_real = np.empty(n, dtype=np.int32)
    for i, d in enumerate(nbr_ids):
        nbr_metric_real[i] = min(
            min(det[1] for det in csr.details(my_id, d)), METRIC_MAX
        )
    dead = tpu.solve_vp(csr) - 1
    nbr_ids_p = np.full(b - 1, dead, dtype=np.int32)
    nbr_ids_p[:n] = nbr_ids
    nbr_metric = np.full(b - 1, METRIC_MAX, dtype=np.int32)
    nbr_metric[:n] = nbr_metric_real
    nbr_over = np.ones(b - 1, dtype=bool)
    nbr_over[:n] = csr.node_overloaded[np.array(nbr_ids, dtype=np.int64)]
    roots = np.full(b, my_id, dtype=np.int32)
    roots[1 : 1 + n] = nbr_ids
    table, dev, has_over = tpu._dispatch(csr)
    assert table == "split", table
    vp = dev["vp"]
    gs = tpu._pick_gs_and_count(dev)
    t1 = time.perf_counter()
    rec("host prep (to_csr, nbrs, dispatch)", (t1 - t0) * 1e3)

    dist_dev, packed = batched_sssp_split_rib(
        dev["base_nbr"], dev["base_wgt"], dev["ov_ids"], dev["ov_nbr"],
        dev["ov_wgt"], dev["out_nbr"], dev["over"], jnp.asarray(roots),
        jnp.asarray(nbr_metric), jnp.asarray(nbr_ids_p),
        jnp.asarray(nbr_over), jnp.int32(my_id),
        has_overloads=has_over, with_lfa=tpu.enable_lfa, gs_chunks=gs,
    )
    jax.block_until_ready(packed)
    t2 = time.perf_counter()
    rec("fused dispatch + block_until_ready", (t2 - t1) * 1e3)

    buf = np.asarray(packed)
    t3 = time.perf_counter()
    rec(f"packed transfer ({buf.nbytes / 1e6:.2f} MB)", (t3 - t2) * 1e3)

    d_root, fh, lfa = unpack_rib_buffer(buf, vp, b, tpu.enable_lfa)
    t4 = time.perf_counter()
    rec("unpack_rib_buffer", (t4 - t3) * 1e3)
    rec("TOTAL", (t4 - t0) * 1e3)

for k, xs in rows.items():
    print(f"  {k:42s} p50 {p50(xs):9.2f} ms  (min {min(xs):.2f})", flush=True)
