"""BASELINE config 5: incremental SPF under sustained link-flap churn.

Measures, on one Decision module fed through its real publication path:
  * steady-state recompute latency p50/p99 (full LSDB → RIB, using the
    incremental CSR patch journal + device-array cache),
  * flap → RouteUpdate end-to-end latency (publication push to route
    delta emitted, including debounce),
  * coalescing: flaps absorbed per recompute (debounce effectiveness).

Run: python benchmarks/bench_churn.py [--nodes 1280] [--flaps-per-sec 1000]
     [--seconds 10]
Prints one JSON line (same contract as bench.py).

reference analogue: openr/decision/tests/DecisionBenchmark.cpp † measures
full rebuilds on synthetic grids; the reference has no incremental path —
this harness exists to show churn does NOT cost a full rebuild here.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from openr_tpu.common.tasks import guard_task, reap  # noqa: E402


def _bench_trace():
    """OPENR_BENCH_TRACE=<dir> xprof trace hook shared by the measured
    stages (same contract as bench.py's headline loop): a no-op when
    unset or the profiler is unavailable (monitor/profiling.py)."""
    from openr_tpu.monitor import profiling

    return profiling.trace(os.environ.get("OPENR_BENCH_TRACE"))


def build_decision(
    adj_dbs, prefix_dbs, debounce_min=None, debounce_max=None,
    solver="tpu", counters=None, areas=("0",),
):
    from openr_tpu.config import Config
    from openr_tpu.decision.decision import Decision
    from openr_tpu.messaging import ReplicateQueue
    from openr_tpu.types.kvstore import Publication, Value
    from openr_tpu.types.serde import to_wire

    cfg = Config.default(adj_dbs[0].this_node_name)
    if debounce_min is not None:
        cfg.node.decision.debounce_min_ms = debounce_min
    if debounce_max is not None:
        cfg.node.decision.debounce_max_ms = debounce_max
    pubs = ReplicateQueue(name="pubs")
    routes = ReplicateQueue(name="routes")
    dec = Decision(
        cfg, pubs.get_reader("d"), routes, solver=solver, counters=counters
    )

    def pub_for(db, version=1, area="0"):
        return Publication(
            area=area,
            key_vals={
                f"adj:{db.this_node_name}": Value(
                    version=version,
                    originator_id=db.this_node_name,
                    value=to_wire(db),
                ).with_hash()
            },
        )

    # the same adjacency plane published under every requested area
    # (multi-area work bench: a dual-plane topology so the cross-area
    # merge book genuinely selects across two full per-area tables)
    for area in areas:
        for db in adj_dbs:
            dec.process_publication(pub_for(db, area=area))
    from openr_tpu.common import constants as C

    for pdb in prefix_dbs:
        for entry in pdb.prefix_entries:
            dec.process_publication(
                Publication(
                    area=areas[0],
                    key_vals={
                        C.prefix_key(
                            pdb.this_node_name, areas[0], str(entry.prefix)
                        ): Value(
                            version=1,
                            originator_id=pdb.this_node_name,
                            value=to_wire(pdb),
                        ).with_hash()
                    },
                )
            )
    return dec, pubs, routes, pub_for


async def churn(
    dec, pubs, routes, pub_for, adj_dbs, flaps_per_sec, seconds, burst=10
):
    """Flap link metrics at the target rate while Decision runs live.

    `burst` flaps are delivered back-to-back per wakeup (aggregate rate
    unchanged); real KvStore floods deliver publication BATCHES. The
    inter-wakeup gap (burst / flaps_per_sec) is the protocol's most
    load-bearing knob: gaps at or below Decision's debounce MIN
    (default 10 ms) re-defer the coalescing window on every poke, so
    each cycle runs to the debounce MAX cap (default 250 ms) — the
    by-design saturating-churn regime (~250-flap batches, flap→RIB
    ≈ max/2 + recompute). Gaps above the min (burst 20 at 1 kHz ⇒
    20 ms) fire the min-debounce after every burst — the low-latency
    regime. See the BASELINE.md config-5 protocol note; traced
    poke-by-poke in round 5."""
    import dataclasses

    from openr_tpu.messaging import QueueClosedError

    await dec.start()
    reader = routes.get_reader("bench")
    # LSDB was loaded synchronously before start: trigger + await the
    # first full RIB (includes the one-time jit compile)
    dec.debounce.poke()
    await asyncio.wait_for(dec.rib_computed.wait(), 600)

    from openr_tpu.monitor import perf

    rng = np.random.default_rng(7)
    flap_t: dict[int, float] = {}  # flap seq -> send time
    got_t: list[float] = []  # flap→update latencies
    trace_ms: list[float] = []  # PerfEvents-derived flap→update totals
    spf_ms: list[float] = []
    breakdown: dict[str, list[float]] = {}
    versions = {db.this_node_name: 1 for db in adj_dbs}
    n_flaps = 0
    stop = time.perf_counter() + seconds
    interval = 1.0 / flaps_per_sec

    async def drain():
        while True:
            try:
                upd = await reader.get()
            except QueueClosedError:
                return
            now = time.perf_counter()
            # only credit flaps published BEFORE the snapshot behind this
            # update — later flaps land in the NEXT rebuild and counting
            # them here would deflate the reported latency
            cutoff = dec._last_emitted_snapshot_t0
            for seq, t0 in list(flap_t.items()):
                if t0 <= cutoff:
                    got_t.append((now - t0) * 1e3)
                    del flap_t[seq]
            # trace-derived latency: the per-stage-stamped PerfEvents the
            # sampled flaps carried through Decision (KVSTORE_FLOODED →
            # ROUTE_UPDATE_SENT), independent of this loop's wall clock
            for pe in upd.perf_events:
                trace_ms.append(pe.total_ms())

    drainer = guard_task(
        asyncio.ensure_future(drain()), owner="bench_churn.drain"
    )
    # Pre-generate the flap publications: in production the serialization
    # happens at each flapping link's OWN router (LinkMonitor persistKey);
    # this node only ever sees the serialized value arrive from KvStore.
    # Building them in the send loop would bill the remote originators'
    # encode cost to the node under test.
    max_flaps = int(flaps_per_sec * seconds * 1.2) + 100
    pregen = []
    for _ in range(max_flaps):
        i = int(rng.integers(0, len(adj_dbs)))
        db = adj_dbs[i]
        k = int(rng.integers(0, len(db.adjacencies)))
        new_adjs = list(db.adjacencies)
        a = new_adjs[k]
        new_adjs[k] = dataclasses.replace(
            a, metric=int(rng.integers(1, 64))
        )
        db = dataclasses.replace(db, adjacencies=tuple(new_adjs))
        adj_dbs[i] = db
        versions[db.this_node_name] += 1
        pregen.append(pub_for(db, version=versions[db.this_node_name]))

    next_send = time.perf_counter()
    base_spf_runs = dec._spf_runs
    last_runs = dec._spf_runs
    no_change_flaps = [0]
    stop = time.perf_counter() + seconds  # exclude pregen time
    while time.perf_counter() < stop and n_flaps < max_flaps:
        for _ in range(burst):
            if n_flaps >= max_flaps:
                break
            flap_t[n_flaps] = time.perf_counter()
            if n_flaps % 50 == 0:
                # sampled tracing (1-in-50): enough samples for a p50
                # without letting trace bookkeeping distort the very
                # hot path this bench measures
                pregen[n_flaps].perf_events = perf.PerfEvents.start(
                    perf.KVSTORE_FLOODED, node="bench"
                )
            dec.process_publication(pregen[n_flaps])
            n_flaps += 1
        dec.debounce.poke()
        # one recompute-latency sample PER RECOMPUTE (flap-weighted
        # sampling would duplicate the pre-churn value hundreds of times)
        if dec._spf_runs != last_runs:
            last_runs = dec._spf_runs
            spf_ms.append(dec._last_spf_ms)
            for k, v in dec.last_breakdown_ms.items():
                breakdown.setdefault(k, []).append(v)
        # flaps proven to have produced no route change (their rebuild
        # completed without emitting) are dropped, not timed forever
        emitted, completed = (
            dec._last_emitted_snapshot_t0, dec._last_completed_snapshot_t0
        )
        if completed > emitted:
            for seq, t in list(flap_t.items()):
                if emitted < t <= completed:
                    del flap_t[seq]
                    no_change_flaps[0] += 1
        next_send += interval * burst
        delay = next_send - time.perf_counter()
        if delay > 0:
            await asyncio.sleep(delay)
        else:
            await asyncio.sleep(0)  # yield so Decision can run
    # let the tail drain
    await asyncio.sleep(1.0)
    spf_runs = dec._spf_runs - base_spf_runs
    await reap(drainer)
    await dec.stop()
    return (
        n_flaps, spf_runs, spf_ms, got_t, no_change_flaps[0], breakdown,
        trace_ms,
    )


def measure_prefix_churn(
    nodes: int = 80,
    rounds: int = 120,
    burst: int = 8,
    solver: str = "cpu",
    force_full: bool = False,
    seed: int = 3,
    warmup_rounds: int = 4,
    work_accounting: bool = True,
):
    """Prefix-only churn microbench: the dirty-scoped rebuild's headline.

    Fixed fat-tree topology; a rotating pool of extra /24s is
    re-advertised / withdrawn through the REAL publication path, and the
    rebuild coroutine is driven directly (no debounce timing noise) —
    each round is `burst` prefix events then one rebuild, sampling
    `Decision._last_spf_ms`. On the scoped pipeline every round is a
    `decision.rebuild.prefix_only` with ZERO SPF solves; with
    `force_full=True` the SAME workload runs down the from-scratch path
    (`Decision.force_full_rebuild`) for the speedup comparison.

    Returns a dict with `prefix_churn_p50_ms`/p99 plus the pipeline
    counters proving which path ran (`rebuild_prefix_only`,
    `rebuild_full`, `area_solves`, `engine_solves`).
    """
    from openr_tpu.common import constants as C
    from openr_tpu.monitor import Counters, compile_ledger, work_ledger
    from openr_tpu.types.kvstore import Publication, Value
    from openr_tpu.types.network import IpPrefix
    from openr_tpu.types.serde import to_wire
    from openr_tpu.types.topology import PrefixDatabase, PrefixEntry
    from openr_tpu.utils import topogen

    led = compile_ledger.install()
    work_ledger.reset()
    work_ledger.set_enabled(work_accounting)
    k = max(4, int(round((nodes * 4 / 5) ** 0.5 / 2)) * 2)
    adj_dbs, prefix_dbs = topogen.fat_tree(k, metric=10)
    counters = Counters()
    dec, _pubs, _routes, _pub_for = build_decision(
        adj_dbs, prefix_dbs, solver=solver, counters=counters
    )
    dec.force_full_rebuild = force_full
    rng = np.random.default_rng(seed)
    names = [db.this_node_name for db in adj_dbs]
    pool_n = 200  # rotating advertise/withdraw pool, one /24 each
    advertised = [False] * pool_n
    versions: dict[str, int] = {}

    async def run():
        samples: list[float] = []
        await dec._rebuild_routes()  # initial full build (jit compile)
        solves0 = dec._area_solves
        for r in range(rounds):
            if r == warmup_rounds:
                # post-warmup rounds must be pure jit-cache hits: any
                # later XLA compile is a ledger violation the smoke
                # lane exits 1 on; the work ledger's steady-state
                # window opens at the same boundary
                led.mark_warm()
                work_ledger.mark_warm()
            for _ in range(burst):
                i = int(rng.integers(0, pool_n))
                node = names[i % len(names)]
                pstr = f"10.77.{i}.0/24"
                key = C.prefix_key(node, "0", pstr)
                if advertised[i]:
                    pub = Publication(area="0", expired_keys=[key])
                else:
                    versions[key] = versions.get(key, 0) + 1
                    pub = Publication(
                        area="0",
                        key_vals={
                            key: Value(
                                version=versions[key],
                                originator_id=node,
                                value=to_wire(
                                    PrefixDatabase(
                                        this_node_name=node,
                                        prefix_entries=(
                                            PrefixEntry(
                                                prefix=IpPrefix(prefix=pstr)
                                            ),
                                        ),
                                        area="0",
                                    )
                                ),
                            ).with_hash()
                        },
                    )
                advertised[i] = not advertised[i]
                dec.process_publication(pub)
            await dec._rebuild_routes()
            if r >= warmup_rounds:
                samples.append(dec._last_spf_ms)
        return samples, solves0

    samples, solves0 = asyncio.new_event_loop().run_until_complete(run())
    steady_compiles = led.compiles_since_warm()
    led.reset_warm()
    work = work_ledger.since_warm() if work_accounting else {}
    work_ledger.reset_warm()
    work_ledger.set_enabled(True)
    arr = np.array(samples) if samples else np.array([0.0])
    engine_solves = (
        dec._tpu.solve_count if dec._tpu is not None else dec._area_solves
    )
    return {
        "prefix_churn_p50_ms": round(float(np.percentile(arr, 50)), 3),
        "prefix_churn_p99_ms": round(float(np.percentile(arr, 99)), 3),
        "steady_state_compiles": sum(steady_compiles.values()),
        "steady_state_compile_fns": sorted(steady_compiles),
        # per-stage steady-state work attribution (docs/Monitor.md
        # "Work ledger"): touched/delta/ratio since the warm mark
        "work": work,
        "work_accounting": work_accounting,
        "nodes": len(adj_dbs),
        "rounds": rounds,
        "burst": burst,
        "engine": solver,
        "forced_full": force_full,
        "rebuild_prefix_only": int(
            counters.get("decision.rebuild.prefix_only")
        ),
        "rebuild_full": int(counters.get("decision.rebuild.full")),
        "area_solves": dec._area_solves,
        "churn_area_solves": dec._area_solves - solves0,
        "engine_solves": engine_solves,
    }


def measure_topo_churn(
    nodes: int = 320,
    rounds: int = 60,
    solver: str = "cpu",
    force_full: bool = False,
    seed: int = 5,
    warmup_rounds: int = 2,
    check_parity_every: int = 0,
    revert_every: int = 4,
):
    """Seeded link-flap / metric-change storm microbench: the
    topology-delta warm-start's headline (`--topo-churn`).

    Fixed grid topology; each round flaps ONE random non-root link's
    metric through the REAL publication path and drives the rebuild
    coroutine directly (no debounce timing noise), sampling
    `Decision._last_spf_ms`. Every `revert_every`-th round reverts the
    previous flap (flap-then-revert, the convergence-critical shape).
    On the warm pipeline every round is a `decision.rebuild.topo_delta`
    with zero full area solves; `force_full=True` runs the SAME
    workload down the from-scratch path for the speedup comparison.

    With `check_parity_every=N > 0`, every Nth round's published RIB is
    compared byte-for-byte against a from-scratch `compute_rib` — the
    CI smoke lane's gate.

    Returns `topo_churn_p50_ms`/p99 plus the counters proving which
    path ran (`rebuild_topo_delta`, `rebuild_full`, `warm_starts`,
    `engine_solves`, `churn_area_solves`) and `parity` ("ok" /
    "MISMATCH:<round>" / "unchecked").
    """
    import dataclasses

    from openr_tpu.monitor import Counters, compile_ledger, work_ledger
    from openr_tpu.utils import topogen

    led = compile_ledger.install()
    work_ledger.reset()
    side = max(2, int(round(nodes ** 0.5)))
    adj_dbs, prefix_dbs = topogen.grid(side, side)
    counters = Counters()
    dec, _pubs, _routes, pub_for = build_decision(
        adj_dbs, prefix_dbs, solver=solver, counters=counters
    )
    if solver == "tpu":
        # the native single-root engine has no warm-start path (its
        # artifact carries no neighbor distance columns): measure the
        # batched-kernel pipeline the delta path targets
        if dec._tpu is not None:
            dec._tpu.native_rib = "off"
    dec.force_full_rebuild = force_full
    rng = np.random.default_rng(seed)
    adj_cur = {db.this_node_name: db for db in adj_dbs}
    names = [db.this_node_name for db in adj_dbs]
    versions = {n: 1 for n in names}
    parity = ["unchecked"]

    def flap(node: str, k: int, metric: int):
        db = adj_cur[node]
        adjs = list(db.adjacencies)
        adjs[k] = dataclasses.replace(adjs[k], metric=metric)
        db = dataclasses.replace(db, adjacencies=tuple(adjs))
        adj_cur[node] = db
        versions[node] += 1
        dec.process_publication(pub_for(db, version=versions[node]))

    async def run():
        samples: list[float] = []
        await dec._rebuild_routes()  # initial full build (jit compile)
        solves0 = dec._area_solves
        parity_solves = 0
        last: tuple | None = None
        for r in range(rounds):
            if r == warmup_rounds:
                # zero-steady-state-recompile gate (ci.sh smoke lane):
                # every post-warmup round — warm kernel, cone scatter,
                # patch scatter, parity compute_rib — must hit the jit
                # cache; the ledger counts anything that doesn't
                led.mark_warm()
                work_ledger.mark_warm()
            if last is not None and revert_every and r % revert_every == 0:
                node, k, old_metric = last
                flap(node, k, old_metric)  # flap-then-revert
                last = None
            else:
                # never the RIB root: a root-incident metric change
                # legitimately falls back to full (nexthop slot metrics
                # move) — that case is covered by tests, not the bench
                node = names[int(rng.integers(1, len(names)))]
                db = adj_cur[node]
                k = int(rng.integers(0, len(db.adjacencies)))
                old_metric = int(db.adjacencies[k].metric)
                new_metric = old_metric
                while new_metric == old_metric:
                    # a draw equal to the current metric would be a
                    # no-op round (no rebuild → stale latency sample,
                    # missed counter) — re-roll, still seed-determined
                    new_metric = int(rng.integers(1, 64))
                flap(node, k, new_metric)
                last = (node, k, old_metric)
            await dec._rebuild_routes()
            if r >= warmup_rounds:
                samples.append(dec._last_spf_ms)
            if check_parity_every and r % check_parity_every == 0:
                before = dec._area_solves
                ref = dec.compute_rib()
                parity_solves += dec._area_solves - before
                if (
                    dec.rib.unicast_routes != ref.unicast_routes
                    or dec.rib.mpls_routes != ref.mpls_routes
                ):
                    parity[0] = f"MISMATCH:{r}"
                    break
                if parity[0] == "unchecked":
                    parity[0] = "ok"
        return samples, solves0, parity_solves

    # OPENR_BENCH_TRACE=<dir> captures an xprof trace of the churn rounds
    with _bench_trace():
        samples, solves0, parity_solves = asyncio.run(run())
    steady_compiles = led.compiles_since_warm()
    led.reset_warm()
    # NOTE: with check_parity_every > 0 the from-scratch compute_rib
    # parity calls land inside the steady window, so the spf_full row
    # includes the parity solves' honest full-table work (single-area
    # bench: no merge fold runs, scoped or full)
    work = work_ledger.since_warm()
    work_ledger.reset_warm()
    arr = np.array(samples) if samples else np.array([0.0])
    engine_solves = (
        dec._tpu.solve_count if dec._tpu is not None else dec._area_solves
    )
    warm_engine = dec._tpu.warm_solves if dec._tpu is not None else None
    return {
        "topo_churn_p50_ms": round(float(np.percentile(arr, 50)), 3),
        "topo_churn_p99_ms": round(float(np.percentile(arr, 99)), 3),
        "steady_state_compiles": sum(steady_compiles.values()),
        "steady_state_compile_fns": sorted(steady_compiles),
        "work": work,
        "nodes": len(adj_dbs),
        "rounds": rounds,
        "engine": solver,
        "forced_full": force_full,
        "rebuild_topo_delta": int(
            counters.get("decision.rebuild.topo_delta")
        ),
        "rebuild_full": int(counters.get("decision.rebuild.full")),
        "warm_starts": int(counters.get("decision.spf.warm_starts")),
        "warm_fallbacks": int(
            counters.get("decision.spf.warm_fallbacks")
        ),
        "area_solves": dec._area_solves,
        # full-area solves the CHURN itself cost (parity-check
        # compute_rib calls excluded): zero on the warm pipeline
        "churn_area_solves": dec._area_solves - solves0 - parity_solves,
        "engine_solves": engine_solves,
        "engine_warm_solves": warm_engine,
        "parity": parity[0],
    }


class _NullKv:
    """KvStoreClient stub for the work bench's PrefixManager: the
    redistribution book's walks are the measurement; re-advertisement
    back into KvStore is out of scope (and would need a full cluster)."""

    def persist_key(self, area, key, value, ttl_ms=0):
        pass

    def unset_key(self, area, key):
        pass


def measure_work_churn(
    nodes: int = 320,
    prefixes: int = 100_000,
    rounds: int = 24,
    burst: int = 16,
    mode: str = "prefix",
    solver: str = "tpu",
    seed: int = 9,
    warmup_rounds: int = 4,
):
    """Work-ledger attribution bench (`--work-bench`): the full route
    dataflow — dirt → SPF → election → assembly → cross-area merge →
    diff → FIB programming → PrefixManager redistribution — under
    steady churn, with every stage's touched-entity count accounted
    against its input delta (docs/Monitor.md "Work ledger").

    Unlike the prefix/topo microbenches this one is built so the whole
    delta pipeline — including the two formerly-O(routes) stages — runs
    end to end every round:

      * a dual-plane two-area topology (the same adjacency graph
        published under areas "0" and "1", the static prefix pool split
        between them) makes every scoped rebuild exercise the
        cross-area delta merge book (merge_scope_delta patching the
        live RIB in place);
      * a real PrefixManager in the ABR role (two configured areas,
        stub KvStore client) folds every RouteUpdate through
        `fold_rib_update` + `_sync_advertisements` — delta-native entry
        books since ISSUE 17, touched ≈ the update's own churn;
      * a real Fib (MockFibHandler) programs every RouteUpdate through
        the delta book, pinning `work.fib.ratio` at 1.

    `mode="prefix"` churns a rotating advertise/withdraw pool in area
    "0"; `mode="topo"` flaps one link metric per round in area "0"
    (area "1" stays cached). Returns per-stage steady attribution plus
    the derived `oroutes_share`: the fraction of the full-table budget
    (routes × steady rounds) merge + redistribute actually touched —
    ~1 while those walks were O(routes) (BENCH_WORK.json pinned ratios
    6565/13129), ~0 since the delta books (BENCH_WORK_r02.json).
    """
    from openr_tpu.common import constants as C
    from openr_tpu.config import AreaConfig, Config, NodeConfig
    from openr_tpu.fib.fib import Fib, MockFibHandler
    from openr_tpu.monitor import Counters, compile_ledger, work_ledger
    from openr_tpu.types.kvstore import Publication, Value
    from openr_tpu.types.network import IpPrefix
    from openr_tpu.types.serde import to_wire
    from openr_tpu.types.topology import PrefixDatabase, PrefixEntry
    from openr_tpu.utils import topogen

    led = compile_ledger.install()
    work_ledger.reset()
    areas = ("0", "1")
    if mode == "topo":
        side = max(2, int(round(nodes ** 0.5)))
        adj_dbs, prefix_dbs = topogen.grid(side, side)
    else:
        k = max(4, int(round((nodes * 4 / 5) ** 0.5 / 2)) * 2)
        adj_dbs, prefix_dbs = topogen.fat_tree(k, metric=10)
    counters = Counters()
    dec, _pubs, routes, pub_for = build_decision(
        adj_dbs, prefix_dbs, solver=solver, counters=counters, areas=areas
    )
    if solver == "tpu" and dec._tpu is not None:
        # the native single-root engine has no warm-start path (see
        # measure_topo_churn): measure the batched-kernel pipeline so
        # topo rounds take the warm path, not a full solve per flap
        dec._tpu.native_rib = "off"
    names = [db.this_node_name for db in adj_dbs]
    root = names[0]

    # pad the prefix table to the target scale, split between the two
    # areas (so each per-area RIB holds ~half and the merge fold is the
    # only place the full table exists). Batched publications: one
    # process_publication per 2048 keys, not per prefix.
    batches: dict[str, dict] = {a: {} for a in areas}

    def flush(area: str) -> None:
        if batches[area]:
            dec.process_publication(
                Publication(area=area, key_vals=dict(batches[area]))
            )
            batches[area].clear()

    for i in range(max(0, prefixes - len(dec.rib.unicast_routes))):
        node = names[i % len(names)]
        area = areas[i % 2]
        pstr = f"10.{128 + (i >> 16)}.{(i >> 8) & 0xFF}.{i & 0xFF}/32"
        batches[area][C.prefix_key(node, area, pstr)] = Value(
            version=1,
            originator_id=node,
            value=to_wire(
                PrefixDatabase(
                    this_node_name=node,
                    prefix_entries=(
                        PrefixEntry(prefix=IpPrefix(prefix=pstr)),
                    ),
                    area=area,
                )
            ),
        ).with_hash()
        if len(batches[area]) >= 2048:
            flush(area)
    for area in areas:
        flush(area)

    two_area_cfg = Config(
        NodeConfig(
            node_name=root,
            areas=tuple(AreaConfig(area_id=a) for a in areas),
        )
    )
    from openr_tpu.prefixmgr.prefix_manager import PrefixManager

    pm = PrefixManager(two_area_cfg, _NullKv(), counters=counters)
    fib = Fib(
        two_area_cfg,
        routes.get_reader("work_fib"),
        MockFibHandler(),
        counters=counters,
    )
    reader = routes.get_reader("work_bench")

    rng = np.random.default_rng(seed)
    pool_n = 256
    advertised = [False] * pool_n
    versions: dict[str, int] = {}
    adj_cur = {db.this_node_name: db for db in adj_dbs}
    adj_versions = {n: 1 for n in names}

    def churn_prefix_round():
        for _ in range(burst):
            i = int(rng.integers(0, pool_n))
            node = names[i % len(names)]
            pstr = f"10.77.{i >> 8}.{i & 0xFF}/32"
            key = C.prefix_key(node, "0", pstr)
            if advertised[i]:
                pub = Publication(area="0", expired_keys=[key])
            else:
                versions[key] = versions.get(key, 0) + 1
                pub = Publication(
                    area="0",
                    key_vals={
                        key: Value(
                            version=versions[key],
                            originator_id=node,
                            value=to_wire(
                                PrefixDatabase(
                                    this_node_name=node,
                                    prefix_entries=(
                                        PrefixEntry(
                                            prefix=IpPrefix(prefix=pstr)
                                        ),
                                    ),
                                    area="0",
                                )
                            ),
                        ).with_hash()
                    },
                )
            advertised[i] = not advertised[i]
            dec.process_publication(pub)

    def churn_topo_round():
        import dataclasses

        node = names[int(rng.integers(1, len(names)))]
        db = adj_cur[node]
        j = int(rng.integers(0, len(db.adjacencies)))
        old = int(db.adjacencies[j].metric)
        new = old
        while new == old:
            new = int(rng.integers(1, 64))
        adjs = list(db.adjacencies)
        adjs[j] = dataclasses.replace(adjs[j], metric=new)
        db = dataclasses.replace(db, adjacencies=tuple(adjs))
        adj_cur[node] = db
        adj_versions[node] += 1
        dec.process_publication(
            pub_for(db, version=adj_versions[node], area="0")
        )

    async def feed_downstream() -> None:
        """Run every drained RouteUpdate through the real downstream
        consumers — the Fib delta program and the ABR redistribution
        fold — exactly as their module loops would."""
        while True:
            upd = reader.get_nowait()
            if upd is None:
                return
            fib._fold_update(upd)
            fib._have_rib = True
            await fib._program_once()
            pm.fold_rib_update(upd)
            pm._sync_advertisements()

    async def run():
        samples: list[float] = []
        await dec._rebuild_routes()  # initial full build (jit compile)
        await feed_downstream()  # initial FULL_SYNC program + fold
        for r in range(rounds):
            if r == warmup_rounds:
                led.mark_warm()
                work_ledger.mark_warm()
            if mode == "topo":
                churn_topo_round()
            else:
                churn_prefix_round()
            await dec._rebuild_routes()
            await feed_downstream()
            if r >= warmup_rounds:
                samples.append(dec._last_spf_ms)
        return samples

    with _bench_trace():
        samples = asyncio.new_event_loop().run_until_complete(run())
    steady_compiles = led.compiles_since_warm()
    led.reset_warm()
    work = work_ledger.since_warm()
    # the delta-proportional-by-design stages must hold k·delta+floor —
    # since ISSUE 17 that includes merge and redistribute (delta merge
    # book + incremental redistribution books). Full area solves, the
    # fallback merge_full fold and the warm region (topology-bounded,
    # not delta-count-bounded) are the documented exemptions
    # (docs/Monitor.md "Work ledger"). Under topology dirt the route-db
    # diff is also honestly O(tables) — a metric change can move any
    # route, so both tables are compared — while under prefix churn it
    # is scoped (ratio 1) and gated.
    exempt = ("spf_full", "spf_warm", "merge_full", "full_sync")
    if mode == "topo":
        exempt = exempt + ("diff",)
    violations = work_ledger.steady_violations(exempt=exempt)
    work_ledger.reset_warm()
    arr = np.array(samples) if samples else np.array([0.0])
    steady_rounds = max(1, rounds - warmup_rounds)
    oroutes_touched = sum(
        work.get(s, {}).get("touched", 0) for s in ("merge", "redistribute")
    )

    def stage_ratio(stage: str):
        row = work.get(stage)
        return row["ratio"] if row else None

    def touched_per_round(stage: str):
        row = work.get(stage)
        if not row or not row["rounds"]:
            return 0.0
        return round(row["touched"] / row["rounds"], 1)

    routes_total = len(dec.rib.unicast_routes) + len(dec.rib.mpls_routes)
    return {
        "work_churn_p50_ms": round(float(np.percentile(arr, 50)), 3),
        "work_churn_p99_ms": round(float(np.percentile(arr, 99)), 3),
        "mode": mode,
        "nodes": len(adj_dbs),
        "prefixes": prefixes,
        "routes_total": routes_total,
        "redistribution_book": len(pm._entries),
        "rounds": rounds,
        "steady_rounds": steady_rounds,
        "burst": burst,
        "engine": solver,
        "steady_state_compiles": sum(steady_compiles.values()),
        "steady_state_compile_fns": sorted(steady_compiles),
        "work": work,
        # the headline attribution, re-based by ISSUE 17: the fraction
        # of the full-table budget (routes_total × steady rounds) that
        # merge + redistribute actually touched. ~1 while the walks
        # were O(routes); ~0 now that both stages are delta-native.
        # (The old all-stages-touched denominator stopped meaning
        # anything once every stage became delta-proportional — the
        # two stages' RELATIVE share among tiny per-delta costs is not
        # the regression signal; their absolute table share is.)
        "oroutes_share": round(
            oroutes_touched / max(routes_total * steady_rounds, 1), 4
        ),
        "merge_touched_per_round": touched_per_round("merge"),
        "redistribute_touched_per_round": touched_per_round("redistribute"),
        "work_merge_ratio": stage_ratio("merge"),
        "work_redistribute_ratio": stage_ratio("redistribute"),
        "work_election_ratio": stage_ratio("election"),
        "work_fib_ratio": stage_ratio("fib"),
        "work_dirt_ratio": stage_ratio("dirt"),
        "work_violations": violations,
        "rebuild_prefix_only": int(
            counters.get("decision.rebuild.prefix_only")
        ),
        "rebuild_topo_delta": int(
            counters.get("decision.rebuild.topo_delta")
        ),
        "rebuild_full": int(counters.get("decision.rebuild.full")),
    }


def _ledger_round_cost_us(iters: int = 100_000) -> float:
    """Deterministic microbench of ONE prefix-churn round's ledger
    traffic — the exact commit/scope sites a scoped rebuild performs
    (dirt commit, election scope, assembly commit, diff commit; merge
    only joins in multi-area). Isolated on a private WorkLedger so the
    measurement never pollutes the process ledger."""
    import time as _time

    from openr_tpu.monitor.work_ledger import WorkLedger

    led = WorkLedger()
    led.mark_warm()  # worst case: the warm path also tracks worst-round
    t0 = _time.perf_counter()
    for _ in range(iters):
        led.commit("dirt", 2, 2)
        with led.scope("election", 2) as ws:
            ws.add(3)
        led.commit("assembly", 2, 2)
        led.commit("diff", 2, 2)
    return (_time.perf_counter() - t0) / iters * 1e6


def measure_work_overhead(
    nodes: int = 80, rounds: int = 400, repeats: int = 3
) -> dict:
    """WorkScope steady-state cost on the hottest measured path,
    reported two ways:

      * headline `overhead_pct` — the deterministic per-round ledger
        cost (`_ledger_round_cost_us`) as a percentage of the measured
        enabled-arm prefix-churn p50. The ledger does a handful of
        integer commits per round (~4 µs), which is below what
        end-to-end timing can resolve on a burstable host, so the
        exact code-path cost is the honest headline.
      * `e2e_paired_pct` — prefix-churn p50 with accounting ON vs OFF
        (`work_ledger.set_enabled`), interleaved pairs, median of
        per-pair ratios (adjacent pairs share the host's slow drift).
        Corroboration only: across runs it lands within ±several
        percent of zero, i.e. indistinguishable from no overhead —
        which is the point, and why it is not the gate.
    """
    on: list[float] = []
    off: list[float] = []
    for _ in range(max(1, repeats)):
        off.append(
            measure_prefix_churn(
                nodes=nodes, rounds=rounds, solver="tpu",
                work_accounting=False,
            )["prefix_churn_p50_ms"]
        )
        on.append(
            measure_prefix_churn(
                nodes=nodes, rounds=rounds, solver="tpu",
                work_accounting=True,
            )["prefix_churn_p50_ms"]
        )
    pair_pcts = sorted(
        (a / max(b, 1e-9) - 1) * 100 for a, b in zip(on, off)
    )
    e2e_paired_pct = pair_pcts[len(pair_pcts) // 2]
    round_us = _ledger_round_cost_us()
    p50_us = min(on) * 1e3
    return {
        "overhead_pct": round(round_us / max(p50_us, 1e-9) * 100, 2),
        "ledger_us_per_round": round(round_us, 3),
        "e2e_paired_pct": round(e2e_paired_pct, 2),
        "e2e_pair_pcts": [round(p, 2) for p in pair_pcts],
        "p50_ms_enabled": min(on),
        "p50_ms_disabled": min(off),
        "p50_ms_enabled_runs": on,
        "p50_ms_disabled_runs": off,
        "repeats": repeats,
    }


def _grid_edges(side: int) -> list[tuple[str, str]]:
    edges = []
    for r in range(side):
        for c in range(side):
            if c < side - 1:
                edges.append((f"n{r}x{c}", f"n{r}x{c + 1}"))
            if r < side - 1:
                edges.append((f"n{r}x{c}", f"n{r + 1}x{c}"))
    return edges


async def _new_traces(cluster, seen_before: dict[str, int], timeout_s: float):
    """Wait for the first node to complete a new PerfEvents trace after
    a link event, then keep collecting until the count is stable for a
    full second (drain, not a fixed grace window: a fixed window
    censors exactly the slow stragglers a slow codec produces, biasing
    its p50 LOW — the straggler set must close before either codec's
    distribution is read)."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout_s

    def collect():
        out = []
        for name, node in cluster.nodes.items():
            n_new = (
                int(node.counters.get("monitor.perf_traces", 0))
                - seen_before[name]
            )
            if n_new > 0:
                out.extend(list(node.monitor.perf_traces)[-n_new:])
        return out

    while loop.time() < deadline:
        if collect():
            break
        await asyncio.sleep(0.05)
    stable_since = loop.time()
    n_last = len(collect())
    while loop.time() < deadline:
        await asyncio.sleep(0.1)
        n_now = len(collect())
        if n_now != n_last:
            n_last = n_now
            stable_since = loop.time()
        elif loop.time() - stable_since >= 1.0:
            break
    return collect()


# counters the flood bench reports as deltas (all summed cluster-wide)
_FLOOD_COUNTERS = (
    "kvstore.floods_sent",
    "kvstore.flood_bytes",
    "kvstore.flood_span_bytes",
    "kvstore.flood_encodes",
    "kvstore.flood_keys_coalesced",
    "kvstore.full_syncs",
    "kvstore.full_syncs_served",
    "kvstore.full_sync_keys_sent",
    "kvstore.full_syncs_noop",
    "kvstore.full_syncs_noop_served",
    "kvstore.full_sync_probe_miss",
)


def measure_flood(
    codec: str = "bin",
    side: int = 8,
    churn_events: int = 400,
    churn_hz: float = 200.0,
    pool: int = 48,
    flap_rounds: int = 4,
    seed: int = 11,
    timeout_s: float = 180.0,
    trace_every: int = 0,
) -> dict:
    """Full-stack emulated-cluster flood benchmark for ONE wire codec
    (`--flood-bench` runs it for both and prints the comparison).

    A side×side grid of complete OpenrNodes (real Spark / LinkMonitor /
    KvStore / Decision / Fib over mock I/O, CPU oracle solver — no jax)
    runs three seeded stages:

      1. sustained prefix churn through the PrefixManager seam at
         `churn_hz`, then drain until every store is byte-identical —
         floods/sec (deliveries per second of pure-CPU wire-seam
         time: `kvstore.flood_encode_ms` + `flood_decode_ms`) and
         bytes/flood
         over that window, all counter-derived (`kvstore.flood_bytes`
         is the wire frame size the transport reported, not an
         estimate; wall-clock floods/sec is reported as
         `floods_per_sec_wall` but is pipeline- and host-noise-
         dominated);
      2. `flap_rounds` link fail/heal events — `convergence_p50_ms`
         from the PerfEvents traces (NEIGHBOR_EVENT → FIB_PROGRAMMED),
         the same instrumentation bench.py's headline uses;
      3. one forced anti-entropy sweep on the converged cluster — the
         delta full_sync path's noop-probe counters (docs/Wire.md).

    Ends with the emulator invariant checker (same classes the chaos
    and soak suites gate on) so the measured path is also a verified
    one. The serialize-once contract is visible in the row:
    `encodes_per_flood` ≈ 1/fan-out on the binary path, exactly 1.0 on
    the legacy per-peer JSON path.
    """
    import random
    from dataclasses import replace

    from openr_tpu.emulator import invariants
    from openr_tpu.emulator.cluster import Cluster, scaled_spark
    from openr_tpu.monitor import perf
    from openr_tpu.prefixmgr.prefix_manager import (
        PrefixEvent,
        PrefixEventType,
        PrefixSource,
    )
    from openr_tpu.types.network import IpPrefix
    from openr_tpu.types.topology import PrefixEntry

    n_nodes = side * side
    # the bench CHURNS while the whole grid shares one host core:
    # scale the Spark timers as if the cluster were 2x its size, or
    # the 64-node JSON baseline bring-up wave hits the hold-expiry
    # flap storm scaled_spark's docstring describes (the hold timer
    # would be measuring codec cost, not liveness — exactly the
    # congestion this PR's binary path relieves). The hold timer is
    # then pinned well past the worst event-loop stall a churn-drain
    # wave produces (the JSON baseline stalls keepalive RX for
    # multiple seconds at 64 nodes; a hold inside that window turns
    # the drain into a self-sustaining neighbor-down cascade) but
    # below _new_traces' 30 s flap-detection window. Trace-derived
    # convergence starts at NEIGHBOR_EVENT, so the longer hold never
    # enters the reported latency — it only delays fail_link
    # detection. Key TTL is pushed past the bench horizon: the
    # default 300s TTL starts synchronized refresh waves ~225s in
    # (client.py TTL_REFRESH_FRACTION), background noise that would
    # pollute the seeded workload both codecs must share.
    spark_hdr = scaled_spark(n_nodes * 2) if n_nodes > 16 else None
    if spark_hdr is not None:
        spark_hdr = replace(
            spark_hdr,
            hold_time_ms=12_000,
            graceful_restart_time_ms=24_000,
        )

    def transform(ncfg):
        if spark_hdr is not None:
            ncfg = replace(
                ncfg,
                spark=replace(
                    spark_hdr, wire_codec=ncfg.spark.wire_codec
                ),
            )
        return replace(
            ncfg,
            kvstore=replace(
                ncfg.kvstore,
                key_ttl_ms=3_600_000,
                # cross-node flood tracing (docs/Monitor.md): sampled
                # hop spans ride the floods; 0 = tracing off (the
                # baseline the --flood-trace overhead gate compares to)
                trace_sample_every=trace_every,
                trace_seed=seed,
            ),
        )

    c = Cluster.from_edges(
        _grid_edges(side), solver="cpu", wire_codec=codec,
        node_config_transform=transform,
    )

    def csum(name: str) -> int:
        return sum(
            int(n.counters.get(name, 0)) for n in c.nodes.values()
        )

    def snap() -> dict[str, int]:
        return {k: csum(k) for k in _FLOOD_COUNTERS}

    def seam_split() -> dict[str, float]:
        """Cluster-wide pure-CPU time inside the wire seam, split by
        side: every flood encode (`kvstore.flood_encode_ms`) and every
        receive decode (`kvstore.flood_decode_ms`). Neither stat spans
        an await, so event-loop queueing — which dominates the
        wall-clock `kvstore.flood_fanout_ms` latency under a 64-node
        churn wave and drowns the codec effect in scheduler noise —
        can't inflate it (docs/Wire.md)."""
        out = {"enc": 0.0, "dec": 0.0}
        for n in c.nodes.values():
            for key, stat in (
                ("enc", "kvstore.flood_encode_ms"),
                ("dec", "kvstore.flood_decode_ms"),
            ):
                s = n.counters.stats.get(stat)
                if s is not None:
                    out[key] += s.sum
        return out

    def seam_ms_sum() -> float:
        s = seam_split()
        return s["enc"] + s["dec"]

    ids: dict[str, int] = {}

    def push_prefix(node_name: str, idx: int, add: bool) -> None:
        entry = PrefixEntry(
            prefix=IpPrefix.make(
                f"10.210.{ids[node_name] & 0xFF}.{idx}/32"
            )
        )
        c.nodes[node_name].prefix_events.push(
            PrefixEvent(
                type=(
                    PrefixEventType.ADD_PREFIXES
                    if add
                    else PrefixEventType.WITHDRAW_PREFIXES
                ),
                source=PrefixSource.API,
                entries=(entry,),
            )
        )

    t_wall = time.perf_counter()

    def _stage(msg: str) -> None:
        print(
            f"[flood-bench {codec}] +{time.perf_counter() - t_wall:.1f}s "
            f"{msg}",
            file=sys.stderr,
        )

    async def run() -> dict:
        rng = random.Random(seed)
        await c.start()
        try:
            await c.wait_converged(timeout=timeout_s)
            _stage("converged")
            await asyncio.sleep(0.5)  # bring-up floods/syncs settle
            names = sorted(c.nodes)
            ids.update({n: i for i, n in enumerate(names)})
            loop = asyncio.get_running_loop()

            # stage 1: seeded prefix churn → counter-derived throughput
            base = snap()
            split0 = seam_split()
            advertised: set[tuple[str, int]] = set()
            t0 = loop.time()
            for _ in range(churn_events):
                node_name = names[rng.randrange(len(names))]
                idx = rng.randrange(pool)
                key = (node_name, idx)
                add = key not in advertised
                push_prefix(node_name, idx, add)
                (advertised.add if add else advertised.discard)(key)
                await asyncio.sleep(1.0 / churn_hz)
            _stage(f"churn pushed ({loop.time() - t0:.1f}s)")
            while True:
                # drained = routes converged AND every store identical
                if c.converged() and not invariants.check_kvstore_consistency(c):
                    break
                if loop.time() - t0 > timeout_s:
                    raise TimeoutError("flood churn never drained")
                await asyncio.sleep(0.05)
            elapsed = loop.time() - t0
            churn = {k: csum(k) - base[k] for k in _FLOOD_COUNTERS}
            split1 = seam_split()
            seam_enc = split1["enc"] - split0["enc"]
            seam_dec = split1["dec"] - split0["dec"]
            seam_ms = seam_enc + seam_dec
            _stage(f"churn drained ({elapsed:.1f}s)")

            # stage 2: link flaps → trace-derived convergence latency
            trace_ms: list[float] = []
            for _ in range(flap_rounds):
                ls = c.links[rng.randrange(len(c.links))]
                seen = {
                    name: int(
                        node.counters.get("monitor.perf_traces", 0)
                    )
                    for name, node in c.nodes.items()
                }
                c.fail_link(ls.a, ls.b)
                got = await _new_traces(c, seen, timeout_s=30.0)
                trace_ms.extend(
                    t.total_ms()
                    for t in got
                    if t.last_event() == perf.FIB_PROGRAMMED
                    and len(t.events) >= 5
                )
                c.heal_link(ls.a, ls.b)
                await c.wait_converged(timeout=timeout_s)
                await asyncio.sleep(0.3)

            _stage("flap stage done")
            # stage 3: forced anti-entropy sweep on the converged
            # cluster — the delta full_sync noop-probe fast path
            base_ae = snap()
            for node in c.nodes.values():
                await node.kvstore._anti_entropy()
            t_ae = loop.time()
            while any(
                p.sync_task is not None and not p.sync_task.done()
                for node in c.nodes.values()
                for p in node.kvstore.peers.values()
            ):
                if loop.time() - t_ae > timeout_s:
                    raise TimeoutError("anti-entropy sweep stuck")
                await asyncio.sleep(0.02)
            ae = {k: csum(k) - base_ae[k] for k in _FLOOD_COUNTERS}
            _stage("anti-entropy swept")

            # the measured path must also be a correct one: same
            # invariant classes + quiescence gate the chaos and soak
            # suites end every round with
            await invariants.wait_quiescent(
                c,
                timeout_s=timeout_s,
                context=f"flood-bench codec={codec} seed={seed}",
            )
            _stage("quiesced")

            floods = churn["kvstore.floods_sent"]
            tarr = np.array(trace_ms) if trace_ms else np.array([0.0])
            trace_stats = None
            if trace_every > 0:
                # completed hop-span traces cluster-wide: completions,
                # deepest path, waterfall-vs-total agreement, and the
                # per-stage attribution the BENCH row carries
                from openr_tpu.emulator import tracing

                trace_stats = tracing.trace_report(c)
            return {
                "codec": codec,
                "nodes": len(c.nodes),
                "churn_events": churn_events,
                "churn_elapsed_s": round(elapsed, 2),
                "floods_sent": floods,
                # the headline throughput: deliveries per second of
                # wire-SEAM time (counter-derived from the pure-CPU
                # kvstore.flood_encode_ms + flood_decode_ms stats —
                # see seam_ms_sum). The wall-clock variant is
                # kept for context but is dominated by the rest of
                # the pipeline (decision rebuilds, fib programming)
                # and by this host class's sustained-load throttling
                # (±25% between adjacent identical runs) — it cannot
                # resolve a wire-path change; the seam measure can
                # (docs/Wire.md)
                "floods_per_sec": round(
                    floods / max(seam_ms / 1e3, 1e-9), 1
                ),
                "wire_seam_ms": round(seam_ms, 1),
                "wire_seam_encode_ms": round(seam_enc, 1),
                "wire_seam_decode_ms": round(seam_dec, 1),
                # codec efficiency, robust to coalescing batch shape:
                # µs/flood conflates batch size with codec cost (bigger
                # batches = fewer, fatter frames), ns/byte does not
                "seam_ns_per_byte": round(
                    seam_ms * 1e6
                    / max(churn["kvstore.flood_bytes"], 1),
                    2,
                ),
                # flood tracing's DIRECT wire footprint: packed span
                # bytes shipped as a fraction of all flood bytes
                "span_byte_share": round(
                    churn["kvstore.flood_span_bytes"]
                    / max(churn["kvstore.flood_bytes"], 1),
                    5,
                ),
                "floods_per_sec_wall": round(floods / elapsed, 1),
                "flood_bytes": churn["kvstore.flood_bytes"],
                "bytes_per_flood": round(
                    churn["kvstore.flood_bytes"] / max(floods, 1), 1
                ),
                "flood_encodes": churn["kvstore.flood_encodes"],
                "encodes_per_flood": round(
                    churn["kvstore.flood_encodes"] / max(floods, 1), 3
                ),
                "keys_coalesced": churn["kvstore.flood_keys_coalesced"],
                "convergence_p50_ms": round(
                    float(np.percentile(tarr, 50)), 3
                ),
                "convergence_p99_ms": round(
                    float(np.percentile(tarr, 99)), 3
                ),
                "convergence_traces": len(trace_ms),
                "anti_entropy": {
                    "full_syncs": ae["kvstore.full_syncs"],
                    "noop": ae["kvstore.full_syncs_noop"],
                    "noop_served": ae["kvstore.full_syncs_noop_served"],
                    "probe_miss": ae["kvstore.full_sync_probe_miss"],
                    "keys_sent": ae["kvstore.full_sync_keys_sent"],
                },
                "trace_every": trace_every,
                "flood_traces": trace_stats,
                # per-stage p50 breakdown from hop spans (alongside
                # convergence_p50_ms, per the observability plan)
                "convergence_attribution": (
                    trace_stats["attribution"].get("stages_p50_ms")
                    if trace_stats is not None
                    else None
                ),
                "invariants": "ok",
            }
        finally:
            await c.stop()

    # OPENR_BENCH_TRACE=<dir> wraps the whole flood run (churn + flap +
    # anti-entropy stages) in an xprof trace
    with _bench_trace():
        return asyncio.run(run())


def _smoke_gate(label: str, scoped: dict, checks: dict[str, bool]) -> None:
    """Shared CI-gate core for the churn smoke lanes: every named check
    must hold, plus the clause common to EVERY lane — zero post-warmup
    XLA compiles (the compile-ledger invariant; a steady-state recompile
    means a shape leaked past the padding buckets, docs/Linting.md
    OR008-OR010). On failure: one diagnostic line naming the failed
    checks with the full counter row, then exit 1."""
    checks = dict(checks)
    checks["zero steady-state compiles"] = (
        scoped["steady_state_compiles"] == 0
    )
    failed = [name for name, ok in checks.items() if not ok]
    if not failed:
        return
    counters = {
        k: v for k, v in scoped.items() if not k.endswith("_ms")
    }
    print(
        f"{label} smoke FAILED: {'; '.join(failed)} — "
        f"counters: {json.dumps(counters)}",
        file=sys.stderr,
    )
    sys.exit(1)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1280)
    ap.add_argument("--flaps-per-sec", type=float, default=1000.0)
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--debounce-min-ms", type=float, default=None)
    ap.add_argument("--debounce-max-ms", type=float, default=None)
    ap.add_argument("--burst", type=int, default=10)
    ap.add_argument(
        "--backend", choices=("auto", "cpu"), default="auto",
        help="cpu forces jax onto host CPU (the axon sitecustomize "
        "overrides JAX_PLATFORMS env, so the config must be set "
        "in-process before backend init)",
    )
    ap.add_argument(
        "--prefix-churn", action="store_true",
        help="run the prefix-only (re-advertise/withdraw) workload on a "
        "fixed topology instead of link flaps: measures the dirty-scoped "
        "rebuild fast path, and the same workload forced down the "
        "full-rebuild path for the speedup ratio",
    )
    ap.add_argument("--prefix-rounds", type=int, default=120)
    ap.add_argument(
        "--force-full", action="store_true",
        help="with --prefix-churn/--topo-churn: skip the scoped/warm "
        "run and measure only the forced full-rebuild path",
    )
    ap.add_argument(
        "--topo-churn", action="store_true",
        help="run the seeded link-flap + metric-change storm on a fixed "
        "grid: measures the topology-delta warm-start path "
        "(decision.rebuild.topo_delta), and the same workload forced "
        "down the full path for the speedup ratio",
    )
    ap.add_argument("--topo-rounds", type=int, default=60)
    ap.add_argument(
        "--flood-bench", action="store_true",
        help="run the full-stack emulated-cluster flood benchmark on "
        "BOTH wire codecs (legacy per-peer JSON vs serialize-once "
        "binary, docs/Wire.md): floods/sec, counter-derived "
        "bytes/flood, trace-derived convergence_p50_ms, and the delta "
        "full_sync noop-probe counters, with the emulator invariant "
        "checker gating each run",
    )
    ap.add_argument(
        "--flood-side", type=int, default=8,
        help="grid side for --flood-bench (8 → the 64-node headline)",
    )
    ap.add_argument("--flood-events", type=int, default=400)
    ap.add_argument("--flood-flaps", type=int, default=4)
    ap.add_argument(
        "--flood-codec", choices=("both", "bin", "json"), default="both",
    )
    ap.add_argument(
        "--flood-timeout", type=float, default=180.0,
        help="per-stage timeout (s) inside each flood-bench run; the "
        "64-node JSON baseline on a throttled burstable host can need "
        "several minutes to drain — raise this rather than letting "
        "the slow BASELINE abort the comparison",
    )
    ap.add_argument(
        "--flood-trace", action="store_true",
        help="run the flood workload in interleaved traced/untraced "
        "pairs on the binary codec (--flood-trace-every sampling, "
        "--flood-repeats pairs) and report completed cross-node "
        "traces, the named-stage waterfall/attribution, and tracing's "
        "isolated wire cost (span byte share + seam ns/byte ratio). "
        "With --smoke, exits 1 unless sampled traces complete "
        "end-to-end across >=3 hops, waterfalls attribute >=95%% of "
        "each span's total, and both overhead estimators stay <5%% "
        "(docs/Monitor.md 'Flood tracing')",
    )
    ap.add_argument(
        "--flood-trace-every", type=int, default=8,
        help="head-sampling period for the traced --flood-trace run "
        "(every Nth origination per node, seeded; the ci lane passes "
        "16 — sparser sampling trades span count for a wider margin "
        "under the 5%% overhead gate)",
    )
    ap.add_argument(
        "--flood-repeats", type=int, default=1,
        help="interleaved json/bin measurement rounds; each reported "
        "comparison scalar is the per-metric median across rounds "
        "(counters the throttled-host drift that penalizes whichever "
        "codec runs last, without coupling noisy metrics to one run)",
    )
    ap.add_argument(
        "--work-bench", action="store_true",
        help="run the work-ledger attribution bench (docs/Monitor.md "
        "'Work ledger'): the full dataflow — two-area decision, real "
        "Fib delta programming, real ABR PrefixManager redistribution "
        "— under prefix AND topo churn, reporting per-stage "
        "touched-entity attribution, merge + redistribute's share of "
        "the full-table budget (oroutes_share, ~0 since the ISSUE 17 "
        "delta books), and (without --smoke) the WorkScope overhead "
        "measurement. With --smoke: exits 1 unless work.election.ratio "
        "and work.fib.ratio hold their bounds, merge/redistribute "
        "ratios stay delta-proportional (<= 8), oroutes_share ~0, zero "
        "post-warmup XLA compiles landed, and no delta-proportional "
        "stage violated k*delta+floor",
    )
    ap.add_argument("--work-prefixes", type=int, default=100_000)
    ap.add_argument("--work-rounds", type=int, default=24)
    ap.add_argument("--work-burst", type=int, default=16)
    ap.add_argument(
        "--work-mode", choices=("both", "prefix", "topo"), default="both",
    )
    ap.add_argument(
        "--work-overhead-repeats", type=int, default=3,
        help="interleaved on/off pairs for the WorkScope overhead "
        "measurement (0 skips it; --smoke always skips it)",
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="CI gate mode. With --topo-churn: byte-parity checked "
        "against from-scratch compute_rib every few rounds, and the "
        "process exits 1 unless the warm-start path was actually taken "
        "(counter-asserted) and parity held. With --prefix-churn: the "
        "scoped path must run zero SPF solves. Both paths additionally "
        "assert ZERO post-warmup XLA compiles via the runtime compile "
        "ledger (monitor/compile_ledger.py) — a steady-state recompile "
        "means a shape leaked past the padding buckets",
    )
    args = ap.parse_args()
    if args.backend == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.flood_trace:
        kw = dict(
            side=args.flood_side,
            churn_events=args.flood_events,
            flap_rounds=args.flood_flaps,
            timeout_s=args.flood_timeout,
        )
        # interleaved (baseline, traced) pairs — the PR 8 lesson: this
        # host class drifts between adjacent runs, and the workload
        # itself is timing-coupled (coalescing batch shapes shift run
        # to run), so single-pair comparisons swing tens of percent.
        # Tracing overhead is therefore measured by two estimators
        # that ISOLATE the tracing cost instead of the batch shape:
        #   * span_byte_share — packed span bytes as a fraction of all
        #     flood bytes (the direct wire footprint; counter-derived);
        #   * seam ns/byte ratio, min-per-arm — codec efficiency per
        #     byte (the seam stat is pure CPU, so contention and
        #     unlucky draws only ever add time; µs-per-FLOOD is
        #     reported but NOT gated: span bookkeeping slows relays a
        #     hair, the pump then coalesces MORE keys per frame, and
        #     per-flood time rises while per-byte cost falls — a batch
        #     shape change, not a tracing cost).
        pairs = max(1, args.flood_repeats)
        runs_b: list[dict] = []
        runs_t: list[dict] = []
        for _ in range(pairs):
            runs_b.append(measure_flood("bin", **kw))
            runs_t.append(
                measure_flood(
                    "bin",
                    trace_every=max(1, args.flood_trace_every),
                    **kw,
                )
            )

        def seam_us_per_flood(r: dict) -> float:
            return r["wire_seam_ms"] * 1e3 / max(r["floods_sent"], 1)

        base_nsb = min(r["seam_ns_per_byte"] for r in runs_b)
        traced_nsb = min(r["seam_ns_per_byte"] for r in runs_t)
        per_byte_pct = round((traced_nsb / base_nsb - 1.0) * 100, 2)
        span_shares = [
            round(r["span_byte_share"] * 100, 2) for r in runs_t
        ]
        span_share_pct = max(span_shares)
        # headline: the larger of the two isolated costs (per-byte
        # processing degradation, added span bytes)
        overhead_pct = max(per_byte_pct, span_share_pct)
        reports = [r["flood_traces"] or {} for r in runs_t]
        attrs = [ts.get("attribution") or {} for ts in reports]
        traced = runs_t[-1]
        detail = {
            "pairs": pairs,
            "baseline": runs_b[-1],
            "traced": traced,
            "seam_per_byte_overhead_pct": per_byte_pct,
            "span_byte_share_pct": span_share_pct,
            "span_byte_share_runs_pct": span_shares,
            "seam_ns_per_byte_baseline_runs": [
                r["seam_ns_per_byte"] for r in runs_b
            ],
            "seam_ns_per_byte_traced_runs": [
                r["seam_ns_per_byte"] for r in runs_t
            ],
            "seam_us_per_flood_baseline_runs": [
                round(seam_us_per_flood(r), 2) for r in runs_b
            ],
            "seam_us_per_flood_traced_runs": [
                round(seam_us_per_flood(r), 2) for r in runs_t
            ],
            "trace_every": traced["trace_every"],
            # quality gates aggregate conservatively across traced
            # runs: completions/hops must be reached in EVERY run is
            # too strict for a smoke (draws differ) — best-of for
            # reach, worst-of for correctness fractions
            "completions": max(
                (ts.get("completions", 0) for ts in reports), default=0
            ),
            "max_hops": max(
                (ts.get("max_hops", 0) for ts in reports), default=0
            ),
            "waterfall_ok_frac": min(
                (ts.get("waterfall_ok_frac") or 0 for ts in reports),
                default=0,
            ),
            "attribution_coverage_p50": min(
                (a.get("coverage_p50") or 0 for a in attrs), default=0
            ),
            "convergence_attribution": traced.get(
                "convergence_attribution"
            ),
            "overhead_pct": overhead_pct,
        }
        print(
            json.dumps(
                {
                    "metric": "flood_trace_overhead_pct",
                    "value": overhead_pct,
                    "unit": "%",
                    "vs_baseline": None,
                    "detail": detail,
                }
            )
        )
        if args.smoke:
            checks = {
                # traces actually flowed and completed cluster-wide
                "traces completed (>=50)": detail["completions"] >= 50,
                # at least one span crossed >=3 flooding hops end-to-end
                ">=3-hop trace completed": detail["max_hops"] >= 3,
                # named stages telescope to the span total: every
                # waterfall within 5% of its trace's total_ms, p50
                # coverage >=95% (the acceptance's attribution bar) —
                # in EVERY traced run
                "waterfalls match totals": (
                    detail["waterfall_ok_frac"] >= 0.95
                    and detail["attribution_coverage_p50"] >= 0.95
                ),
                # sampled tracing's isolated wire cost <5%: per-byte
                # codec efficiency must not degrade AND the packed
                # spans' direct byte footprint must stay small
                "tracing overhead <5%": (
                    per_byte_pct < 5.0 and span_share_pct < 5.0
                ),
                "invariants clean": all(
                    r["invariants"] == "ok" for r in (*runs_b, *runs_t)
                ),
            }
            failed = [name for name, ok in checks.items() if not ok]
            if failed:
                print(
                    f"flood-trace smoke FAILED: {'; '.join(failed)} — "
                    f"detail: {json.dumps(detail)}",
                    file=sys.stderr,
                )
                sys.exit(1)
        return

    if args.flood_bench:
        kw = dict(
            side=args.flood_side,
            churn_events=args.flood_events,
            flap_rounds=args.flood_flaps,
            timeout_s=args.flood_timeout,
        )
        codecs = (
            ["json", "bin"]
            if args.flood_codec == "both"
            else [args.flood_codec]
        )
        # interleave codecs across repeats: this host's sustained-load
        # throttling (burstable CPU) makes LATER runs systematically
        # slower, so back-to-back per-codec runs would charge the drift
        # to whichever codec ran second — time-adjacent pairs + a
        # median per codec neutralize it
        samples: dict[str, list[dict]] = {c: [] for c in codecs}
        for _ in range(max(1, args.flood_repeats)):
            for codec_name in codecs:
                samples[codec_name].append(
                    measure_flood(codec_name, **kw)
                )
        def _median(vals: list[float]) -> float:
            vs = sorted(vals)
            n = len(vs)
            mid = vs[n // 2] if n % 2 else (vs[n // 2 - 1] + vs[n // 2]) / 2
            return round(mid, 3)

        # each comparison scalar is the PER-METRIC median across runs:
        # picking one "median row" (by any single metric) would couple
        # every other metric to that run's noise — convergence p50
        # especially swings ±50% round-to-round on this host class,
        # independently of which run had the median throughput
        _MEDIAN_KEYS = (
            "floods_per_sec", "wire_seam_ms", "floods_per_sec_wall",
            "bytes_per_flood", "encodes_per_flood", "churn_elapsed_s",
            "convergence_p50_ms", "convergence_p99_ms",
        )
        rows: dict[str, dict] = {}
        for codec_name, runs in samples.items():
            ordered = sorted(runs, key=lambda r: r["floods_per_sec"])
            med = dict(ordered[(len(ordered) - 1) // 2])
            if len(runs) > 1:
                for k in _MEDIAN_KEYS:
                    med[k] = _median([r[k] for r in runs])
                med["floods_per_sec_runs"] = [
                    r["floods_per_sec"] for r in runs
                ]
                med["convergence_p50_ms_runs"] = [
                    r["convergence_p50_ms"] for r in runs
                ]
            rows[codec_name] = med
        detail: dict = dict(rows)
        if len(rows) == 2:
            j, b = rows["json"], rows["bin"]
            detail["bytes_per_flood_ratio"] = round(
                j["bytes_per_flood"] / max(b["bytes_per_flood"], 1e-9), 2
            )
            detail["floods_per_sec_ratio"] = round(
                b["floods_per_sec"] / max(j["floods_per_sec"], 1e-9), 2
            )
            detail["convergence_p50_ratio"] = round(
                j["convergence_p50_ms"]
                / max(b["convergence_p50_ms"], 1e-9),
                2,
            )
        head = rows.get("bin") or rows["json"]
        print(
            json.dumps(
                {
                    "metric": "flood_throughput_per_sec",
                    "value": head["floods_per_sec"],
                    "unit": "floods/s",
                    "vs_baseline": None,
                    "detail": detail,
                }
            )
        )
        if args.smoke and len(rows) == 2:
            j, b = rows["json"], rows["bin"]
            checks = {
                # serialize-once actually engaged: strictly fewer
                # encodes than flood deliveries on the binary path,
                # while the legacy path pays one encode per delivery
                "binary path active": b["flood_encodes"] > 0
                and b["flood_encodes"] < b["floods_sent"],
                "delta full_sync served (noop probes)": (
                    b["anti_entropy"]["noop_served"] > 0
                    and b["anti_entropy"]["keys_sent"] == 0
                ),
                "floods/sec >= JSON baseline": (
                    b["floods_per_sec"] >= j["floods_per_sec"]
                ),
                "bytes/flood reduced >= 2x": (
                    b["bytes_per_flood"] * 2 <= j["bytes_per_flood"]
                ),
                # invariants: assert_invariants inside measure_flood
                # already raised on violation; this records the fact
                "invariants clean": all(
                    r["invariants"] == "ok" for r in rows.values()
                ),
            }
            failed = [name for name, ok in checks.items() if not ok]
            if failed:
                print(
                    f"flood-bench smoke FAILED: {'; '.join(failed)} — "
                    f"rows: {json.dumps(rows)}",
                    file=sys.stderr,
                )
                sys.exit(1)
        return

    if args.work_bench:
        modes = (
            ["prefix", "topo"]
            if args.work_mode == "both"
            else [args.work_mode]
        )
        rows: dict[str, dict] = {}
        for mode in modes:
            rows[mode] = measure_work_churn(
                nodes=args.nodes,
                prefixes=args.work_prefixes,
                rounds=args.work_rounds,
                burst=args.work_burst,
                mode=mode,
                solver="tpu",
            )
        overhead = None
        if not args.smoke and args.work_overhead_repeats > 0:
            overhead = measure_work_overhead(
                repeats=args.work_overhead_repeats
            )
        head = rows.get("prefix") or rows[modes[0]]
        row = {
            "metric": "work_oroutes_share",
            "value": head["oroutes_share"],
            "unit": "frac",
            "vs_baseline": None,
            # the per-stage ratios at TOP level so the bench-history
            # sentinel (benchmarks/history.py HEADLINE_METRICS) can
            # track their drift across runs
            "work_merge_ratio": head["work_merge_ratio"],
            "work_redistribute_ratio": head["work_redistribute_ratio"],
            "work_election_ratio": head["work_election_ratio"],
            "work_fib_ratio": head["work_fib_ratio"],
            "detail": {
                **rows,
                "work_overhead": overhead,
                "backend": _backend(),
            },
        }
        print(json.dumps(row))
        if not args.smoke:
            try:
                from benchmarks import history

                history.append_row(row)
            except Exception:  # noqa: BLE001 — read-only checkout etc.
                pass
        if args.smoke:
            for mode, scoped in rows.items():
                _smoke_gate(f"work-bench[{mode}]", scoped, {
                    # delta-proportional stages hold their pinned bounds
                    "fib ratio pinned at 1": (
                        scoped["work_fib_ratio"] is not None
                        and scoped["work_fib_ratio"] <= 1.5
                    ),
                    "election ratio bounded": (
                        scoped["work_election_ratio"] is None
                        or scoped["work_election_ratio"] <= 8.0
                    ),
                    # the two formerly-O(routes) walks are delta-native
                    # (ISSUE 17): ratios gate at a small constant (the
                    # merge fold touches scope × areas; redistribution
                    # touches the update's own churn) — a reintroduced
                    # full-table walk blows these by orders of magnitude
                    "merge ratio delta-proportional": (
                        scoped["work_merge_ratio"] is None
                        or scoped["work_merge_ratio"] <= 8.0
                    ),
                    "redistribute ratio delta-proportional": (
                        scoped["work_redistribute_ratio"] is None
                        or scoped["work_redistribute_ratio"] <= 8.0
                    ),
                    # merge + redistribute together touch ~none of the
                    # full-table budget under prefix churn; under topo
                    # churn a single flap legitimately reroutes a few
                    # percent of the table (the warm region's routes),
                    # so the bound is looser — still far below the ~1.0
                    # a reintroduced full-table walk would report
                    "oroutes share ~0": scoped["oroutes_share"] <= (
                        0.05 if mode == "prefix" else 0.25
                    ),
                    # the delta paths never retrace a kernel
                    "zero steady compiles": (
                        scoped["steady_state_compiles"] == 0
                    ),
                    # no scoped delta-proportional stage — merge and
                    # redistribute now included — breached k*delta+floor
                    # in any steady round
                    "no proportionality violations": (
                        not scoped["work_violations"]
                    ),
                })
        return

    if args.topo_churn:
        full = measure_topo_churn(
            nodes=args.nodes, rounds=max(10, args.topo_rounds // 3),
            solver="tpu", force_full=True,
        )
        scoped = None
        if not args.force_full:
            scoped = measure_topo_churn(
                nodes=args.nodes, rounds=args.topo_rounds, solver="tpu",
                check_parity_every=5 if args.smoke else 0,
            )
        head = scoped or full
        detail = {
            "warm": scoped,
            "forced_full": full,
            "backend": _backend(),
        }
        if scoped is not None:
            detail["speedup_vs_full"] = round(
                full["topo_churn_p50_ms"]
                / max(scoped["topo_churn_p50_ms"], 1e-6),
                1,
            )
        print(
            json.dumps(
                {
                    "metric": "topo_churn_p50_ms",
                    "value": head["topo_churn_p50_ms"],
                    "unit": "ms",
                    "vs_baseline": None,
                    "detail": detail,
                }
            )
        )
        if args.smoke and scoped is not None:
            # CI gate: the warm path must actually have been taken —
            # a single-link metric change must never pay a full
            # per-area solve — and byte-parity must hold
            _smoke_gate("topo-churn", scoped, {
                "parity": scoped["parity"] == "ok",
                "warm path every round": (
                    scoped["rebuild_topo_delta"] >= args.topo_rounds - 2
                ),
                "one initial full build": scoped["rebuild_full"] == 1,
                "warm starts taken": scoped["warm_starts"] > 0,
                "zero churn solves": scoped["churn_area_solves"] == 0,
            })
        return

    if args.prefix_churn:
        full = measure_prefix_churn(
            nodes=args.nodes, rounds=max(20, args.prefix_rounds // 3),
            solver="tpu", force_full=True,
        )
        scoped = None
        if not args.force_full:
            scoped = measure_prefix_churn(
                nodes=args.nodes, rounds=args.prefix_rounds, solver="tpu",
            )
        head = scoped or full
        detail = {
            "scoped": scoped,
            "forced_full": full,
            "backend": _backend(),
        }
        if scoped is not None:
            detail["speedup_vs_full"] = round(
                full["prefix_churn_p50_ms"]
                / max(scoped["prefix_churn_p50_ms"], 1e-6),
                1,
            )
        print(
            json.dumps(
                {
                    "metric": "prefix_churn_p50_ms",
                    "value": head["prefix_churn_p50_ms"],
                    "unit": "ms",
                    "vs_baseline": None,
                    "detail": detail,
                }
            )
        )
        if args.smoke and scoped is not None:
            # CI gate: the scoped pipeline must take the prefix-only
            # path for every churn round (the initial build is the one
            # full) and run ZERO SPF solves
            _smoke_gate("prefix-churn", scoped, {
                "prefix-only path every round": (
                    scoped["rebuild_prefix_only"] >= args.prefix_rounds - 1
                ),
                "one initial full build": scoped["rebuild_full"] == 1,
                "zero churn solves": scoped["churn_area_solves"] == 0,
            })
        return

    from openr_tpu.utils import topogen

    # 3-tier fat-tree with ~args.nodes nodes: 5k^2/4 = n → k
    k = max(4, int(round((args.nodes * 4 / 5) ** 0.5 / 2)) * 2)
    adj_dbs, prefix_dbs = topogen.fat_tree(k, metric=10)
    dec, pubs, routes, pub_for = build_decision(
        adj_dbs, prefix_dbs,
        debounce_min=args.debounce_min_ms, debounce_max=args.debounce_max_ms,
    )

    n_flaps, spf_runs, spf_ms, lat, no_change, breakdown, trace_ms = asyncio.new_event_loop().run_until_complete(
        churn(
            dec, pubs, routes, pub_for, list(adj_dbs),
            args.flaps_per_sec, args.seconds, burst=args.burst,
        )
    )
    spf = np.array(spf_ms) if spf_ms else np.array([0.0])
    latency = np.array(lat) if lat else np.array([0.0])
    out = {
        "metric": "churn_steady_state_recompute_p50_ms",
        "value": round(float(np.percentile(spf, 50)), 3),
        "unit": "ms",
        "vs_baseline": None,
        "detail": {
            "config": 5,
            "nodes": len(adj_dbs),
            "k": k,
            "flaps_sent": n_flaps,
            "flap_rate_target": args.flaps_per_sec,
            "burst": args.burst,
            "recomputes": spf_runs,
            "flaps_per_recompute": round(n_flaps / max(spf_runs, 1), 1),
            "no_change_flaps": no_change,
            "spf_p99_ms": round(float(np.percentile(spf, 99)), 3),
            "flap_to_rib_p50_ms": round(float(np.percentile(latency, 50)), 3),
            "flap_to_rib_p99_ms": round(float(np.percentile(latency, 99)), 3),
            # PerfEvents-derived convergence (sampled 1-in-50 flaps,
            # KVSTORE_FLOODED → ROUTE_UPDATE_SENT per-stage markers) —
            # the trace-based counterpart of flap_to_rib_p50_ms
            "convergence_p50_ms": (
                round(float(np.percentile(np.array(trace_ms), 50)), 3)
                if trace_ms else None
            ),
            "convergence_traces": len(trace_ms),
            "rebuild_breakdown_p50_ms": {
                k: round(float(np.percentile(np.array(v), 50)), 2)
                for k, v in breakdown.items()
            },
            # byte-splice decode tiers (decision.py _decode_adj_fast):
            # "fast" should dominate under single-flap-per-key churn
            "decode_stats": dict(dec.decode_stats),
            "backend": _backend(),
        },
    }
    print(json.dumps(out))


def _backend() -> str:
    import jax

    return jax.default_backend()


if __name__ == "__main__":
    main()
