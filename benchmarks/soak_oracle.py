"""Randomized-churn oracle-equivalence soak across graph families.

Burn-in confidence harness (SURVEY §4 test strategy: the oracle is the
ground truth; upstream's DecisionTest churn scenarios † are the model):
for each topology family, apply a random mutation stream — metric
flaps, prefix withdraw/re-add, overload toggles, adjacency
removal/restore — and after EVERY step assert that BOTH production
engines (the batched split-kernel solver and the native C++ radix-heap
engine) produce a RIB identical to the stateless python oracle.

This generalizes tests/test_incremental.py's 24-step property test to
arbitrary step counts, seeds, and families for out-of-CI burn-ins:

    python benchmarks/soak_oracle.py --steps 300 --seed 7

Exit code 0 and one PASS line per family, or a first-failure dump.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def _families():
    from openr_tpu.utils import topogen

    return {
        # name -> (adj_dbs, prefix_dbs) thunk; sizes kept oracle-sized
        "fat_tree_8": lambda: topogen.fat_tree(8),
        "fat_tree_4_hop": lambda: topogen.fat_tree(4),  # uniform metrics
        "grid_9x9": lambda: topogen.grid(9, 9),
        "ring_64": lambda: topogen.ring(64),
        "full_mesh_24": lambda: topogen.full_mesh(24),
    }


def soak_family(name: str, mk, steps: int, seed: int) -> None:
    from openr_tpu.decision.linkstate import LinkState, PrefixState
    from openr_tpu.decision.oracle import (
        compute_routes as oracle_compute_routes,
    )
    from openr_tpu.decision.spf_backend import TpuSpfSolver
    from openr_tpu.ops.native_spf import native_available
    from openr_tpu.types.network import IpPrefix
    from openr_tpu.types.topology import PrefixDatabase, PrefixEntry

    adj_dbs, prefix_dbs = mk()
    ls = LinkState()
    for db in adj_dbs:
        ls.update_adjacency_db(db)
    ps = PrefixState()
    for pdb in prefix_dbs:
        ps.update_prefix_db(pdb)

    rng = np.random.default_rng(seed)
    engines = {"split": TpuSpfSolver(native_rib="off")}
    if native_available():
        engines["native"] = TpuSpfSolver(native_rib="on")
    names = [adb.this_node_name for adb in adj_dbs]
    removed: dict[str, object] = {}
    t0 = time.perf_counter()

    for step in range(steps):
        op = rng.integers(0, 10)
        node = names[int(rng.integers(0, len(names)))]
        db = ls.adjacency_db(node)
        if op < 5 and db and db.adjacencies:
            adjs = list(db.adjacencies)
            k = int(rng.integers(0, len(adjs)))
            adjs[k] = dataclasses.replace(
                adjs[k], metric=int(rng.integers(1, 32))
            )
            ls.update_adjacency_db(
                dataclasses.replace(db, adjacencies=tuple(adjs))
            )
        elif op < 7:
            i = int(rng.integers(0, len(names)))
            pfx = IpPrefix(prefix=f"10.99.{i % 256}.0/24")
            if rng.integers(0, 2):
                ps.update_prefix_db(
                    PrefixDatabase(
                        this_node_name=names[i],
                        prefix_entries=(PrefixEntry(prefix=pfx),),
                    )
                )
            else:
                ps.withdraw(names[i], pfx)
        elif op < 8 and db:
            ls.update_adjacency_db(
                dataclasses.replace(db, is_overloaded=not db.is_overloaded)
            )
        elif op < 9 and db and node not in removed and node != names[0]:
            removed[node] = db
            ls.delete_adjacency_db(node)
        elif removed:
            nm, db_r = removed.popitem()
            ls.update_adjacency_db(db_r)

        # rotate the computing root so first-hop logic is exercised
        # from many vantage points, not just node 0
        root = names[step % min(len(names), 17)]
        if ls.adjacency_db(root) is None:
            root = names[0]
        want = oracle_compute_routes(ls, ps, root)
        for ename, solver in engines.items():
            got = solver.compute_routes(ls, ps, root)
            if (
                got.unicast_routes != want.unicast_routes
                or got.mpls_routes != want.mpls_routes
            ):
                print(
                    f"FAIL {name} step {step} engine {ename} root {root} "
                    f"seed {seed}",
                    flush=True,
                )
                uni_d = {
                    k: (
                        got.unicast_routes.get(k),
                        want.unicast_routes.get(k),
                    )
                    for k in set(got.unicast_routes) ^ set(want.unicast_routes)
                    | {
                        k
                        for k in set(got.unicast_routes)
                        & set(want.unicast_routes)
                        if got.unicast_routes[k] != want.unicast_routes[k]
                    }
                }
                for k, (g, w) in list(uni_d.items())[:5]:
                    print(f"  {k}: got={g}\n     want={w}", flush=True)
                sys.exit(1)
    dt = time.perf_counter() - t0
    print(
        f"PASS {name}: {steps} steps x {len(engines)} engines "
        f"({', '.join(engines)}) vs oracle, {dt:.1f}s",
        flush=True,
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--family", default=None, help="run one family only")
    ap.add_argument(
        "--tpu",
        action="store_true",
        help="run on the session's default backend (tunnel); the soak "
        "is a CPU correctness harness by default — the axon "
        "sitecustomize ignores JAX_PLATFORMS, so we must override the "
        "config before first backend init (tests/conftest.py rationale)",
    )
    args = ap.parse_args()

    if not args.tpu:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    fams = _families()
    if args.family:
        fams = {args.family: fams[args.family]}
    for name, mk in fams.items():
        soak_family(name, mk, args.steps, args.seed)
    print("ALL FAMILIES PASS", flush=True)


if __name__ == "__main__":
    main()
