"""BASELINE config 4: backbone with KSP2_ED_ECMP SR prefixes + LFA.

Measures, on a 2-tier backbone (ring of rings — redundant paths so both
KSP2 and LFA produce real alternates):
  * full-RIB rebuild latency with enable_lfa on,
  * per-KSP2-prefix incremental cost (the masked host re-solve),
  * correctness: RIB equality vs the oracle with both features on.

Run: python benchmarks/bench_ksp_lfa.py [--rings 8] [--ring-size 16]
     [--ksp-frac 0.1] [--backend cpu]
Prints one JSON line (same contract as bench.py).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402


def build_backbone(rings: int, ring_size: int):
    """Ring of rings: `rings` site-rings, adjacent sites joined by two
    parallel inter-site links (edge-disjoint paths everywhere)."""
    from openr_tpu.types.topology import (
        Adjacency,
        AdjacencyDatabase,
    )

    n = rings * ring_size
    edges: dict[tuple[int, int], int] = {}

    def add(a, b, m):
        edges[(a, b)] = m
        edges[(b, a)] = m

    for r in range(rings):
        base = r * ring_size
        for i in range(ring_size):
            add(base + i, base + (i + 1) % ring_size, 10)
        nxt = ((r + 1) % rings) * ring_size
        add(base, nxt, 100)  # inter-site
        add(base + ring_size // 2, nxt + ring_size // 2, 100)
    by_src: dict[int, list] = {}
    for (a, b), m in edges.items():
        by_src.setdefault(a, []).append((b, m))
    dbs = []
    for a in range(n):
        adjs = tuple(
            Adjacency(
                other_node_name=f"bb{b}", if_name=f"if{a}-{b}",
                other_if_name=f"if{b}-{a}", metric=m,
            )
            for b, m in sorted(by_src.get(a, []))
        )
        dbs.append(
            AdjacencyDatabase(
                this_node_name=f"bb{a}", adjacencies=adjs,
                node_label=100_000 + a,
            )
        )
    return dbs


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rings", type=int, default=8)
    ap.add_argument("--ring-size", type=int, default=16)
    ap.add_argument("--ksp-frac", type=float, default=0.1)
    ap.add_argument("--ksp-k", type=int, default=16)  # BASELINE config 4
    ap.add_argument("--backend", choices=("auto", "cpu"), default="auto")
    args = ap.parse_args()
    if args.backend == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        # real-chip run: serialize against the driver's bench slot;
        # always yieldable — an auxiliary harness must never kill a
        # live measurement (bench.py lock protocol)
        import bench

        bench.acquire_bench_lock(yieldable=True)

    from openr_tpu.decision.linkstate import LinkState, PrefixState
    from openr_tpu.decision.oracle import compute_routes as oracle_routes
    from openr_tpu.decision.spf_backend import TpuSpfSolver
    from openr_tpu.types.network import IpPrefix
    from openr_tpu.types.topology import (
        ForwardingAlgorithm,
        ForwardingType,
        PrefixDatabase,
        PrefixEntry,
        PrefixMetrics,
    )

    dbs = build_backbone(args.rings, args.ring_size)
    n = len(dbs)
    rng = np.random.default_rng(0)
    ksp_nodes = set(
        rng.choice(n, size=max(1, int(n * args.ksp_frac)), replace=False)
        .tolist()
    )
    ls, ps = LinkState(), PrefixState()
    for d in dbs:
        ls.update_adjacency_db(d)
    for i in range(n):
        algo = (
            ForwardingAlgorithm.KSP2_ED_ECMP
            if i in ksp_nodes else ForwardingAlgorithm.SP_ECMP
        )
        ftype = (
            ForwardingType.SR_MPLS
            if i in ksp_nodes else ForwardingType.IP
        )
        ps.update_prefix_db(
            PrefixDatabase(
                this_node_name=f"bb{i}",
                prefix_entries=(
                    PrefixEntry(
                        prefix=IpPrefix.make(
                            f"10.{(i >> 8) & 255}.{i & 255}.0/24"
                        ),
                        metrics=PrefixMetrics(),
                        forwarding_type=ftype,
                        forwarding_algorithm=algo,
                    ),
                ),
            )
        )

    me = "bb1"
    solver = TpuSpfSolver(enable_lfa=True, ksp_k=args.ksp_k)
    rib = solver.compute_routes(ls, ps, me)  # warm (compile)
    ts = []
    for _ in range(10):
        t0 = time.perf_counter()
        rib = solver.compute_routes(ls, ps, me)
        ts.append((time.perf_counter() - t0) * 1e3)
    ts = np.array(ts)

    # correctness vs oracle, both features on
    ora = oracle_routes(ls, ps, me, enable_lfa=True, ksp_k=args.ksp_k)
    rib_diff = sum(
        1 for p in set(rib.unicast_routes) | set(ora.unicast_routes)
        if rib.unicast_routes.get(p) != ora.unicast_routes.get(p)
    )

    n_ksp = sum(
        1 for e in rib.unicast_routes.values()
        if e.best_entry is not None
        and e.best_entry.forwarding_algorithm
        == ForwardingAlgorithm.KSP2_ED_ECMP
    )
    n_backup = sum(
        1 for e in rib.unicast_routes.values() if e.backup_nexthops
    )
    # isolate per-KSP-prefix cost: rebuild with KSP prefixes flipped to
    # SP_ECMP and compare
    ps2 = PrefixState()
    for i in range(n):
        ps2.update_prefix_db(
            PrefixDatabase(
                this_node_name=f"bb{i}",
                prefix_entries=(
                    PrefixEntry(
                        prefix=IpPrefix.make(
                            f"10.{(i >> 8) & 255}.{i & 255}.0/24"
                        ),
                        metrics=PrefixMetrics(),
                    ),
                ),
            )
        )
    solver.compute_routes(ls, ps2, me)
    t0 = time.perf_counter()
    solver.compute_routes(ls, ps2, me)
    plain_ms = (time.perf_counter() - t0) * 1e3
    per_ksp_ms = max(0.0, (float(np.percentile(ts, 50)) - plain_ms)) / max(
        n_ksp, 1
    )

    import jax

    print(json.dumps({
        "metric": "ksp_lfa_full_rib_p50_ms",
        "value": round(float(np.percentile(ts, 50)), 3),
        "unit": "ms",
        "vs_baseline": None,
        "detail": {
            "config": 4,
            "nodes": n,
            "ksp_k": args.ksp_k,
            "ksp_prefixes": n_ksp,
            "routes_with_lfa_backups": n_backup,
            "p99_ms": round(float(np.percentile(ts, 99)), 3),
            "per_ksp_prefix_ms": round(per_ksp_ms, 3),
            "rib_diff_vs_oracle": rib_diff,
            "backend": jax.default_backend(),
        },
    }))


if __name__ == "__main__":
    main()
