"""Platform layer: kernel route programming service.

reference: openr/platform/ † — NetlinkFibHandler implements the
Platform.thrift FibService on Linux via the native netlink library.
"""

from openr_tpu.platform.netlink_fib import NetlinkFibService

__all__ = ["NetlinkFibService"]
