"""NetlinkFibService: the real-kernel FibService implementation.

reference: openr/platform/NetlinkFibHandler.{h,cpp} † — implements
Platform.thrift's FibService (addUnicastRoutes / deleteUnicastRoutes /
addMplsRoutes / deleteMplsRoutes / syncFib / syncMplsFib /
getRouteTableByClient) by translating thrift route types into rtnetlink
operations. This rebuild keeps the same seam: `openr_tpu.fib.Fib` talks
to any object with this interface (the MockFibService in tests, this
class on a real router), and the rtnetlink encoding itself is native C++
(native/nl via openr_tpu.nl).

Interface-name → ifindex resolution uses the link dump (refreshed on
miss), like the reference's cached `ifIndexCache_` †. Routes are
installed with rtproto 99 ("openr") so `ip route show proto 99` and
flush-by-protocol behave like upstream.

The netlink socket is blocking; all public coroutines hop to a thread
(asyncio.to_thread) so the caller's event loop never stalls on the
kernel.
"""

from __future__ import annotations

import asyncio
import logging

from openr_tpu.monitor.counters import Counters
from openr_tpu.nl import NetlinkRoute, NetlinkSocket, Nexthop
from openr_tpu.common import constants as C
from openr_tpu.nl.netlink import RTPROT_OPENR

# the kernel's own "static" rtproto (include/uapi/linux/rtnetlink.h):
# manual breeze `fib add` routes carry it so `ip route` shows
# `proto static` and openr's full sync (filtered to its own proto)
# can never reclaim them
RTPROT_STATIC = 4
from openr_tpu.types.network import (
    IpPrefix,
    MplsAction,
    MplsActionType,
    MplsRoute,
    NextHop,
    UnicastRoute,
)

log = logging.getLogger(__name__)

RT_TABLE_MAIN = 254


def _nh_to_nl(nh: NextHop, ifindex: int) -> Nexthop:
    labels: tuple[int, ...] = ()
    act: MplsAction | None = nh.mpls_action
    if act is not None:
        if act.action == MplsActionType.PUSH:
            labels = tuple(act.push_labels)
        elif act.action == MplsActionType.SWAP and act.swap_label is not None:
            labels = (act.swap_label,)
        # PHP / POP_AND_LOOKUP → empty out-stack (implicit-null)
    gw = nh.address or None
    return Nexthop(
        gateway=gw,
        ifindex=ifindex,
        weight=max(1, nh.weight) if nh.weight else 1,
        labels=labels,
    )


class NetlinkFibService:
    """Programs the Linux FIB through the native netlink library."""

    def __init__(
        self,
        table: int = RT_TABLE_MAIN,
        protocol: int = RTPROT_OPENR,
        counters: Counters | None = None,
    ):
        self.table = table
        self.protocol = protocol  # openr's own client (CLIENT_ID_OPENR)
        self.counters = counters
        self._sock: NetlinkSocket | None = None
        self._ifindex: dict[str, int] = {}
        self._lock = asyncio.Lock()

    # ------------------------------------------------------------- plumbing

    def _sock_or_open(self) -> NetlinkSocket:
        if self._sock is None:
            self._sock = NetlinkSocket()
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def _proto_for(self, client_id: int) -> int:
        """Kernel-side client separation (review finding: client_id was
        ignored, so openr's sync_fib deleted breeze-injected static
        routes): each FibService client maps to its own rtproto, and
        every add/delete/dump/sync below filters by it."""
        if client_id == C.FIB_CLIENT_STATIC:
            return RTPROT_STATIC
        return self.protocol

    def _resolve_ifindex(self, if_name: str) -> int:
        if not if_name:
            return 0
        idx = self._ifindex.get(if_name)
        if idx is None:
            # refresh cache on miss (reference: ifIndexCache_ fallback to
            # link dump †)
            for link in self._sock_or_open().links_dump():
                self._ifindex[link["name"]] = link["ifindex"]
            idx = self._ifindex.get(if_name, 0)
        return idx

    def _to_nl(self, route: UnicastRoute, proto: int) -> NetlinkRoute:
        return NetlinkRoute(
            dst=str(route.dest),
            table=self.table,
            protocol=proto,
            nexthops=[
                _nh_to_nl(nh, self._resolve_ifindex(nh.if_name))
                for nh in route.nexthops
            ],
        )

    def _mpls_to_nl(self, route: MplsRoute, proto: int) -> NetlinkRoute:
        # the kernel rejects AF_MPLS RTM_NEWROUTE unless rtm_table is
        # RT_TABLE_MAIN (net/mpls/af_mpls.c rtm_to_route_config)
        return NetlinkRoute(
            mpls_label=route.top_label,
            table=RT_TABLE_MAIN,
            protocol=proto,
            nexthops=[
                _nh_to_nl(nh, self._resolve_ifindex(nh.if_name))
                for nh in route.nexthops
            ],
        )

    def _batch(
        self, routes: list[NetlinkRoute], delete: bool, what: str
    ) -> None:
        sock = self._sock_or_open()
        errs = sock.route_batch(routes, delete=delete, replace=not delete)
        ok = {0, -3} if delete else {0}  # deleting a gone route is fine
        failed = [
            (r.dst or r.mpls_label, e)
            for r, e in zip(routes, errs)
            if e not in ok
        ]
        if self.counters is not None:
            self.counters.increment(f"platform.{what}", len(routes))
        if failed:
            if self.counters is not None:
                self.counters.increment("platform.errors", len(failed))
            raise OSError(f"{what} failed: {failed[:5]}")

    # ----------------------------------------------------- FibService API

    async def add_unicast_routes(
        self, client_id: int, routes: list[UnicastRoute]
    ) -> None:
        proto = self._proto_for(client_id)
        nl = [self._to_nl(r, proto) for r in routes]
        await asyncio.to_thread(self._batch, nl, False, "routes_added")

    async def delete_unicast_routes(
        self, client_id: int, prefixes: list[IpPrefix]
    ) -> None:
        proto = self._proto_for(client_id)
        nl = [
            NetlinkRoute(dst=str(p), table=self.table, protocol=proto)
            for p in prefixes
        ]
        await asyncio.to_thread(self._batch, nl, True, "routes_deleted")

    async def add_mpls_routes(
        self, client_id: int, routes: list[MplsRoute]
    ) -> None:
        proto = self._proto_for(client_id)
        nl = [self._mpls_to_nl(r, proto) for r in routes]
        await asyncio.to_thread(self._batch, nl, False, "mpls_added")

    async def delete_mpls_routes(
        self, client_id: int, labels: list[int]
    ) -> None:
        proto = self._proto_for(client_id)
        nl = [
            NetlinkRoute(
                mpls_label=lbl, table=RT_TABLE_MAIN, protocol=proto
            )
            for lbl in labels
        ]
        await asyncio.to_thread(self._batch, nl, True, "mpls_deleted")

    async def sync_fib(
        self, client_id: int, routes: list[UnicastRoute]
    ) -> None:
        """Full-state sync: install `routes`, remove any other
        openr-protocol route in our table (reference: syncFib computes
        the same add/remove delta against getRouteTableByClient †)."""
        want = {str(r.dest): r for r in routes}
        have = await self.get_route_table_by_client(client_id)
        stale = [r.dest for r in have if str(r.dest) not in want]
        if stale:
            await self.delete_unicast_routes(client_id, stale)
        if routes:
            await self.add_unicast_routes(client_id, routes)

    async def sync_mpls_fib(
        self, client_id: int, routes: list[MplsRoute]
    ) -> None:
        want = {r.top_label for r in routes}
        have = await self.get_mpls_route_table_by_client(client_id)
        stale = [r.top_label for r in have if r.top_label not in want]
        if stale:
            await self.delete_mpls_routes(client_id, stale)
        if routes:
            await self.add_mpls_routes(client_id, routes)

    async def get_route_table_by_client(
        self, client_id: int
    ) -> list[UnicastRoute]:
        def dump():
            out = []
            idx_to_name = {
                l["ifindex"]: l["name"]
                for l in self._sock_or_open().links_dump()
            }
            for r in self._sock_or_open().routes_dump(
                table=self.table, protocol=self._proto_for(client_id)
            ):
                if r.mpls_label is not None:
                    continue
                out.append(
                    UnicastRoute(
                        dest=IpPrefix.make(r.dst),
                        nexthops=tuple(
                            NextHop(
                                address=nh.gateway or "",
                                if_name=idx_to_name.get(nh.ifindex, ""),
                                weight=nh.weight if nh.weight > 1 else 0,
                                mpls_action=(
                                    MplsAction(
                                        action=MplsActionType.PUSH,
                                        push_labels=tuple(nh.labels),
                                    )
                                    if nh.labels
                                    else None
                                ),
                            )
                            for nh in r.nexthops
                        ),
                    )
                )
            return out

        return await asyncio.to_thread(dump)

    async def get_mpls_route_table_by_client(
        self, client_id: int
    ) -> list[MplsRoute]:
        def dump():
            out = []
            idx_to_name = {
                l["ifindex"]: l["name"]
                for l in self._sock_or_open().links_dump()
            }
            for r in self._sock_or_open().routes_dump(
                family=28, protocol=self._proto_for(client_id)  # AF_MPLS
            ):
                if r.mpls_label is None:
                    continue
                out.append(
                    MplsRoute(
                        top_label=r.mpls_label,
                        nexthops=tuple(
                            NextHop(
                                address=nh.gateway or "",
                                if_name=idx_to_name.get(nh.ifindex, ""),
                                mpls_action=MplsAction(
                                    action=MplsActionType.SWAP,
                                    swap_label=nh.labels[0],
                                )
                                if nh.labels
                                else MplsAction(action=MplsActionType.PHP),
                            )
                            for nh in r.nexthops
                        ),
                    )
                )
            return out

        return await asyncio.to_thread(dump)
