"""Spark: neighbor discovery over link-local packet I/O.

reference: openr/spark/ † — hello/handshake/heartbeat FSM per
(interface, neighbor), hold-timer liveness, RTT measurement, graceful
restart, with the IoProvider seam making packet I/O mockable
(reference: openr/spark/IoProvider.h † + tests/MockIoProvider †).
"""

from openr_tpu.spark.io import IoProvider, MockIoHub, UdpIoProvider  # noqa: F401
from openr_tpu.spark.spark import Spark, SparkNeighborState  # noqa: F401
