"""Packet I/O seam for Spark.

reference: openr/spark/IoProvider.h † (real UDP multicast) and
openr/spark/tests/MockIoProvider.h † (in-process hub with configurable
per-link latency and partitions — the seam that makes the whole neighbor
FSM testable without sockets).
"""

from __future__ import annotations

import asyncio
import socket
import struct
from dataclasses import dataclass
from typing import Protocol

from openr_tpu.common.constants import SPARK_MCAST_PORT


class IoProvider(Protocol):
    async def recv(self) -> tuple[str, bytes]:
        """Returns (local_if_name, payload)."""
        ...

    async def send(self, if_name: str, payload: bytes) -> None: ...

    def close(self) -> None: ...


@dataclass
class _MockLink:
    a: tuple[str, str]  # (node, if)
    b: tuple[str, str]
    latency_ms: float = 0.0
    up: bool = True


class MockIoHub:
    """In-process packet fabric: point-to-point links between (node, if)
    endpoints with latency and up/down control.

    reference: MockIoProvider † — connectedPairs + latency + thread pump;
    here the pump is the event loop itself.
    """

    def __init__(self):
        self._links: list[_MockLink] = []
        self._inboxes: dict[str, asyncio.Queue] = {}

    def io_for(self, node: str) -> "MockIo":
        self._inboxes.setdefault(node, asyncio.Queue())
        return MockIo(self, node)

    def link(
        self,
        a_node: str,
        a_if: str,
        b_node: str,
        b_if: str,
        latency_ms: float = 0.0,
    ) -> _MockLink:
        lk = _MockLink(a=(a_node, a_if), b=(b_node, b_if), latency_ms=latency_ms)
        self._links.append(lk)
        return lk

    def set_link(self, a_node: str, a_if: str, up: bool) -> None:
        """Partition control: take every link touching (node, if) up/down."""
        for lk in self._links:
            if (a_node, a_if) in (lk.a, lk.b):
                lk.up = up

    def drop_node(self, node: str) -> None:
        """Forget a node's inbox (emulated crash): in-flight and future
        packets to it are discarded until `io_for` recreates the inbox,
        so a restarted node never replays its dead incarnation's
        backlog."""
        self._inboxes.pop(node, None)

    def _deliver(self, src_node: str, src_if: str, payload: bytes) -> None:
        for lk in self._links:
            if not lk.up:
                continue
            if lk.a == (src_node, src_if):
                dst_node, dst_if = lk.b
            elif lk.b == (src_node, src_if):
                dst_node, dst_if = lk.a
            else:
                continue
            inbox = self._inboxes.get(dst_node)
            if inbox is None:
                continue
            self._enqueue(lk, dst_node, dst_if, payload, inbox)

    def _enqueue(
        self,
        lk: _MockLink,
        dst_node: str,
        dst_if: str,
        payload: bytes,
        inbox: asyncio.Queue,
    ) -> None:
        """Final delivery of one packet onto the destination inbox — the
        per-delivery seam ChaosIoHub overrides to drop/delay/duplicate
        (emulator/chaos.py)."""
        if lk.latency_ms > 0:
            asyncio.get_event_loop().call_later(
                lk.latency_ms / 1e3, inbox.put_nowait, (dst_if, payload)
            )
        else:
            inbox.put_nowait((dst_if, payload))


class MockIo:
    def __init__(self, hub: MockIoHub, node: str):
        self._hub = hub
        self.node = node

    async def recv(self) -> tuple[str, bytes]:
        return await self._hub._inboxes[self.node].get()

    async def send(self, if_name: str, payload: bytes) -> None:
        self._hub._deliver(self.node, if_name, payload)

    def close(self) -> None:
        pass


class UdpIoProvider:
    """Real UDP I/O: one socket per interface, link-local multicast.

    reference: IoProvider † sendmsg/recvmsg on ff02::1. For the emulated
    deployments in this rebuild (no per-interface netns), interfaces map
    to localhost UDP ports: interface registration supplies
    (local_port, peer_addr) pairs.
    """

    def __init__(self):
        self._transports: dict[str, asyncio.DatagramTransport] = {}
        self._peers: dict[str, tuple[str, int]] = {}
        self._rx: asyncio.Queue = asyncio.Queue()

    async def add_interface(
        self, if_name: str, local_port: int = 0,
        peer: tuple[str, int] | None = None,
    ) -> int:
        loop = asyncio.get_event_loop()
        rx = self._rx

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                rx.put_nowait((if_name, data))

        transport, _ = await loop.create_datagram_endpoint(
            Proto, local_addr=("127.0.0.1", local_port)
        )
        self._transports[if_name] = transport
        if peer:
            self._peers[if_name] = peer
        return transport.get_extra_info("sockname")[1]

    def set_peer(self, if_name: str, peer: tuple[str, int]) -> None:
        self._peers[if_name] = peer

    async def recv(self) -> tuple[str, bytes]:
        return await self._rx.get()

    async def send(self, if_name: str, payload: bytes) -> None:
        t = self._transports.get(if_name)
        peer = self._peers.get(if_name)
        if t is not None and peer is not None:
            t.sendto(payload, peer)

    def close(self) -> None:
        for t in self._transports.values():
            t.close()
        self._transports.clear()
