"""Packet I/O seam for Spark.

reference: openr/spark/IoProvider.h † (real UDP multicast) and
openr/spark/tests/MockIoProvider.h † (in-process hub with configurable
per-link latency and partitions — the seam that makes the whole neighbor
FSM testable without sockets).
"""

from __future__ import annotations

import asyncio
import socket
import struct
from dataclasses import dataclass
from typing import Protocol

from openr_tpu.common.constants import SPARK_INBOX_MAXSIZE, SPARK_MCAST_PORT
from openr_tpu.messaging import RQueue


class IoProvider(Protocol):
    async def recv(self) -> tuple[str, bytes]:
        """Returns (local_if_name, payload)."""
        ...

    async def send(self, if_name: str, payload: bytes) -> None: ...

    def close(self) -> None: ...


@dataclass
class _MockLink:
    a: tuple[str, str]  # (node, if)
    b: tuple[str, str]
    latency_ms: float = 0.0
    up: bool = True


class MockIoHub:
    """In-process packet fabric: point-to-point links between (node, if)
    endpoints with latency and up/down control.

    reference: MockIoProvider † — connectedPairs + latency + thread pump;
    here the pump is the event loop itself.
    """

    # per-node inbox bound: a partitioned or stalled receiver sheds its
    # OLDEST packets (hellos are periodic and self-superseding, so the
    # newest state always survives) instead of growing RAM without limit
    INBOX_MAX = SPARK_INBOX_MAXSIZE

    def __init__(self, inbox_max: int | None = None):
        self._links: list[_MockLink] = []
        self._inboxes: dict[str, RQueue] = {}
        self.inbox_max = self.INBOX_MAX if inbox_max is None else inbox_max
        self.inbox_drops: dict[str, int] = {}  # dst node -> dropped packets
        self._counters: dict[str, object] = {}  # dst node -> Counters

    def io_for(self, node: str) -> "MockIo":
        # messaging-seam queue (OR004): the bound + shed-oldest policy
        # live in the queue itself; _inbox_put keeps the per-node drop
        # accounting (`spark.inbox_dropped`) at the shed point
        self._inboxes.setdefault(
            node,
            RQueue(
                name=f"spark.inbox.{node}",
                maxsize=self.inbox_max,
                policy="shed_oldest",
            ),
        )
        return MockIo(self, node)

    def set_counters(self, node: str, counters) -> None:
        """Attach a node's Counters registry so inbox drops surface as
        that node's `spark.inbox_dropped` counter (the hub exists before
        the nodes do, so registration is a second step)."""
        self._counters[node] = counters

    def link(
        self,
        a_node: str,
        a_if: str,
        b_node: str,
        b_if: str,
        latency_ms: float = 0.0,
    ) -> _MockLink:
        lk = _MockLink(a=(a_node, a_if), b=(b_node, b_if), latency_ms=latency_ms)
        self._links.append(lk)
        return lk

    def set_link(self, a_node: str, a_if: str, up: bool) -> None:
        """Partition control: take every link touching (node, if) up/down."""
        for lk in self._links:
            if (a_node, a_if) in (lk.a, lk.b):
                lk.up = up

    def drop_node(self, node: str) -> None:
        """Forget a node's inbox (emulated crash): in-flight and future
        packets to it are discarded until `io_for` recreates the inbox,
        so a restarted node never replays its dead incarnation's
        backlog."""
        self._inboxes.pop(node, None)

    def _deliver(self, src_node: str, src_if: str, payload: bytes) -> None:
        for lk in self._links:
            if not lk.up:
                continue
            if lk.a == (src_node, src_if):
                dst_node, dst_if = lk.b
            elif lk.b == (src_node, src_if):
                dst_node, dst_if = lk.a
            else:
                continue
            inbox = self._inboxes.get(dst_node)
            if inbox is None:
                continue
            self._enqueue(lk, dst_node, dst_if, payload, inbox)

    def _enqueue(
        self,
        lk: _MockLink,
        dst_node: str,
        dst_if: str,
        payload: bytes,
        inbox: RQueue,
    ) -> None:
        """Final delivery of one packet onto the destination inbox — the
        per-delivery seam ChaosIoHub overrides to drop/delay/duplicate
        (emulator/chaos.py)."""
        if lk.latency_ms > 0:
            asyncio.get_event_loop().call_later(
                lk.latency_ms / 1e3, self._inbox_put, dst_node, dst_if, payload
            )
        else:
            self._inbox_put(dst_node, dst_if, payload)

    def _inbox_put(self, dst_node: str, dst_if: str, payload: bytes) -> None:
        """Bounded inbox append (re-resolving the inbox, so a packet
        delayed past a crash is discarded with the dead incarnation).
        At the bound the oldest packet is shed and counted."""
        inbox = self._inboxes.get(dst_node)
        if inbox is None:
            return
        if inbox.full:
            # the RQueue sheds its own oldest at the bound; this branch
            # just keeps the per-node drop accounting
            self.inbox_drops[dst_node] = self.inbox_drops.get(dst_node, 0) + 1
            c = self._counters.get(dst_node)
            if c is not None:
                c.increment("spark.inbox_dropped")
        inbox.put_nowait((dst_if, payload))


class MockIo:
    def __init__(self, hub: MockIoHub, node: str):
        self._hub = hub
        self.node = node

    def attach_counters(self, counters) -> None:
        """Spark hands its node's Counters down at construction so hub
        inbox drops surface as `spark.inbox_dropped` (same seam on every
        IoProvider)."""
        self._hub.set_counters(self.node, counters)

    async def recv(self) -> tuple[str, bytes]:
        return await self._hub._inboxes[self.node].get()

    async def send(self, if_name: str, payload: bytes) -> None:
        self._hub._deliver(self.node, if_name, payload)

    def close(self) -> None:
        pass


class UdpIoProvider:
    """Real UDP I/O: one socket per interface, link-local multicast.

    reference: IoProvider † sendmsg/recvmsg on ff02::1. For the emulated
    deployments in this rebuild (no per-interface netns), interfaces map
    to localhost UDP ports: interface registration supplies
    (local_port, peer_addr) pairs.
    """

    def __init__(self, inbox_max: int = SPARK_INBOX_MAXSIZE):
        self._transports: dict[str, asyncio.DatagramTransport] = {}
        self._peers: dict[str, tuple[str, int]] = {}
        # messaging-seam rx queue (OR004): bounded shed-oldest
        self._rx: RQueue = RQueue(
            name="spark.udp.rx", maxsize=inbox_max, policy="shed_oldest"
        )
        self.inbox_max = inbox_max
        self.rx_dropped = 0  # oldest-shed count at the rx bound
        self._counters = None
        # socket-level chaos seam: interfaces in this set neither send
        # nor deliver received datagrams — the multi-process analogue of
        # MockIoHub.set_link(up=False), installed over ctrl
        # (chaos_set_drop) by the cluster supervisor to cut a REAL UDP
        # path. Dropping rx as well as tx keeps partitions symmetric
        # even when only one side got the rule
        self._dropped_ifs: set[str] = set()

    def set_drop(self, if_name: str, dropped: bool) -> None:
        """Install/remove a per-interface drop rule (partition chaos)."""
        if dropped:
            self._dropped_ifs.add(if_name)
        else:
            self._dropped_ifs.discard(if_name)

    def clear_drops(self) -> None:
        self._dropped_ifs.clear()

    def drop_rules(self) -> list[str]:
        return sorted(self._dropped_ifs)

    def attach_counters(self, counters) -> None:
        """Export rx sheds as `spark.inbox_dropped` (wired by Spark)."""
        self._counters = counters

    async def add_interface(
        self, if_name: str, local_port: int = 0,
        peer: tuple[str, int] | None = None,
    ) -> int:
        loop = asyncio.get_event_loop()
        rx = self._rx

        provider = self

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                if if_name in provider._dropped_ifs:
                    # partitioned interface: discard at the socket edge,
                    # exactly where a real filtered link loses packets
                    if provider._counters is not None:
                        provider._counters.increment("spark.chaos_dropped")
                    return
                # bounded rx: the RQueue sheds its oldest at the bound
                # (periodic Spark traffic is self-superseding); count
                # the drop here where the node identity is known
                if rx.full:
                    provider.rx_dropped += 1
                    if provider._counters is not None:
                        provider._counters.increment("spark.inbox_dropped")
                rx.put_nowait((if_name, data))

        transport, _ = await loop.create_datagram_endpoint(
            Proto, local_addr=("127.0.0.1", local_port)
        )
        self._transports[if_name] = transport
        if peer:
            self._peers[if_name] = peer
        return transport.get_extra_info("sockname")[1]

    def set_peer(self, if_name: str, peer: tuple[str, int]) -> None:
        self._peers[if_name] = peer

    async def recv(self) -> tuple[str, bytes]:
        return await self._rx.get()

    async def send(self, if_name: str, payload: bytes) -> None:
        if if_name in self._dropped_ifs:
            if self._counters is not None:
                self._counters.increment("spark.chaos_dropped")
            return
        t = self._transports.get(if_name)
        peer = self._peers.get(if_name)
        if t is not None and peer is not None:
            t.sendto(payload, peer)

    def close(self) -> None:
        for t in self._transports.values():
            t.close()
        self._transports.clear()
