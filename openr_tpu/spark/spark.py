"""The Spark module: per-(interface, neighbor) discovery FSM.

reference: openr/spark/Spark.{h,cpp} † — state machine
IDLE → WARM → NEGOTIATE → ESTABLISHED (+ RESTART for graceful restart):

  * hello (multicast, periodic; fast-init cadence until first neighbor
    response) carries the sender's heard-neighbor map with timestamps;
    seeing *our own name* in a neighbor's hello proves bidirectional
    reachability → NEGOTIATE.
  * handshake (unicast-in-spirit) negotiates area + exchanges transport
    endpoints (KvStore port), hold times, and the neighbor's label.
  * heartbeats maintain liveness; hold-timer expiry → NEIGHBOR_DOWN.
  * RTT from hello timestamp echo (reference: Spark RTT measurement via
    sent/recv timestamps in hello †).
  * graceful restart: a neighbor's hello with restarting flag →
    NEIGHBOR_RESTARTING; hold adjacency until gr_hold_time; fresh hellos
    → NEIGHBOR_RESTARTED (reference: Spark GR handshake †).
"""

from __future__ import annotations

import enum
import logging
import time
from dataclasses import dataclass, field

from openr_tpu.common.eventbase import OpenrModule
from openr_tpu.config import Config
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.monitor import perf
from openr_tpu.types.events import (
    NeighborEvent,
    NeighborEventType,
    NeighborInfo,
)
from openr_tpu.types.serde import (
    from_wire_auto,
    register_wire_types,
    to_wire,
    to_wire_bin,
)

log = logging.getLogger(__name__)


class SparkNeighborState(enum.IntEnum):
    """reference: SparkNeighState †."""

    IDLE = 0
    WARM = 1
    NEGOTIATE = 2
    ESTABLISHED = 3
    RESTART = 4


@dataclass
class HelloMsg:
    """reference: SparkHelloMsg in Types.thrift †."""

    node_name: str
    if_name: str
    seq: int
    # neighbors I can hear on this interface: name -> [their_seq,
    # their_sent_ts_us echoed back verbatim, my_turnaround_lag_us]
    # (bidirectional check + NTP-free RTT: the echo is on the receiver's
    # own clock; the lag is a duration, clock-independent)
    heard: dict[str, tuple[int, int, int]] = field(default_factory=dict)
    sent_ts_us: int = 0
    restarting: bool = False
    fastinit: bool = False


@dataclass
class HandshakeMsg:
    """reference: SparkHandshakeMsg †."""

    node_name: str
    if_name: str
    area: str
    hold_time_ms: int
    gr_time_ms: int
    kvstore_port: int
    ctrl_port: int
    endpoint_host: str = ""
    label: int = 0
    # set when the sender has already accepted us (stops retransmits)
    is_ack: bool = False


@dataclass
class HeartbeatMsg:
    """reference: SparkHeartbeatMsg †."""

    node_name: str
    if_name: str
    seq: int
    hold_time_ms: int


@dataclass
class SparkPacket:
    hello: HelloMsg | None = None
    handshake: HandshakeMsg | None = None
    heartbeat: HeartbeatMsg | None = None


@dataclass
class _Neighbor:
    node_name: str
    local_if: str
    state: SparkNeighborState = SparkNeighborState.IDLE
    remote_if: str = ""
    area: str = "0"
    hold_time_ms: int = 0
    gr_time_ms: int = 0
    kvstore_port: int = 0
    ctrl_port: int = 0
    endpoint_host: str = ""
    label: int = 0
    rtt_us: int = 0
    last_heard: float = 0.0
    last_seq: int = 0
    handshake_done: bool = False
    # RTT measurement state: the neighbor's latest hello sent-timestamp
    # (THEIR clock, echoed back verbatim) and when we received it (OUR
    # monotonic clock), so our next hello can report our turnaround lag.
    last_their_sent_us: int = 0
    last_recv_mono_us: int = 0


class Spark(OpenrModule):
    def __init__(
        self,
        config: Config,
        io,  # IoProvider
        neighbor_events: ReplicateQueue,
        kvstore_port: int = 0,
        ctrl_port: int = 0,
        endpoint_host: str = "127.0.0.1",
        counters=None,
    ):
        super().__init__(f"{config.node_name}.spark", counters=counters)
        self.config = config
        self.node_name = config.node_name
        self.io = io
        self.events = neighbor_events
        self.kvstore_port = kvstore_port
        self.ctrl_port = ctrl_port
        self.endpoint_host = endpoint_host
        self.interfaces: set[str] = set()
        # tx wire codec (docs/Wire.md): compact binary frames by
        # default; "json" keeps legacy canonical-JSON packets for
        # mixed-version interop. The RX path sniffs every packet's
        # first byte (from_wire_auto), so either codec is always
        # accepted regardless of this knob.
        self._encode = (
            to_wire_bin
            if config.node.spark.wire_codec == "bin"
            else to_wire
        )
        # inbox-shed visibility: every IoProvider that bounds its rx
        # queue exports drops through this node's counters
        attach = getattr(io, "attach_counters", None)
        if attach is not None and counters is not None:
            attach(counters)
        # (if_name, neighbor_name) -> state
        self.neighbors: dict[tuple[str, str], _Neighbor] = {}
        self.seq = 0
        self._fastinit_until: dict[str, float] = {}

    # ---------------------------------------------------------------- setup

    def add_interface(self, if_name: str) -> None:
        """Start discovery on an interface (from LinkMonitor).

        reference: Spark interface updates from LinkMonitor via
        InterfaceDb †; fast-init hello cadence on new interfaces."""
        if if_name in self.interfaces:
            return
        self.interfaces.add(if_name)
        cfg = self.config.node.spark
        self._fastinit_until[if_name] = (
            time.monotonic() + 4 * cfg.hello_time_ms / 1e3
        )

    def remove_interface(self, if_name: str) -> None:
        self.interfaces.discard(if_name)
        for key in [k for k in self.neighbors if k[0] == if_name]:
            self._neighbor_down(self.neighbors[key], "interface removed")

    # ----------------------------------------------------------------- main

    async def main(self) -> None:
        cfg = self.config.node.spark
        self.spawn(self._rx_loop(), name=f"{self.name}.rx")
        self.run_every(
            cfg.fastinit_hello_time_ms / 1e3,
            self._hello_tick,
            name=f"{self.name}.hello",
        )
        self.run_every(
            cfg.keepalive_time_ms / 1e3,
            self._heartbeat_tick,
            name=f"{self.name}.hb",
        )
        self.run_every(
            cfg.keepalive_time_ms / 1e3 / 2,
            self._hold_timer_tick,
            name=f"{self.name}.hold",
        )

    async def cleanup(self) -> None:
        self.io.close()

    # ------------------------------------------------------------------- tx

    _last_slow_hello: float = 0.0

    async def _hello_tick(self) -> None:
        """Hellos at fast-init cadence on fresh interfaces, normal cadence
        otherwise (the timer runs at fastinit rate; slow interfaces skip)."""
        cfg = self.config.node.spark
        now = time.monotonic()
        slow_due = now - self._last_slow_hello >= cfg.hello_time_ms / 1e3
        if slow_due:
            self._last_slow_hello = now
        self.seq += 1
        for if_name in list(self.interfaces):
            fast = now < self._fastinit_until.get(if_name, 0)
            if not (fast or slow_due):
                continue
            heard = {}
            now_us = int(now * 1e6)
            for (ifn, nname), nb in self.neighbors.items():
                if ifn != if_name or nb.state == SparkNeighborState.IDLE:
                    continue
                lag_us = now_us - nb.last_recv_mono_us if nb.last_recv_mono_us else 0
                heard[nname] = (nb.last_seq, nb.last_their_sent_us, lag_us)
            pkt = SparkPacket(
                hello=HelloMsg(
                    node_name=self.node_name,
                    if_name=if_name,
                    seq=self.seq,
                    heard=heard,
                    sent_ts_us=int(now * 1e6),
                    fastinit=fast,
                )
            )
            await self.io.send(if_name, self._encode(pkt))
            if self.counters is not None:
                self.counters.increment("spark.hello_sent")

    async def announce_restart(self) -> None:
        """Tell every neighbor we are about to gracefully restart
        (reference: Spark GR † — the departing instance floods a hello
        with restarting=true so peers hold the adjacency for gr_time
        instead of withdrawing on hold-timer expiry). Called by the
        emulator's Cluster.crash_node(graceful=True) before stop.

        These are the instance's last words: the interface set is
        cleared afterwards so a hello tick racing the (yielding) module
        teardown can't send a restarting=False hello that would cancel
        the GR hold on the receivers."""
        self.seq += 1
        now_us = int(time.monotonic() * 1e6)
        interfaces, self.interfaces = list(self.interfaces), set()
        for if_name in interfaces:
            pkt = SparkPacket(
                hello=HelloMsg(
                    node_name=self.node_name,
                    if_name=if_name,
                    seq=self.seq,
                    sent_ts_us=now_us,
                    restarting=True,
                )
            )
            await self.io.send(if_name, self._encode(pkt))
            if self.counters is not None:
                self.counters.increment("spark.restart_announced")

    async def _heartbeat_tick(self) -> None:
        cfg = self.config.node.spark
        sent_ifs = set()
        for (if_name, _), nb in self.neighbors.items():
            if nb.state != SparkNeighborState.ESTABLISHED:
                continue
            if if_name in sent_ifs:
                continue
            sent_ifs.add(if_name)
            self.seq += 1
            pkt = SparkPacket(
                heartbeat=HeartbeatMsg(
                    node_name=self.node_name,
                    if_name=if_name,
                    seq=self.seq,
                    hold_time_ms=cfg.hold_time_ms,
                )
            )
            await self.io.send(if_name, self._encode(pkt))
            if self.counters is not None:
                self.counters.increment("spark.heartbeat_sent")

    async def _send_handshake(self, nb: _Neighbor, is_ack: bool) -> None:
        cfg = self.config.node.spark
        pkt = SparkPacket(
            handshake=HandshakeMsg(
                node_name=self.node_name,
                if_name=nb.local_if,
                area=self._negotiate_area(nb.node_name),
                hold_time_ms=cfg.hold_time_ms,
                gr_time_ms=cfg.graceful_restart_time_ms,
                kvstore_port=self.kvstore_port,
                ctrl_port=self.ctrl_port,
                endpoint_host=self.endpoint_host,
                label=0,
                is_ack=is_ack,
            )
        )
        await self.io.send(nb.local_if, self._encode(pkt))
        if self.counters is not None:
            self.counters.increment("spark.handshake_sent")

    def _negotiate_area(self, neighbor_name: str) -> str:
        """reference: Spark per-area negotiation via AreaConfig neighbor
        regexes † — first matching area wins."""
        import re

        for area in self.config.areas:
            for pattern in area.neighbor_regexes:
                if re.fullmatch(pattern, neighbor_name):
                    return area.area_id
        return self.config.areas[0].area_id

    # ------------------------------------------------------------------- rx

    async def _rx_loop(self) -> None:
        while True:
            if_name, payload = await self.io.recv()
            if if_name not in self.interfaces:
                continue
            try:
                pkt = from_wire_auto(payload, SparkPacket)
            except Exception:  # noqa: BLE001
                if self.counters is not None:
                    self.counters.increment("spark.bad_packets")
                continue
            if pkt.hello is not None:
                self._on_hello(if_name, pkt.hello)
            elif pkt.handshake is not None:
                await self._on_handshake(if_name, pkt.handshake)
            elif pkt.heartbeat is not None:
                self._on_heartbeat(if_name, pkt.heartbeat)

    def _nb(self, if_name: str, node: str) -> _Neighbor:
        key = (if_name, node)
        if key not in self.neighbors:
            self.neighbors[key] = _Neighbor(node_name=node, local_if=if_name)
        return self.neighbors[key]

    def _on_hello(self, if_name: str, hello: HelloMsg) -> None:
        if hello.node_name == self.node_name:
            return
        nb = self._nb(if_name, hello.node_name)
        now = time.monotonic()
        nb.last_heard = now
        nb.last_seq = hello.seq
        nb.remote_if = hello.if_name
        if self.counters is not None:
            self.counters.increment("spark.hello_recv")

        was_established = nb.state in (
            SparkNeighborState.ESTABLISHED,
            SparkNeighborState.RESTART,
        )
        if hello.restarting:
            if nb.state == SparkNeighborState.ESTABLISHED:
                nb.state = SparkNeighborState.RESTART
                # the restarting instance's transport endpoints die with
                # it: a REAL restart comes back on fresh (ephemeral)
                # ports, so the cached handshake is void — re-establish
                # only after the new instance handshakes again
                nb.handshake_done = False
                self._emit(NeighborEventType.NEIGHBOR_RESTARTING, nb)
            return

        now_us = int(now * 1e6)
        nb.last_their_sent_us = hello.sent_ts_us
        nb.last_recv_mono_us = now_us

        heard_us = self.node_name in hello.heard
        if nb.state == SparkNeighborState.ESTABLISHED and not heard_us:
            # an ESTABLISHED neighbor always carries us in its heard map
            # (entries are only dropped when the neighbor object is), so
            # its absence means the sender is a FRESH instance after a
            # non-graceful restart (SIGKILL/re-exec — it never announced,
            # so we never entered RESTART) or it expired us via its own
            # hold timer. Its transport endpoints may have changed with
            # it: tear down and re-negotiate from scratch so the fresh
            # handshake re-learns the new kvstore/ctrl ports (exercised
            # with real SIGKILLs by ProcCluster, docs/Emulator.md).
            self._neighbor_down(nb, "established neighbor no longer hears us")
            if self.counters is not None:
                self.counters.increment("spark.nongr_restarts_detected")
            nb = self._nb(if_name, hello.node_name)
            nb.last_heard = now
            nb.last_seq = hello.seq
            nb.remote_if = hello.if_name
            nb.last_their_sent_us = hello.sent_ts_us
            nb.last_recv_mono_us = now_us
        if nb.state == SparkNeighborState.IDLE:
            nb.state = SparkNeighborState.WARM
        if heard_us:
            # RTT (reference: Spark::processHelloMsg RTT computation †):
            # the neighbor echoed OUR sent timestamp plus its turnaround
            # lag; both endpoints of the subtraction are our clock.
            _seq, echoed_sent_us, their_lag_us = hello.heard[self.node_name]
            if echoed_sent_us > 0 and their_lag_us >= 0:
                raw_rtt = now_us - echoed_sent_us - their_lag_us
                if raw_rtt > 0:
                    self._update_rtt(nb, raw_rtt)
            if nb.state == SparkNeighborState.WARM:
                nb.state = SparkNeighborState.NEGOTIATE
                self.spawn(self._send_handshake(nb, is_ack=False))
            elif (
                nb.state == SparkNeighborState.RESTART
                and nb.handshake_done
            ):
                # neighbor came back from graceful restart AND its new
                # instance has re-handshaked (fresh kvstore/ctrl ports).
                # Re-establishing on the hello alone would advertise the
                # pre-restart endpoints — a peer that no longer exists
                # when the restart was a real process re-exec.
                nb.state = SparkNeighborState.ESTABLISHED
                self._emit(NeighborEventType.NEIGHBOR_RESTARTED, nb)

    # reference: Spark uses a step-detector on measured RTTs †; an EWMA +
    # 10% emit-threshold gives the same "ignore jitter, report real shifts"
    # behavior with less machinery.
    RTT_EWMA_ALPHA = 0.5
    RTT_CHANGE_FRACTION = 0.1

    def _update_rtt(self, nb: _Neighbor, raw_rtt_us: int) -> None:
        old = nb.rtt_us
        nb.rtt_us = (
            raw_rtt_us
            if old == 0
            else int(
                self.RTT_EWMA_ALPHA * raw_rtt_us
                + (1 - self.RTT_EWMA_ALPHA) * old
            )
        )
        if (
            nb.state == SparkNeighborState.ESTABLISHED
            and abs(nb.rtt_us - old) > self.RTT_CHANGE_FRACTION * max(old, 1)
        ):
            self._emit(NeighborEventType.NEIGHBOR_RTT_CHANGE, nb)

    async def _on_handshake(self, if_name: str, hs: HandshakeMsg) -> None:
        if hs.node_name == self.node_name:
            return
        nb = self._nb(if_name, hs.node_name)
        now = time.monotonic()
        nb.last_heard = now
        nb.area = hs.area
        nb.hold_time_ms = hs.hold_time_ms
        nb.gr_time_ms = hs.gr_time_ms
        nb.kvstore_port = hs.kvstore_port
        nb.ctrl_port = hs.ctrl_port
        nb.endpoint_host = hs.endpoint_host
        nb.label = hs.label
        if self.counters is not None:
            self.counters.increment("spark.handshake_recv")
        if not hs.is_ack:
            await self._send_handshake(nb, is_ack=True)
        if nb.state in (SparkNeighborState.WARM, SparkNeighborState.NEGOTIATE):
            nb.state = SparkNeighborState.ESTABLISHED
            nb.handshake_done = True
            self._emit(NeighborEventType.NEIGHBOR_UP, nb)
        elif nb.state == SparkNeighborState.RESTART:
            # the restarted instance is a fresh FSM, so it ALWAYS
            # handshakes anew — this is the moment its new transport
            # endpoints are known, so re-establish HERE (reference:
            # Spark GR handshake †), not on the hello that merely
            # proves it is alive again
            nb.state = SparkNeighborState.ESTABLISHED
            nb.handshake_done = True
            self._emit(NeighborEventType.NEIGHBOR_RESTARTED, nb)
        elif nb.state == SparkNeighborState.ESTABLISHED and not hs.is_ack:
            # a steady-state peer never re-handshakes (handshakes are
            # sent only from NEGOTIATE), so an unsolicited handshake
            # from an ESTABLISHED neighbor is a fresh FSM after a
            # restart we never got the GR announcement for (SIGKILL /
            # re-exec — often the only observable sign: the survivor's
            # own stale heard entry lets the new instance skip straight
            # to NEGOTIATE, so no empty-heard hello ever arrives). The
            # endpoint fields above just took its NEW kvstore/ctrl
            # ports; re-emit so consumers re-peer instead of flooding
            # the dead endpoint forever (found by ProcCluster hard
            # kills, docs/Emulator.md). A duplicate NEGOTIATE-phase
            # handshake that lost the race to our ack lands here too —
            # the re-emitted endpoints are then unchanged and the
            # consumers' re-peer is a no-op.
            nb.handshake_done = True
            if self.counters is not None:
                self.counters.increment("spark.nongr_restarts_detected")
            self._emit(NeighborEventType.NEIGHBOR_RESTARTED, nb)

    def _on_heartbeat(self, if_name: str, hb: HeartbeatMsg) -> None:
        if hb.node_name == self.node_name:
            return
        key = (if_name, hb.node_name)
        nb = self.neighbors.get(key)
        if nb is None:
            return
        nb.last_heard = time.monotonic()
        nb.hold_time_ms = hb.hold_time_ms or nb.hold_time_ms

    # ------------------------------------------------------------ liveness

    def _hold_timer_tick(self) -> None:
        cfg = self.config.node.spark
        now = time.monotonic()
        for key in list(self.neighbors):
            nb = self.neighbors[key]
            if nb.state == SparkNeighborState.IDLE:
                continue
            hold_s = (nb.hold_time_ms or cfg.hold_time_ms) / 1e3
            if nb.state == SparkNeighborState.RESTART:
                hold_s = (nb.gr_time_ms or cfg.graceful_restart_time_ms) / 1e3
            if now - nb.last_heard > hold_s:
                self._neighbor_down(nb, "hold timer expired")

    def _neighbor_down(self, nb: _Neighbor, why: str) -> None:
        was_up = nb.state in (
            SparkNeighborState.ESTABLISHED,
            SparkNeighborState.RESTART,
        )
        log.debug("%s: neighbor %s down (%s)", self.name, nb.node_name, why)
        self.neighbors.pop((nb.local_if, nb.node_name), None)
        if was_up:
            self._emit(NeighborEventType.NEIGHBOR_DOWN, nb)
            if self.counters is not None:
                self.counters.increment("spark.neighbor_down")

    # -------------------------------------------------------------- events

    def _emit(self, etype: NeighborEventType, nb: _Neighbor) -> None:
        self.events.push(
            NeighborEvent(
                type=etype,
                perf_events=perf.PerfEvents.start(
                    perf.NEIGHBOR_EVENT, node=self.node_name
                ),
                info=NeighborInfo(
                    node_name=nb.node_name,
                    local_if=nb.local_if,
                    remote_if=nb.remote_if,
                    area=nb.area,
                    kvstore_port=nb.kvstore_port,
                    ctrl_port=nb.ctrl_port,
                    hold_time_ms=nb.hold_time_ms,
                    gr_time_ms=nb.gr_time_ms,
                    rtt_us=nb.rtt_us,
                    label=nb.label,
                    endpoint_host=nb.endpoint_host,
                ),
            )
        )
        if self.counters is not None and etype == NeighborEventType.NEIGHBOR_UP:
            self.counters.increment("spark.neighbor_up")


# wire-schema lock registration: the four UDP discovery frame types
register_wire_types(HelloMsg, HandshakeMsg, HeartbeatMsg, SparkPacket)
