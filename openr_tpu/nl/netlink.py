"""ctypes bindings for native/nl (libopenr_nl.so).

reference: openr/nl/NetlinkProtocolSocket.h † public API — route add/del
(v4/v6 ECMP/UCMP + MPLS), link/address dumps, event subscription. The
blocking native calls are small and fast; async callers run them through
``asyncio.to_thread`` (the platform module does).

Struct layouts here MUST mirror native/nl/netlink.hpp (#pragma pack(1)).
"""

from __future__ import annotations

import ctypes
import ipaddress
import json
import os
import socket as pysocket
import subprocess
from dataclasses import dataclass, field
from pathlib import Path

AF_MPLS = 28
MAX_NEXTHOPS = 32
MAX_LABELS = 8
RTPROT_OPENR = 99

# RTMGRP_* subscription bits (linux/rtnetlink.h)
RTMGRP_LINK = 1
RTMGRP_IPV4_IFADDR = 0x10
RTMGRP_IPV6_IFADDR = 0x100


class NetlinkError(OSError):
    pass


class _CNexthop(ctypes.Structure):
    _pack_ = 1
    _fields_ = [
        ("af", ctypes.c_int32),
        ("gateway", ctypes.c_uint8 * 16),
        ("ifindex", ctypes.c_int32),
        ("weight", ctypes.c_uint32),
        ("num_labels", ctypes.c_uint32),
        ("labels", ctypes.c_uint32 * MAX_LABELS),
    ]


class _CRoute(ctypes.Structure):
    _pack_ = 1
    _fields_ = [
        ("family", ctypes.c_int32),
        ("dst", ctypes.c_uint8 * 16),
        ("dst_len", ctypes.c_uint32),
        ("mpls_label", ctypes.c_uint32),
        ("table", ctypes.c_uint32),
        ("protocol", ctypes.c_uint32),
        ("priority", ctypes.c_uint32),
        ("num_nexthops", ctypes.c_uint32),
        ("nh", _CNexthop * MAX_NEXTHOPS),
    ]


@dataclass
class Nexthop:
    gateway: str | None = None  # v4/v6 literal
    ifindex: int = 0
    weight: int = 1
    labels: tuple[int, ...] = ()  # MPLS push stack, outermost first


@dataclass
class NetlinkRoute:
    """One unicast or MPLS route (reference: openr/nl route structs †)."""

    dst: str | None = None  # "10.0.0.0/24" / "fc00::/64"; None for MPLS
    mpls_label: int | None = None  # incoming label (AF_MPLS route)
    table: int = 254  # RT_TABLE_MAIN
    protocol: int = RTPROT_OPENR
    priority: int = 0
    nexthops: list[Nexthop] = field(default_factory=list)

    @property
    def family(self) -> int:
        if self.mpls_label is not None:
            return AF_MPLS
        net = ipaddress.ip_network(self.dst, strict=False)
        return pysocket.AF_INET if net.version == 4 else pysocket.AF_INET6

    def to_c(self) -> _CRoute:
        c = _CRoute()
        c.family = self.family
        c.table = self.table
        c.protocol = self.protocol
        c.priority = self.priority
        if self.mpls_label is not None:
            c.mpls_label = self.mpls_label
        else:
            net = ipaddress.ip_network(self.dst, strict=False)
            packed = net.network_address.packed
            ctypes.memmove(c.dst, packed, len(packed))
            c.dst_len = net.prefixlen
        if len(self.nexthops) > MAX_NEXTHOPS:
            raise NetlinkError(
                f"too many nexthops: {len(self.nexthops)} > {MAX_NEXTHOPS}"
            )
        c.num_nexthops = len(self.nexthops)
        for i, nh in enumerate(self.nexthops):
            cn = c.nh[i]
            cn.ifindex = nh.ifindex
            cn.weight = max(1, nh.weight)
            if nh.gateway:
                addr = ipaddress.ip_address(nh.gateway)
                cn.af = (
                    pysocket.AF_INET if addr.version == 4
                    else pysocket.AF_INET6
                )
                ctypes.memmove(cn.gateway, addr.packed, len(addr.packed))
            if len(nh.labels) > MAX_LABELS:
                raise NetlinkError(f"label stack too deep: {nh.labels}")
            cn.num_labels = len(nh.labels)
            for j, lbl in enumerate(nh.labels):
                cn.labels[j] = lbl
        return c

    @staticmethod
    def from_json(d: dict) -> "NetlinkRoute":
        return NetlinkRoute(
            dst=d.get("dst"),
            mpls_label=d.get("mpls_label"),
            table=d.get("table", 254),
            protocol=d.get("protocol", RTPROT_OPENR),
            priority=d.get("priority", 0),
            nexthops=[
                Nexthop(
                    gateway=n.get("gateway"),
                    ifindex=n.get("ifindex", 0),
                    weight=n.get("weight", 1),
                    labels=tuple(n.get("labels", ())),
                )
                for n in d.get("nexthops", ())
            ],
        )


# ---- library loading ------------------------------------------------------

_LIB_PATHS = [
    Path(__file__).resolve().parents[2] / "native" / "build" / "libopenr_nl.so",
]
_lib: ctypes.CDLL | None = None
_lib_err: str | None = None


def _try_build() -> None:
    """Best-effort `make -C native` (dev convenience; CI prebuilds)."""
    mk = Path(__file__).resolve().parents[2] / "native"
    if (mk / "Makefile").exists():
        subprocess.run(
            ["make", "-C", str(mk)], capture_output=True, timeout=120,
            check=False,
        )


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    path = next((p for p in _LIB_PATHS if p.exists()), None)
    if path is None:
        _try_build()
        path = next((p for p in _LIB_PATHS if p.exists()), None)
    if path is None:
        _lib_err = f"libopenr_nl.so not found (tried {_LIB_PATHS})"
        return None
    lib = ctypes.CDLL(str(path))
    lib.onl_open.restype = ctypes.c_void_p
    lib.onl_open.argtypes = [ctypes.c_uint32]
    lib.onl_close.argtypes = [ctypes.c_void_p]
    lib.onl_fd.argtypes = [ctypes.c_void_p]
    lib.onl_last_error.restype = ctypes.c_char_p
    lib.onl_last_error.argtypes = [ctypes.c_void_p]
    lib.onl_route_add.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_CRoute), ctypes.c_int
    ]
    lib.onl_route_del.argtypes = [ctypes.c_void_p, ctypes.POINTER(_CRoute)]
    lib.onl_route_batch.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_CRoute), ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
    ]
    for name in ("onl_routes_dump", "onl_links_dump", "onl_addrs_dump",
                 "onl_next_events"):
        getattr(lib, name).restype = ctypes.c_void_p  # manual free
    lib.onl_routes_dump.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_uint32, ctypes.c_uint32
    ]
    lib.onl_links_dump.argtypes = [ctypes.c_void_p]
    lib.onl_addrs_dump.argtypes = [ctypes.c_void_p]
    lib.onl_next_events.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.onl_free.argtypes = [ctypes.c_void_p]
    lib.onl_build_route_nlmsg.argtypes = [
        ctypes.POINTER(_CRoute), ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
    ]
    lib.onl_parse_route_nlmsg.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.POINTER(_CRoute)
    ]
    lib.onl_abi_sizeof_route.restype = ctypes.c_uint32
    # ABI guard: struct drift between the .py and .hpp copies is a
    # memory-corruption bug — fail loudly at load time instead
    expect = ctypes.sizeof(_CRoute)
    got = lib.onl_abi_sizeof_route()
    if got != expect:
        _lib_err = f"ABI mismatch: C Route={got}B, ctypes={expect}B"
        return None
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def _json_result(lib, h, raw: int | None) -> list:
    if not raw:
        err = lib.onl_last_error(h).decode()
        raise NetlinkError(err or "netlink dump failed")
    try:
        return json.loads(ctypes.string_at(raw).decode())
    finally:
        lib.onl_free(raw)


class NetlinkSocket:
    """One rtnetlink socket (reference: NetlinkProtocolSocket †).

    Blocking; run via asyncio.to_thread from event-loop code. Pass
    `groups` (RTMGRP_* bitmask) to subscribe to link/addr events and
    drive `next_events`.
    """

    def __init__(self, groups: int = 0):
        lib = _load()
        if lib is None:
            raise NetlinkError(_lib_err or "native netlink unavailable")
        self._lib = lib
        self._h = lib.onl_open(groups)
        if not self._h:
            raise NetlinkError(lib.onl_last_error(None).decode())

    def close(self) -> None:
        if self._h:
            self._lib.onl_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _check(self, rc: int, what: str) -> None:
        if rc != 0:
            err = self._lib.onl_last_error(self._h).decode()
            raise NetlinkError(rc, f"{what}: {err or os.strerror(-rc)}")

    # ---- routes ----

    def route_add(self, route: NetlinkRoute, replace: bool = True) -> None:
        c = route.to_c()
        self._check(
            self._lib.onl_route_add(self._h, ctypes.byref(c), int(replace)),
            f"route_add {route.dst or route.mpls_label}",
        )

    def route_del(self, route: NetlinkRoute) -> None:
        c = route.to_c()
        self._check(
            self._lib.onl_route_del(self._h, ctypes.byref(c)),
            f"route_del {route.dst or route.mpls_label}",
        )

    def route_batch(
        self, routes: list[NetlinkRoute], delete: bool = False,
        replace: bool = True,
    ) -> list[int]:
        """Pipelined add/del of many routes; returns per-route 0/-errno."""
        if not routes:
            return []
        arr = (_CRoute * len(routes))(*[r.to_c() for r in routes])
        errs = (ctypes.c_int32 * len(routes))()
        self._lib.onl_route_batch(
            self._h, arr, len(routes), int(delete), int(replace), errs
        )
        return list(errs)

    def routes_dump(
        self, family: int = 0, table: int = 0, protocol: int = 0
    ) -> list[NetlinkRoute]:
        raw = self._lib.onl_routes_dump(self._h, family, table, protocol)
        return [
            NetlinkRoute.from_json(d)
            for d in _json_result(self._lib, self._h, raw)
        ]

    # ---- links / addrs / events ----

    def links_dump(self) -> list[dict]:
        return _json_result(
            self._lib, self._h, self._lib.onl_links_dump(self._h)
        )

    def addrs_dump(self) -> list[dict]:
        return _json_result(
            self._lib, self._h, self._lib.onl_addrs_dump(self._h)
        )

    def next_events(self, timeout_ms: int = 1000) -> list[dict]:
        return _json_result(
            self._lib, self._h, self._lib.onl_next_events(self._h, timeout_ms)
        )

    # ---- kernel-free serialization (tests) ----

    @staticmethod
    def build_nlmsg(
        route: NetlinkRoute, delete: bool = False, replace: bool = True
    ) -> bytes:
        lib = _load()
        if lib is None:
            raise NetlinkError(_lib_err or "native netlink unavailable")
        c = route.to_c()
        buf = (ctypes.c_uint8 * 4096)()
        n = lib.onl_build_route_nlmsg(
            ctypes.byref(c), int(delete), int(replace), buf, len(buf)
        )
        if n < 0:
            raise NetlinkError("build_nlmsg failed")
        return bytes(buf[:n])

    @staticmethod
    def parse_nlmsg(data: bytes) -> NetlinkRoute:
        lib = _load()
        if lib is None:
            raise NetlinkError(_lib_err or "native netlink unavailable")
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        out = _CRoute()
        if lib.onl_parse_route_nlmsg(buf, len(data), ctypes.byref(out)) != 0:
            raise NetlinkError("parse_nlmsg failed")
        # convert back through the JSON form for one canonical path
        nhs = []
        for i in range(out.num_nexthops):
            cn = out.nh[i]
            gw = None
            if cn.af:
                alen = 4 if cn.af == pysocket.AF_INET else 16
                gw = str(ipaddress.ip_address(bytes(cn.gateway[:alen])))
            nhs.append(
                Nexthop(
                    gateway=gw,
                    ifindex=cn.ifindex,
                    weight=cn.weight,
                    labels=tuple(cn.labels[j] for j in range(cn.num_labels)),
                )
            )
        if out.family == AF_MPLS:
            return NetlinkRoute(
                mpls_label=out.mpls_label, table=out.table,
                protocol=out.protocol, priority=out.priority, nexthops=nhs,
            )
        alen = 4 if out.family == pysocket.AF_INET else 16
        addr = ipaddress.ip_address(bytes(out.dst[:alen]))
        return NetlinkRoute(
            dst=f"{addr}/{out.dst_len}", table=out.table,
            protocol=out.protocol, priority=out.priority, nexthops=nhs,
        )
