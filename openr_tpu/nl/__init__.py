"""Python bindings for the native netlink library (native/nl).

reference: openr/nl/ † — the reference's from-scratch C++ rtnetlink
library. The rebuild keeps this layer native (see native/nl/netlink.hpp)
and exposes it here via ctypes.
"""

from openr_tpu.nl.netlink import (
    NetlinkError,
    NetlinkRoute,
    NetlinkSocket,
    Nexthop,
    native_available,
)

__all__ = [
    "NetlinkError",
    "NetlinkRoute",
    "NetlinkSocket",
    "Nexthop",
    "native_available",
]
