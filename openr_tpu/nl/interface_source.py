"""NetlinkInterfaceSource: real kernel interfaces → LinkMonitor.

reference: LinkMonitor's netlink subscription in the reference †
(openr/link-monitor/LinkMonitor.cpp consumes link/addr events from
openr/nl's NetlinkProtocolSocket and replays an initial snapshot). Here
the same seam is the InterfaceEvent queue: this module snapshots
links+addrs at start, then converts subscribed rtnetlink events into
`InterfaceEvent`s, so LinkMonitor code is identical for mock (tests/
emulator) and real-kernel deployments.
"""

from __future__ import annotations

import asyncio
import logging
import threading

from openr_tpu.common.eventbase import OpenrModule
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.nl.netlink import (
    RTMGRP_IPV4_IFADDR,
    RTMGRP_IPV6_IFADDR,
    RTMGRP_LINK,
    NetlinkSocket,
)
from openr_tpu.types.events import InterfaceEvent, InterfaceInfo

log = logging.getLogger(__name__)


class NetlinkInterfaceSource(OpenrModule):
    """Feeds kernel link/addr state into an InterfaceEvent queue."""

    def __init__(
        self,
        node_name: str,
        interface_events_queue: ReplicateQueue,
        counters=None,
        poll_ms: int = 500,
    ):
        super().__init__(f"{node_name}.nlifaces", counters=counters)
        self.queue = interface_events_queue
        self.poll_ms = poll_ms
        self._sock: NetlinkSocket | None = None
        # serializes native socket use between the poll worker thread and
        # close(): cancelling the awaiting task does NOT stop the thread
        # blocked in poll/recv, so close() must wait for it to drain
        self._io_lock = threading.Lock()
        # name -> InterfaceInfo (current view)
        self.interfaces: dict[str, InterfaceInfo] = {}

    async def main(self) -> None:
        groups = RTMGRP_LINK | RTMGRP_IPV4_IFADDR | RTMGRP_IPV6_IFADDR
        # subscribe BEFORE the snapshot so no transition is lost between
        # dump and first poll (reference: same subscribe-then-replay order †)
        self._sock = NetlinkSocket(groups=groups)
        await asyncio.to_thread(self._snapshot)
        self.queue.push(
            InterfaceEvent(interfaces=list(self.interfaces.values()))
        )
        self.spawn(self._event_loop(), name=f"{self.name}.events")

    async def cleanup(self) -> None:
        # detach first so the poll loop exits at its next iteration, then
        # close under the io lock once any in-flight next_events (blocked
        # for up to poll_ms) has returned — avoids a use-after-free on the
        # native Socket
        sock, self._sock = self._sock, None
        if sock is not None:
            await asyncio.to_thread(self._locked_close, sock)

    def _locked_close(self, sock: NetlinkSocket) -> None:
        with self._io_lock:
            sock.close()

    def _next_events(self, poll_ms: int) -> list:
        with self._io_lock:
            sock = self._sock  # bind once: cleanup() nulls it lock-free
            if sock is None:
                return []
            return sock.next_events(poll_ms)

    def _snapshot(self) -> None:
        with self._io_lock:
            sock = self._sock
            if sock is None:
                return
            self._snapshot_locked(sock)

    def _snapshot_locked(self, sock: NetlinkSocket) -> None:
        addrs_by_if: dict[int, list[str]] = {}
        for a in sock.addrs_dump():
            addrs_by_if.setdefault(a["ifindex"], []).append(a["addr"])
        for link in sock.links_dump():
            self.interfaces[link["name"]] = InterfaceInfo(
                name=link["name"],
                is_up=bool(link["up"]),
                ifindex=link["ifindex"],
                addrs=tuple(addrs_by_if.get(link["ifindex"], ())),
            )

    async def _event_loop(self) -> None:
        while not self.stopped:
            if self._sock is None:
                return
            evs = await asyncio.to_thread(self._next_events, self.poll_ms)
            if not evs:
                continue
            changed: dict[str, InterfaceInfo] = {}
            resync_addrs = False
            for ev in evs:
                if ev["kind"] == "link":
                    name = ev.get("name", "")
                    if not name:
                        continue
                    if ev["deleted"]:
                        old = self.interfaces.pop(name, None)
                        if old is not None:
                            changed[name] = InterfaceInfo(
                                name=name, is_up=False,
                                ifindex=old.ifindex, addrs=(),
                            )
                    else:
                        old = self.interfaces.get(name)
                        info = InterfaceInfo(
                            name=name,
                            is_up=bool(ev["up"]),
                            ifindex=ev["ifindex"],
                            addrs=old.addrs if old else (),
                        )
                        if old != info:
                            self.interfaces[name] = info
                            changed[name] = info
                else:  # addr event: cheapest correct response is re-dump
                    resync_addrs = True
            if resync_addrs:
                await asyncio.to_thread(self._resync_addrs, changed)
            if changed:
                if self.counters is not None:
                    self.counters.increment(
                        "nlifaces.events", len(changed)
                    )
                self.queue.push(
                    InterfaceEvent(interfaces=list(changed.values()))
                )

    def _resync_addrs(self, changed: dict[str, InterfaceInfo]) -> None:
        with self._io_lock:
            sock = self._sock
            if sock is None:
                return
            addrs_by_if: dict[int, list[str]] = {}
            for a in sock.addrs_dump():
                addrs_by_if.setdefault(a["ifindex"], []).append(a["addr"])
        for name, info in list(self.interfaces.items()):
            new_addrs = tuple(addrs_by_if.get(info.ifindex, ()))
            if new_addrs != info.addrs:
                ni = InterfaceInfo(
                    name=name, is_up=info.is_up,
                    ifindex=info.ifindex, addrs=new_addrs,
                )
                self.interfaces[name] = ni
                changed[name] = ni
