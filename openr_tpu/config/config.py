"""Config schema + validation (reference: openr/if/OpenrConfig.thrift †,
openr/config/Config.cpp † populateInternalDb-style checks)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field, replace

from openr_tpu.common import constants as C
from openr_tpu.types.network import IpPrefix
from openr_tpu.types.serde import from_wire
from openr_tpu.types.topology import (
    ForwardingAlgorithm,
    ForwardingType,
    PrefixMetrics,
)


class ConfigError(ValueError):
    """Invalid configuration (reference: Config.cpp throws std::invalid_argument †)."""


@dataclass
class SparkConfig:
    """reference: OpenrConfig.thrift † SparkConfig."""

    hello_time_ms: int = C.SPARK_HELLO_INTERVAL_MS
    fastinit_hello_time_ms: int = C.SPARK_FASTINIT_HELLO_INTERVAL_MS
    handshake_time_ms: int = C.SPARK_HANDSHAKE_INTERVAL_MS
    keepalive_time_ms: int = C.SPARK_HEARTBEAT_INTERVAL_MS
    hold_time_ms: int = C.SPARK_HOLD_TIME_MS
    graceful_restart_time_ms: int = C.SPARK_GR_HOLD_TIME_MS
    # tx packet framing (docs/Wire.md): "bin" = compact binary, "json"
    # = legacy canonical JSON. RX always sniffs, so mixed-codec
    # neighbors interoperate. (Appended field: binary wire schema
    # evolution is append-only.)
    wire_codec: str = "bin"


@dataclass
class KvstoreConfig:
    """reference: OpenrConfig.thrift † KvstoreConfig."""

    key_ttl_ms: int = C.KVSTORE_DEFAULT_TTL_MS
    sync_interval_s: int = C.KVSTORE_SYNC_INTERVAL_S
    flood_rate_msgs_per_sec: int = C.KVSTORE_FLOOD_RATE_MSGS_PER_SEC
    flood_rate_burst_size: int = C.KVSTORE_FLOOD_RATE_BURST
    # bound on a peer's coalesced pending-flood queue; overflow drops the
    # backlog and schedules a FULL_SYNC (backpressure)
    flood_pending_max_keys: int = C.KVSTORE_FLOOD_PENDING_MAX_KEYS
    enable_flood_optimization: bool = False
    # DUAL flood-root eligibility (reference: is_flood_root †). The
    # reference restricts root eligibility to a few well-connected
    # nodes; every-node-a-root means O(V) root machines per node, so
    # the default is False and deployments elect roots explicitly:
    # either set is_flood_root on ~2 nodes, or list candidate node
    # names in flood_root_candidates (same config on every node; a node
    # is root iff its own name is listed — overrides is_flood_root).
    is_flood_root: bool = False
    flood_root_candidates: tuple[str, ...] = ()
    # grace before declaring KVSTORE_SYNCED with zero peers (covers the
    # window before LinkMonitor delivers the first PeerEvent)
    initial_sync_grace_s: float = 2.0
    # cross-node flood tracing (docs/Monitor.md "Flood tracing"):
    # deterministic head-sampling — every Nth ACCEPTED local origination
    # carries a per-hop flood span cluster-wide. 0 disables tracing
    # (the default: span stamps cost wire bytes on every sampled hop).
    # The sampling phase is derived from (node_name, trace_seed) so a
    # seeded emulation replays the same sampled set while different
    # nodes stay decorrelated. Affordability guidance: a coalesced
    # flood batch is traced when ANY merged origination was sampled
    # (per-frame taint ≈ 1-(1-1/N)^batch), so size N with the CLUSTER
    # — a few × node count under heavy churn keeps the wire overhead
    # in low single digits (measured: docs/Monitor.md, BENCH_TRACE);
    # each sampled origination still completes a span on every node
    # it reaches, so trace volume stays ample.
    trace_sample_every: int = 0
    trace_seed: int = 0


@dataclass
class MessagingConfig:
    """Bounds + overflow policies for the inter-module queues
    (openr_tpu/messaging). The reference's ReplicateQueues are unbounded;
    under sustained churn that is an OOM waiting to happen, so every
    policied seam here gets a depth cap (DeltaPath, PAPERS.md: churn
    throughput is governed by batching/coalescing at the seams)."""

    # per-reader depth cap for the policied queues (kvstore_pubs,
    # route_updates, fib_updates coalesce; log_samples, perf_events
    # shed-oldest). 0 = unbounded.
    queue_maxsize: int = C.QUEUE_MAXSIZE
    # False keeps the caps configured (the soak's bounded-depth invariant
    # still reads queue_maxsize) but builds the queues UNBOUNDED — the
    # deliberately-broken control case that proves the watermark check
    # catches unbounded growth.
    enforce_bounds: bool = True


@dataclass
class LinkMonitorConfig:
    """reference: OpenrConfig.thrift † LinkMonitorConfig."""

    linkflap_initial_backoff_ms: int = C.LINK_FLAP_INITIAL_BACKOFF_MS
    linkflap_max_backoff_ms: int = C.LINK_FLAP_MAX_BACKOFF_MS
    use_rtt_metric: bool = False
    include_interface_regexes: tuple[str, ...] = ()
    exclude_interface_regexes: tuple[str, ...] = ()


@dataclass
class DecisionConfig:
    """reference: OpenrConfig.thrift † DecisionConfig."""

    debounce_min_ms: int = C.DECISION_DEBOUNCE_MIN_MS
    debounce_max_ms: int = C.DECISION_DEBOUNCE_MAX_MS
    # TPU solver knobs (rebuild-specific)
    use_tpu_solver: bool = True  # False → CPU oracle path (tests/tiny nodes)
    use_dense_kernel: bool | None = None  # None = auto
    # VMEM-resident Pallas relax kernel — interpreter-mode (CPU) design
    # reference ONLY. On real TPU backends the solver REFUSES this knob
    # at construction: the kernel's row gather lowers to
    # tpu.dynamic_gather, which v5e Mosaic only supports inside one
    # 8x128 vreg (measured, docs/spf_kernel_profile.md §2) — any
    # production-size shape fails in the backend compiler. Production
    # TPU solves use the XLA v3 split kernel (spf_kernel="split").
    use_pallas_kernel: bool = False
    # batched kernel implementation: "split" (v3 split-width tables +
    # compacted tail — the default) or "dense" (the r2 kernel)
    spf_kernel: str = "split"
    # native C++ radix-heap solver (native/spf) for the single-root RIB
    # path: "auto" (use when built and LFA off), "on", "off"
    native_rib: str = "auto"
    enable_lfa: bool = False
    # edge-disjoint paths per SR-MPLS KSP prefix (reference hardwires 2
    # in KSP2_ED_ECMP †; BASELINE config 4 exercises k=16; the batched
    # kernel supports k<=16 — validated)
    ksp_paths: int = 2
    # multi-chip mesh for BATCHED solves (fleet/all-sources shapes):
    # sources × graph device grid (parallel.make_mesh). 0 = off
    # (single device). Requires mesh_sources × mesh_graph ≤ available
    # jax devices; the single-root production rebuild always stays
    # single-device (latency shape).
    mesh_sources: int = 0
    mesh_graph: int = 1
    # topology-delta warm start (DeltaPath/Bounded-Dijkstra): metric-only
    # link churn re-solves only the affected region from the cached
    # SolveArtifact instead of paying a full per-area solve
    # (REBUILD_TOPO_DELTA; docs/Decision.md). False forces every
    # topology change down the full path.
    enable_topo_delta: bool = True
    # fallback-to-full threshold: a warm start is refused when the
    # changed-edge DELTA SET exceeds this fraction of the graph's
    # edges — past that a cold solve is cheaper than per-edge
    # bookkeeping. The affected REGION is deliberately uncapped: it may
    # legitimately cover most of the graph (a raised edge near the
    # root of a uniform-metric topology), and its worst case costs
    # about one cold solve.
    topo_delta_max_frac: float = 0.25


@dataclass
class FibConfig:
    """reference: OpenrConfig.thrift † (fib port etc.)."""

    initial_retry_ms: int = C.FIB_INITIAL_RETRY_MS
    max_retry_ms: int = C.FIB_MAX_RETRY_MS
    sync_interval_s: int = C.FIB_SYNC_INTERVAL_S
    dry_run: bool = False
    # warm boot (graceful restart dataplane continuity): read the
    # previous incarnation's programmed routes at start and program only
    # the delta against the first computed RIB — never flush (reference:
    # Fib warm-boot sync †, SURVEY §5.3/5.4)
    enable_warm_boot: bool = True
    # max routes per FibService add/delete call on the delta program
    # path (docs/Fib.md): a million-route convergence ships bounded
    # chunks instead of one giant frame. Appended field (wire evolution:
    # older peers default it).
    program_batch_size: int = 4096


@dataclass
class SegmentRoutingConfig:
    """reference: OpenrConfig.thrift † SegmentRoutingConfig (sr_enable,
    label ranges)."""

    enable: bool = False
    node_segment_label: int = 0  # 0 = auto-allocate from range
    sr_global_range: tuple[int, int] = C.SR_GLOBAL_RANGE
    sr_local_range: tuple[int, int] = C.SR_LOCAL_RANGE


@dataclass
class WatchdogConfig:
    """reference: OpenrConfig.thrift † WatchdogConfig."""

    enable: bool = True
    interval_s: int = C.WATCHDOG_INTERVAL_S
    thread_timeout_s: int = C.WATCHDOG_THREAD_TIMEOUT_S


@dataclass
class AreaConfig:
    """reference: OpenrConfig.thrift † AreaConfig (area id + interface /
    neighbor membership regexes)."""

    area_id: str = C.DEFAULT_AREA
    include_interface_regexes: tuple[str, ...] = (".*",)
    neighbor_regexes: tuple[str, ...] = (".*",)


@dataclass
class OriginatedPrefix:
    """reference: OpenrConfig.thrift † OriginatedPrefix."""

    prefix: str = ""
    forwarding_type: ForwardingType = ForwardingType.IP
    forwarding_algorithm: ForwardingAlgorithm = ForwardingAlgorithm.SP_ECMP
    path_preference: int = 1000
    source_preference: int = 100
    minimum_supporting_routes: int = 0
    install_to_fib: bool = False
    tags: tuple[str, ...] = ()


@dataclass(frozen=True)
class PolicyStatementConfig:
    """Config mirror of policy.PolicyStatement (kept here so the config
    schema has no dependency on the policy engine; OpenrNode converts).
    reference: PolicyStatement in openr/policy/ †."""

    name: str = ""
    match_tags: tuple[str, ...] = ()
    match_prefixes: tuple[str, ...] = ()
    action_accept: bool = True
    set_path_preference: int | None = None
    set_source_preference: int | None = None
    set_distance_increment: int | None = None
    add_tags: tuple[str, ...] = ()


@dataclass
class RouteMapTermConfig:
    """Config mirror of policy.RouteMapTerm (ordered route-map term).
    `match_prefixes` entries are "PREFIX [ge N] [le N]" strings, parsed
    by OpenrNode at assembly. reference: openr/policy/ † ordered
    statement evaluation."""

    seq: int = 0
    action: str = "permit"
    match_tags_any: tuple[str, ...] = ()
    match_tags_all: tuple[str, ...] = ()
    match_not_tags: tuple[str, ...] = ()
    match_prefixes: tuple[str, ...] = ()
    set_path_preference: int | None = None
    set_source_preference: int | None = None
    set_distance_increment: int | None = None
    set_tags: tuple[str, ...] | None = None
    add_tags: tuple[str, ...] = ()
    remove_tags: tuple[str, ...] = ()


@dataclass
class PrefixAllocationConfig:
    """reference: OpenrConfig.thrift † PrefixAllocationConfig — carve
    `seed_prefix` into /alloc_prefix_len blocks; each node elects a
    collision-free block index through KvStore write conflicts."""

    seed_prefix: str = ""
    alloc_prefix_len: int = 0
    # STATIC mode pins the index instead of electing (reference:
    # prefix_allocation_mode †)
    static_index: int | None = None


@dataclass
class UdpInterfaceConfig:
    """One point-to-point UDP 'interface' for a standalone deployment
    without per-interface kernel multicast: Spark's hello traffic for
    `if_name` is carried on a local UDP port bound to a fixed peer
    (reference: the IoProvider abstraction † makes the packet path
    pluggable; this is the cross-host provider's link definition)."""

    if_name: str
    local_port: int
    peer_host: str
    peer_port: int


@dataclass
class TlsConfig:
    """Control-plane TLS (reference: thrift server TLS knobs †, the
    ctrl-server's optional secure thrift). Applied to the ctrl listener
    and the KvStore RPC mesh; contexts built by openr_tpu.rpc.tls."""

    enabled: bool = False
    cert_path: str = ""
    key_path: str = ""
    ca_path: str = ""  # trust anchor for peer verification (both sides)
    # require a verified client certificate (router-to-router mutual
    # auth); operator CLIs without certs need this off on ctrl
    require_client_cert: bool = True


@dataclass
class NodeConfig:
    """Root config document (reference: OpenrConfig.thrift † OpenrConfig)."""

    node_name: str = ""
    areas: tuple[AreaConfig, ...] = (AreaConfig(),)
    spark: SparkConfig = field(default_factory=SparkConfig)
    kvstore: KvstoreConfig = field(default_factory=KvstoreConfig)
    messaging: MessagingConfig = field(default_factory=MessagingConfig)
    link_monitor: LinkMonitorConfig = field(default_factory=LinkMonitorConfig)
    decision: DecisionConfig = field(default_factory=DecisionConfig)
    fib: FibConfig = field(default_factory=FibConfig)
    segment_routing: SegmentRoutingConfig = field(
        default_factory=SegmentRoutingConfig
    )
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    originated_prefixes: tuple[OriginatedPrefix, ...] = ()
    # origination policy statements applied by PrefixManager before a
    # prefix is advertised (reference: area_policies / PolicyManager †);
    # empty = accept everything
    prefix_policy_statements: tuple["PolicyStatementConfig", ...] = ()
    prefix_policy_default_accept: bool = True
    # ordered route-map (numbered terms, first-match-wins, implicit
    # deny unless prefix_route_map_default_accept) — takes precedence
    # over prefix_policy_statements when non-empty
    prefix_route_map: tuple["RouteMapTermConfig", ...] = ()
    prefix_route_map_default_accept: bool = False
    prefix_allocation: PrefixAllocationConfig | None = None
    enable_v4: bool = True
    enable_best_route_selection: bool = True
    # ports (0 = ephemeral, for in-process multi-node tests)
    ctrl_port: int = C.CTRL_PORT
    kvstore_port: int = C.KVSTORE_PORT
    dry_run: bool = False
    # standalone-process deployment: static point-to-point UDP links for
    # Spark when kernel multicast interfaces aren't used (python -m
    # openr_tpu); empty = interfaces come from netlink
    udp_interfaces: tuple[UdpInterfaceConfig, ...] = ()
    # host to bind kvstore/ctrl listeners + advertise to neighbors
    endpoint_host: str = "127.0.0.1"
    # optional control-plane TLS (ctrl + kvstore RPC listeners/dialers)
    tls: TlsConfig = field(default_factory=TlsConfig)


class Config:
    """Validated accessor wrapper (reference: openr/config/Config †)."""

    def __init__(self, node: NodeConfig):
        self.node = node
        self._validate()

    # ---- construction -----------------------------------------------------

    @staticmethod
    def from_json(text: str | bytes) -> "Config":
        return Config(from_wire(text, NodeConfig))

    @staticmethod
    def from_file(path: str) -> "Config":
        with open(path, "rb") as f:
            return Config.from_json(f.read())

    @staticmethod
    def default(node_name: str, **overrides) -> "Config":
        return Config(replace(NodeConfig(node_name=node_name), **overrides))

    def to_json(self) -> str:
        from openr_tpu.types.serde import to_jsonable

        # straight through the jsonable tree — no encode-to-canonical-
        # bytes-then-reparse round trip
        return json.dumps(to_jsonable(self.node), indent=2)

    # ---- validation (reference: Config::populateInternalDb checks †) ------

    def _validate(self) -> None:
        n = self.node
        try:
            C.validate_name(n.node_name, "node_name")
        except ValueError as e:
            raise ConfigError(str(e)) from e
        if not n.areas:
            raise ConfigError("at least one area required")
        seen = set()
        for a in n.areas:
            try:
                C.validate_name(a.area_id, "area_id")
            except ValueError as e:
                raise ConfigError(str(e)) from e
            if a.area_id in seen:
                raise ConfigError(f"duplicate area {a.area_id!r}")
            seen.add(a.area_id)
        s = n.spark
        if not (
            0 < s.fastinit_hello_time_ms <= s.hello_time_ms
        ):
            raise ConfigError("spark: fastinit must be <= hello interval")
        if s.hold_time_ms < 3 * s.keepalive_time_ms:
            raise ConfigError(
                "spark: hold_time must be >= 3x keepalive "
                "(reference: Config.cpp † hold/keepalive check)"
            )
        if s.wire_codec not in ("bin", "json"):
            raise ConfigError("spark: wire_codec must be bin|json")
        d = n.decision
        if not (0 < d.debounce_min_ms <= d.debounce_max_ms):
            raise ConfigError("decision: debounce min must be <= max")
        if not (1 <= d.ksp_paths <= 16):
            raise ConfigError(
                "decision: ksp_paths must be in 1..16 (the vectorized "
                "k-disjoint-paths kernel bound — ops/ksp.py)"
            )
        if d.spf_kernel not in ("split", "dense"):
            raise ConfigError("decision: spf_kernel must be split|dense")
        if d.native_rib not in ("auto", "on", "off"):
            raise ConfigError(
                "decision: native_rib must be auto|on|off"
            )
        if d.mesh_sources < 0 or d.mesh_graph < 1:
            raise ConfigError(
                "decision: mesh_sources must be >= 0 and mesh_graph >= 1"
            )
        k = n.kvstore
        if k.key_ttl_ms <= 0:
            raise ConfigError("kvstore: key_ttl_ms must be positive")
        if n.messaging.queue_maxsize < 0:
            raise ConfigError("messaging: queue_maxsize must be >= 0")
        f = n.fib
        if not (0 < f.initial_retry_ms <= f.max_retry_ms):
            raise ConfigError("fib: retry bounds invalid")
        sr = n.segment_routing
        if sr.enable:
            lo, hi = sr.sr_global_range
            if not (C.MPLS_LABEL_MIN <= lo <= hi <= C.MPLS_LABEL_MAX):
                raise ConfigError("segment_routing: bad global label range")
        for p in n.originated_prefixes:
            try:
                IpPrefix.make(p.prefix)
            except ValueError as e:
                raise ConfigError(f"bad originated prefix {p.prefix!r}") from e
        pa = n.prefix_allocation
        if pa is not None:
            try:
                seed = IpPrefix.make(pa.seed_prefix)
            except ValueError as e:
                raise ConfigError(f"bad seed prefix {pa.seed_prefix!r}") from e
            if not (seed.prefix_len < pa.alloc_prefix_len <= (32 if seed.is_v4 else 128)):
                raise ConfigError(
                    "prefix_allocation: alloc_prefix_len must be within "
                    f"({seed.prefix_len}, {32 if seed.is_v4 else 128}]"
                )

    # ---- accessors --------------------------------------------------------

    @property
    def node_name(self) -> str:
        return self.node.node_name

    @property
    def areas(self) -> tuple[AreaConfig, ...]:
        return self.node.areas

    def area_ids(self) -> list[str]:
        return [a.area_id for a in self.node.areas]
