"""Typed node configuration.

reference: openr/if/OpenrConfig.thrift † + openr/config/Config.{h,cpp} † —
one validated JSON document parsed into typed sub-configs
(SparkConfig, KvstoreConfig, LinkMonitorConfig, DecisionConfig, …,
per-area AreaConfig blocks), with accessors consumed by every module.
"""

from openr_tpu.config.config import (  # noqa: F401
    AreaConfig,
    Config,
    ConfigError,
    DecisionConfig,
    FibConfig,
    KvstoreConfig,
    LinkMonitorConfig,
    MessagingConfig,
    NodeConfig,
    OriginatedPrefix,
    PrefixAllocationConfig,
    SparkConfig,
    SegmentRoutingConfig,
    WatchdogConfig,
)
