"""Async RPC — the thrift-RPC equivalent, JSON lines + binary frames.

reference: the control plane of openr is fbthrift services everywhere
(OpenrCtrl.thrift †, Platform.thrift † FibService, KvStore thrift peering
†). This rebuild uses one small asyncio RPC core with the same roles:
request/response calls, fire-and-forget notifications, and server-push
streams (≙ thrift server-streaming used by subscribeKvStoreFilter /
subscribeFib †). Payloads are the wire codecs from openr_tpu.types.serde,
so every schema dataclass travels as-is.

Envelope shape (one object per frame):
  request:      {"id": 1, "method": "m", "params": {...}}
  response:     {"id": 1, "result": {...}} | {"id": 1, "error": "..."}
  notification: {"method": "m", "params": {...}}            (no id)
  stream item:  {"id": 1, "item": {...}}                    (until "end")
  stream end:   {"id": 1, "end": true}

Framing (docs/Wire.md): every connection starts as newline-delimited
canonical JSON; a ``_wire.hello`` negotiation upgrades both directions
to length-prefixed binary frames (``[0xB1][uvarint len][serde blob]``,
compact TLV with varint ints and raw bytes). The receive path sniffs
each frame's first byte, so mixed-version peers interoperate.
"""

from openr_tpu.rpc.core import (  # noqa: F401
    WIRE_CODEC_BIN,
    RpcClient,
    RpcError,
    RpcServer,
    RpcTransportError,
    StreamWriter,
    WireFrameError,
    bin_frame,
)
