"""Async RPC over newline-delimited JSON — the thrift-RPC equivalent.

reference: the control plane of openr is fbthrift services everywhere
(OpenrCtrl.thrift †, Platform.thrift † FibService, KvStore thrift peering
†). This rebuild uses one small asyncio RPC core with the same roles:
request/response calls, fire-and-forget notifications, and server-push
streams (≙ thrift server-streaming used by subscribeKvStoreFilter /
subscribeFib †). Payloads are the canonical-JSON wire codec from
openr_tpu.types.serde, so every schema dataclass travels as-is.

Wire format (one JSON object per line):
  request:      {"id": 1, "method": "m", "params": {...}}
  response:     {"id": 1, "result": {...}} | {"id": 1, "error": "..."}
  notification: {"method": "m", "params": {...}}            (no id)
  stream item:  {"id": 1, "item": {...}}                    (until "end")
  stream end:   {"id": 1, "end": true}
"""

from openr_tpu.rpc.core import (  # noqa: F401
    RpcClient,
    RpcError,
    RpcServer,
    StreamWriter,
)
