"""Optional TLS for the control plane (ctrl server + KvStore RPC mesh).

reference: openr/ctrl-server/ † runs its thrift service with optional
TLS (secure thrift via fizz/wangle; cert/key/CA paths in config, with
mutual auth between routers). The rebuild's equivalent: `ssl.SSLContext`
on the asyncio listeners/dialers of `openr_tpu.rpc.core`, built from the
same cert/key/CA triple, with mutual auth on by default — a router mesh
is exactly the peer-to-peer case client-cert verification exists for.
"""

from __future__ import annotations

import ssl

# cfg is openr_tpu.config.TlsConfig (duck-typed here so the config
# package stays import-light)


def server_ssl_context(cfg) -> ssl.SSLContext | None:
    if not cfg.enabled:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_cert_chain(cfg.cert_path, cfg.key_path)
    if cfg.ca_path:
        ctx.load_verify_locations(cfg.ca_path)
    if cfg.require_client_cert:
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_ssl_context(cfg) -> ssl.SSLContext | None:
    if not cfg.enabled:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    if cfg.ca_path:
        ctx.load_verify_locations(cfg.ca_path)
    # routers dial each other by IP; identity comes from the CA-signed
    # cert (and mutual auth), not DNS hostnames
    ctx.check_hostname = False
    if cfg.cert_path:
        ctx.load_cert_chain(cfg.cert_path, cfg.key_path)
    return ctx
