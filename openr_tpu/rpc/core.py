"""RPC core: see package docstring for the wire format."""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, AsyncIterator, Awaitable, Callable

from openr_tpu.common.tasks import guard_task, reap
from openr_tpu.messaging import QueueClosedError, RQueue

log = logging.getLogger(__name__)

MAX_LINE = 64 * 1024 * 1024  # LSDB dumps can be large

# per-subscription client-side buffer: a slow stream consumer
# backpressures the rx loop (and so, via TCP, the server's per-sub
# eviction queue) instead of growing RAM without bound
STREAM_BUF = 1024

# how long the rx loop will sit blocked at one stream's bound before
# declaring that consumer dead and breaking its stream — a subscriber
# that never drains (or a generator that was never iterated, whose
# cleanup can therefore never run) must not stall every other reply on
# the client forever
STREAM_STALL_S = 30.0


class RpcError(Exception):
    """Remote handler raised / transport failed."""


class StreamWriter:
    """Handed to streaming handlers to push items to the subscriber."""

    def __init__(self, writer: asyncio.StreamWriter, req_id: int):
        self._writer = writer
        self._id = req_id
        self.closed = False

    async def send(self, item: Any) -> None:
        if self.closed:
            raise RpcError("stream closed")
        try:
            self._writer.write(_dumps({"id": self._id, "item": item}))
            await self._writer.drain()
        except (ConnectionError, RuntimeError) as e:
            self.closed = True
            raise RpcError(f"stream write failed: {e}") from e

    async def end(self) -> None:
        if not self.closed:
            self.closed = True
            try:
                self._writer.write(_dumps({"id": self._id, "end": True}))
                await self._writer.drain()
            except (ConnectionError, RuntimeError):
                pass


def _dumps(obj: dict) -> bytes:
    return json.dumps(obj, separators=(",", ":")).encode() + b"\n"


Handler = Callable[..., Awaitable[Any]]


class RpcServer:
    """Dispatches methods on incoming connections.

    register(name, fn): async fn(params_dict) -> jsonable result.
    register_stream(name, fn): async fn(params_dict, stream: StreamWriter);
    the stream stays open until fn returns or the client disconnects.
    """

    def __init__(self, name: str = "rpc"):
        self.name = name
        self._methods: dict[str, Handler] = {}
        self._streams: dict[str, Handler] = {}
        self._server: asyncio.AbstractServer | None = None
        self._conn_tasks: set[asyncio.Task] = set()
        self.port: int | None = None

    def register(self, method: str, fn: Handler) -> None:
        self._methods[method] = fn

    def register_stream(self, method: str, fn: Handler) -> None:
        self._streams[method] = fn

    async def start(
        self, host: str = "127.0.0.1", port: int = 0, ssl=None
    ) -> int:
        """Bind and serve; returns the bound port (0 → ephemeral).
        Pass an `ssl.SSLContext` (see rpc.tls) for a TLS listener."""
        self._server = await asyncio.start_server(
            self._on_conn, host, port, limit=MAX_LINE, ssl=ssl
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        # cancel handlers BEFORE wait_closed(): since py3.12 wait_closed
        # blocks until every connection handler returns
        for t in list(self._conn_tasks):
            t.cancel()
        for t in list(self._conn_tasks):
            # swallows only t's own cancellation; one aimed at stop()
            # itself re-raises (OR005). cancel=False: all conn tasks
            # were cancelled above.
            await reap(t, cancel=False)
        self._conn_tasks.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        if task:
            self._conn_tasks.add(task)
        stream_tasks: list[asyncio.Task] = []
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except ValueError:
                    # JSONDecodeError *or* UnicodeDecodeError: a garbage
                    # frame that isn't valid UTF-8 raises the latter,
                    # which json.JSONDecodeError does NOT cover — the
                    # asyncio sanitizer caught the conn task dying on it
                    # (test_fuzz_wire::test_rpc_server_survives_garbage)
                    log.warning("%s: bad json from peer", self.name)
                    continue
                method = msg.get("method")
                req_id = msg.get("id")
                params = msg.get("params") or {}
                if method in self._streams and req_id is not None:
                    sw = StreamWriter(writer, req_id)

                    async def run_stream(fn=self._streams[method], p=params, s=sw):
                        try:
                            await fn(p, s)
                        except RpcError:
                            pass
                        except asyncio.CancelledError:
                            raise  # conn teardown cancels us (OR005)
                        except Exception:  # noqa: BLE001
                            log.exception("%s: stream handler failed", self.name)
                        finally:
                            await s.end()

                    stream_tasks.append(asyncio.ensure_future(run_stream()))
                elif method in self._methods:
                    try:
                        result = await self._methods[method](params)
                        reply = {"id": req_id, "result": result}
                    except asyncio.CancelledError:
                        raise  # server stop cancels conn tasks (OR005)
                    except Exception as e:  # noqa: BLE001
                        log.exception("%s: handler %s failed", self.name, method)
                        reply = {"id": req_id, "error": f"{type(e).__name__}: {e}"}
                    if req_id is not None:
                        writer.write(_dumps(reply))
                        await writer.drain()
                elif req_id is not None:
                    writer.write(
                        _dumps({"id": req_id, "error": f"no method {method!r}"})
                    )
                    await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except asyncio.CancelledError:
            raise
        finally:
            for t in stream_tasks:
                t.cancel()
            writer.close()
            if task:
                self._conn_tasks.discard(task)


class RpcClient:
    """One connection; concurrent calls multiplexed by request id."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, ssl=None):
        self.host = host
        self.port = port
        self.ssl = ssl  # ssl.SSLContext (rpc.tls) or None for plaintext
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._next_id = 1
        self._pending: dict[int, asyncio.Future] = {}
        self._streams: dict[int, RQueue] = {}
        self._rx_task: asyncio.Task | None = None

    @property
    def connected(self) -> bool:
        return self._writer is not None

    async def connect(self, timeout: float = 5.0) -> None:
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(
                self.host, self.port, limit=MAX_LINE, ssl=self.ssl
            ),
            timeout,
        )
        self._rx_task = guard_task(
            asyncio.ensure_future(self._rx_loop()), owner="rpc.client.rx"
        )

    async def close(self) -> None:
        if self._rx_task:
            # swallows only the rx fiber's cancellation, not close()'s
            await reap(self._rx_task)
            self._rx_task = None
        if self._writer:
            self._writer.close()
            self._writer = None
        self._fail_all(RpcError("client closed"))

    def _fail_all(self, err: Exception) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()
        for q in self._streams.values():
            # force: the sentinel must land even on a full queue
            q.put_nowait(_STREAM_ERR, force=True)
        self._streams.clear()

    async def _rx_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    break
                msg = json.loads(line)
                req_id = msg.get("id")
                if "item" in msg and req_id in self._streams:
                    try:
                        # backpressured put: a slow consumer stalls line
                        # reads (and via TCP, the sender) at STREAM_BUF
                        await asyncio.wait_for(
                            self._streams[req_id].put(msg["item"]),
                            STREAM_STALL_S,
                        )
                    except QueueClosedError:
                        # consumer abandoned the stream (gen() closed
                        # its queue) — possibly while we were blocked
                        # at the bound; drop the item and move on
                        self._streams.pop(req_id, None)
                    except asyncio.TimeoutError:
                        # consumer sat at the bound for STREAM_STALL_S
                        # without draining — or the generator was never
                        # even iterated (its cleanup can't run). Break
                        # THAT stream (its next get raises) rather than
                        # stall every reply on this client forever.
                        dead = self._streams.pop(req_id, None)
                        if dead is not None:
                            dead.close()
                elif msg.get("end") and req_id in self._streams:
                    self._streams.pop(req_id).put_nowait(
                        _STREAM_END, force=True
                    )
                elif req_id in self._streams and (
                    "error" in msg or "result" in msg
                ):
                    # server treated the subscription as a plain call (bad
                    # method / non-stream handler): fail the stream instead
                    # of hanging the subscriber forever
                    self._streams.pop(req_id).put_nowait(
                        _STREAM_ERR, force=True
                    )
                elif req_id in self._pending:
                    fut = self._pending.pop(req_id)
                    if not fut.done():
                        if "error" in msg:
                            fut.set_exception(RpcError(msg["error"]))
                        else:
                            fut.set_result(msg.get("result"))
        except (ConnectionError, ValueError, asyncio.IncompleteReadError):
            # ValueError covers JSONDecodeError AND UnicodeDecodeError —
            # a non-UTF-8 frame from a corrupt/hostile server must take
            # the clean connection-lost path, same as the server side
            pass
        except asyncio.CancelledError:
            raise
        finally:
            self._fail_all(RpcError("connection lost"))

    async def call(
        self, method: str, params: Any = None, timeout: float = 30.0
    ) -> Any:
        if self._writer is None:
            raise RpcError("not connected")
        req_id = self._next_id
        self._next_id += 1
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[req_id] = fut
        self._writer.write(
            _dumps({"id": req_id, "method": method, "params": params or {}})
        )
        await self._writer.drain()
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError as e:
            self._pending.pop(req_id, None)  # don't leak the slot
            raise RpcError(f"call {method!r} timed out after {timeout}s") from e

    async def notify(self, method: str, params: Any = None) -> None:
        if self._writer is None:
            raise RpcError("not connected")
        self._writer.write(_dumps({"method": method, "params": params or {}}))
        await self._writer.drain()

    async def subscribe(
        self, method: str, params: Any = None
    ) -> AsyncIterator[Any]:
        """Server-push stream; iterate until the server ends it."""
        if self._writer is None:
            raise RpcError("not connected")
        req_id = self._next_id
        self._next_id += 1
        # messaging-seam queue (OR004): bounded, block policy — the rx
        # loop's awaited put is the backpressure point
        q: RQueue = RQueue(
            name=f"rpc.stream.{req_id}", maxsize=STREAM_BUF, policy="block"
        )
        self._streams[req_id] = q
        self._writer.write(
            _dumps({"id": req_id, "method": method, "params": params or {}})
        )
        await self._writer.drain()

        async def gen():
            try:
                while True:
                    try:
                        item = await q.get()
                    except QueueClosedError:
                        # the rx loop declared this consumer stalled
                        # (STREAM_STALL_S at the bound) and broke the
                        # stream to protect the rest of the client
                        raise RpcError(
                            "stream dropped: consumer stalled past "
                            "the buffer bound"
                        ) from None
                    if item is _STREAM_END:
                        return
                    if item is _STREAM_ERR:
                        raise RpcError("stream broken")
                    yield item
            finally:
                # consumer stopped iterating (break / aclose / GC):
                # deregister AND close the queue, waking an rx loop
                # blocked on `await q.put(...)` — otherwise one
                # abandoned stream at the bound would stall every
                # reply on this client forever
                if self._streams.pop(req_id, None) is not None:
                    q.close()

        return gen()


class _Sentinel:
    pass


_STREAM_END = _Sentinel()
_STREAM_ERR = _Sentinel()
