"""RPC core: see package docstring for the wire format.

Two framings coexist per connection (docs/Wire.md):

  * JSON lines (legacy / negotiation): one JSON object per ``\\n``-
    terminated line. Every connection STARTS here.
  * Binary frames: ``[0xB1][uvarint length][payload]`` where payload is
    a complete ``serde.to_wire_bin`` blob (its own magic + version
    byte) of the same envelope dict.

The receive path never needs mode state: a JSON text can't begin with
0xB1, so every frame is sniffed by its first byte. Only the TRANSMIT
codec is negotiated — a client that wants binary sends a
``_wire.hello`` call as its first request; a server that agrees replies
``{"codec": "bin1"}`` and both sides switch their writers. An old peer
either never sends the hello (server stays on JSON for that conn) or
answers it with a no-such-method error (client stays on JSON) — mixed
versions interoperate frame-by-frame.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, AsyncIterator, Awaitable, Callable

from openr_tpu.common.tasks import guard_task, reap
from openr_tpu.messaging import QueueClosedError, RQueue
from openr_tpu.types.serde import (
    WIRE_BIN_MAGIC,
    WireDecodeError,
    from_wire_bin,
    to_wire_bin,
    write_uvarint,
)

log = logging.getLogger(__name__)

MAX_LINE = 64 * 1024 * 1024  # LSDB dumps can be large

# the codec name the hello negotiates; bumping the serde wire version
# would introduce "bin2" here and old peers would keep matching "bin1"
WIRE_CODEC_BIN = "bin1"

# per-subscription client-side buffer: a slow stream consumer
# backpressures the rx loop (and so, via TCP, the server's per-sub
# eviction queue) instead of growing RAM without bound
STREAM_BUF = 1024

# how long the rx loop will sit blocked at one stream's bound before
# declaring that consumer dead and breaking its stream — a subscriber
# that never drains (or a generator that was never iterated, whose
# cleanup can therefore never run) must not stall every other reply on
# the client forever
STREAM_STALL_S = 30.0

_MAGIC = bytes((WIRE_BIN_MAGIC,))


class RpcError(Exception):
    """Remote handler raised / transport failed."""


class RpcTransportError(RpcError):
    """The CONNECTION failed (refused, reset, closed, timed out) — the
    remote handler never answered. Distinct from a plain RpcError so
    callers running capability probes (KvStore's delta-sync negotiation)
    can tell "the peer's handler rejected this method" from "the peer
    process died mid-call": only the former says anything about what
    the peer supports. Subclasses RpcError, so every existing
    `except RpcError` path is unchanged."""


class WireFrameError(ValueError):
    """Framing is unrecoverable on this connection (bad varint,
    oversized length prefix): the byte stream can no longer be resynced,
    so the CONNECTION is dropped — never the node."""


def _dumps(obj: dict) -> bytes:
    return json.dumps(obj, separators=(",", ":")).encode() + b"\n"


def bin_frame(obj: dict) -> bytes:
    """One binary wire frame: magic + uvarint length + serde blob."""
    blob = to_wire_bin(obj)
    head = bytearray(_MAGIC)
    write_uvarint(head, len(blob))
    return bytes(head) + blob


async def _read_frame(reader: asyncio.StreamReader) -> tuple[str, bytes]:
    """Sniff + read one wire message: ("bin", blob) | ("json", line).

    Raises IncompleteReadError at EOF / mid-frame truncation,
    WireFrameError when the binary framing itself is corrupt, and
    LimitOverrunError for an overlong JSON line.
    """
    first = await reader.readexactly(1)
    if first == _MAGIC:
        n = 0
        shift = 0
        while True:
            b = (await reader.readexactly(1))[0]
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 35:
                raise WireFrameError("unterminated length varint")
        if n > MAX_LINE:
            raise WireFrameError(f"oversized frame ({n} bytes)")
        return "bin", await reader.readexactly(n)
    return "json", first + await reader.readuntil(b"\n")


class _ConnState:
    """Per-connection transmit state: negotiated codec + accounting.
    The receive path sniffs every frame and needs no state."""

    __slots__ = ("writer", "codec", "counters")

    def __init__(self, writer: asyncio.StreamWriter, counters=None):
        self.writer = writer
        self.codec = "json"
        self.counters = counters

    def write_msg(self, msg: dict) -> None:
        data = bin_frame(msg) if self.codec == "bin" else _dumps(msg)
        self.writer.write(data)
        if self.counters is not None:
            self.counters.increment("rpc.bytes_tx", len(data))


class StreamWriter:
    """Handed to streaming handlers to push items to the subscriber."""

    def __init__(self, conn: _ConnState, req_id: int):
        self._conn = conn
        self._id = req_id
        self.closed = False

    async def send(self, item: Any) -> None:
        if self.closed:
            raise RpcTransportError("stream closed")
        try:
            self._conn.write_msg({"id": self._id, "item": item})
            await self._conn.writer.drain()
        except (ConnectionError, RuntimeError) as e:
            self.closed = True
            raise RpcTransportError(f"stream write failed: {e}") from e

    async def end(self) -> None:
        if not self.closed:
            self.closed = True
            try:
                self._conn.write_msg({"id": self._id, "end": True})
                await self._conn.writer.drain()
            except (ConnectionError, RuntimeError):
                pass


Handler = Callable[..., Awaitable[Any]]


class RpcServer:
    """Dispatches methods on incoming connections.

    register(name, fn): async fn(params_dict) -> jsonable result.
    register_stream(name, fn): async fn(params_dict, stream: StreamWriter);
    the stream stays open until fn returns or the client disconnects.

    `binary=True` (default) agrees to binary in ``_wire.hello``
    negotiations; False declines (replies ``{"codec": "json"}``) so the
    connection stays on JSON — the interop tests' "old peer". A truly
    pre-binary server answers the hello with a no-method error, which
    the client treats the same way.
    """

    def __init__(self, name: str = "rpc", counters=None, binary: bool = True):
        self.name = name
        self.counters = counters
        self.binary = binary
        self._methods: dict[str, Handler] = {}
        self._streams: dict[str, Handler] = {}
        self._server: asyncio.AbstractServer | None = None
        self._conn_tasks: set[asyncio.Task] = set()
        self.port: int | None = None

    def register(self, method: str, fn: Handler) -> None:
        self._methods[method] = fn

    def register_stream(self, method: str, fn: Handler) -> None:
        self._streams[method] = fn

    async def start(
        self, host: str = "127.0.0.1", port: int = 0, ssl=None
    ) -> int:
        """Bind and serve; returns the bound port (0 → ephemeral).
        Pass an `ssl.SSLContext` (see rpc.tls) for a TLS listener."""
        self._server = await asyncio.start_server(
            self._on_conn, host, port, limit=MAX_LINE, ssl=ssl
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        # cancel handlers BEFORE wait_closed(): since py3.12 wait_closed
        # blocks until every connection handler returns
        for t in list(self._conn_tasks):
            t.cancel()
        for t in list(self._conn_tasks):
            # swallows only t's own cancellation; one aimed at stop()
            # itself re-raises (OR005). cancel=False: all conn tasks
            # were cancelled above.
            await reap(t, cancel=False)
        self._conn_tasks.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        if task:
            self._conn_tasks.add(task)
        conn = _ConnState(writer, counters=self.counters)
        stream_tasks: list[asyncio.Task] = []
        try:
            while True:
                try:
                    kind, payload = await _read_frame(reader)
                except asyncio.IncompleteReadError:
                    break  # peer closed (or died mid-frame)
                except (WireFrameError, asyncio.LimitOverrunError,
                        ValueError):
                    # unrecoverable framing: the stream can't be
                    # resynced — drop THIS connection, keep serving
                    log.warning(
                        "%s: unrecoverable framing from peer", self.name
                    )
                    break
                if self.counters is not None:
                    self.counters.increment("rpc.bytes_rx", len(payload))
                try:
                    if kind == "bin":
                        msg = from_wire_bin(payload)
                    else:
                        msg = json.loads(payload)
                except ValueError:
                    # JSONDecodeError *or* UnicodeDecodeError *or*
                    # WireDecodeError: a corrupt payload inside intact
                    # framing — skip the frame, keep the connection
                    # (test_fuzz_wire::test_rpc_server_survives_garbage)
                    log.warning("%s: bad frame from peer", self.name)
                    continue
                if not isinstance(msg, dict):
                    log.warning("%s: non-object frame from peer", self.name)
                    continue
                method = msg.get("method")
                req_id = msg.get("id")
                params = msg.get("params") or {}
                if method == "_wire.hello":
                    # codec negotiation (docs/Wire.md): agree to binary
                    # when both sides support it, then switch OUR
                    # transmit codec; the client switches on seeing the
                    # reply. Reply goes out in the OLD codec.
                    codecs = (
                        params.get("codecs") if isinstance(params, dict)
                        else None
                    ) or []
                    agree = (
                        WIRE_CODEC_BIN
                        if self.binary and WIRE_CODEC_BIN in codecs
                        else "json"
                    )
                    if req_id is not None:
                        conn.write_msg({"id": req_id,
                                        "result": {"codec": agree}})
                        await writer.drain()
                    if agree == WIRE_CODEC_BIN:
                        conn.codec = "bin"
                        if self.counters is not None:
                            self.counters.increment("rpc.conns_binary")
                    continue
                if method in self._streams and req_id is not None:
                    sw = StreamWriter(conn, req_id)

                    async def run_stream(fn=self._streams[method], p=params, s=sw):
                        try:
                            await fn(p, s)
                        except RpcError:
                            pass
                        except asyncio.CancelledError:
                            raise  # conn teardown cancels us (OR005)
                        except Exception:  # noqa: BLE001
                            log.exception("%s: stream handler failed", self.name)
                        finally:
                            await s.end()

                    stream_tasks.append(asyncio.ensure_future(run_stream()))
                elif method in self._methods:
                    try:
                        result = await self._methods[method](params)
                        reply = {"id": req_id, "result": result}
                    except asyncio.CancelledError:
                        raise  # server stop cancels conn tasks (OR005)
                    except Exception as e:  # noqa: BLE001
                        log.exception("%s: handler %s failed", self.name, method)
                        reply = {"id": req_id, "error": f"{type(e).__name__}: {e}"}
                    if req_id is not None:
                        conn.write_msg(reply)
                        await writer.drain()
                elif req_id is not None:
                    conn.write_msg(
                        {"id": req_id, "error": f"no method {method!r}"}
                    )
                    await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except asyncio.CancelledError:
            raise
        finally:
            for t in stream_tasks:
                t.cancel()
            writer.close()
            if task:
                self._conn_tasks.discard(task)


class RpcClient:
    """One connection; concurrent calls multiplexed by request id.

    `negotiate=True` (default) sends a ``_wire.hello`` on connect and
    upgrades the connection to binary frames when the server agrees;
    against an old (JSON-only) server the hello fails cleanly and the
    connection stays on JSON.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        ssl=None,
        counters=None,
        negotiate: bool = True,
    ):
        self.host = host
        self.port = port
        self.ssl = ssl  # ssl.SSLContext (rpc.tls) or None for plaintext
        self.counters = counters
        self.negotiate = negotiate
        self._codec = "json"
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._next_id = 1
        self._pending: dict[int, asyncio.Future] = {}
        self._streams: dict[int, RQueue] = {}
        self._rx_task: asyncio.Task | None = None

    @property
    def connected(self) -> bool:
        return self._writer is not None

    @property
    def codec(self) -> str:
        """Negotiated transmit codec: "json" or "bin"."""
        return self._codec

    async def connect(self, timeout: float = 5.0) -> None:
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(
                self.host, self.port, limit=MAX_LINE, ssl=self.ssl
            ),
            timeout,
        )
        self._codec = "json"
        self._rx_task = guard_task(
            asyncio.ensure_future(self._rx_loop()), owner="rpc.client.rx"
        )
        if self.negotiate:
            try:
                res = await self.call(
                    "_wire.hello", {"codecs": [WIRE_CODEC_BIN]},
                    timeout=timeout,
                )
                if isinstance(res, dict) and res.get("codec") == WIRE_CODEC_BIN:
                    self._codec = "bin"
            except RpcError:
                # old server: no such method (or conn-level failure the
                # next real call will surface) — stay on JSON frames
                pass

    async def close(self) -> None:
        if self._rx_task:
            # swallows only the rx fiber's cancellation, not close()'s
            await reap(self._rx_task)
            self._rx_task = None
        if self._writer:
            self._writer.close()
            self._writer = None
        self._fail_all(RpcTransportError("client closed"))

    def _fail_all(self, err: Exception) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()
        for q in self._streams.values():
            # force: the sentinel must land even on a full queue
            q.put_nowait(_STREAM_ERR, force=True)
        self._streams.clear()

    def _write_msg(self, msg: dict) -> int:
        data = bin_frame(msg) if self._codec == "bin" else _dumps(msg)
        self._writer.write(data)
        if self.counters is not None:
            self.counters.increment("rpc.bytes_tx", len(data))
        return len(data)

    async def send_frame(self, frame: bytes) -> None:
        """Write one pre-encoded wire frame (the serialize-once flood
        path: the SAME immutable frame is handed to every peer client).
        The frame must match this connection's negotiated codec."""
        if self._writer is None:
            raise RpcTransportError("not connected")
        self._writer.write(frame)
        if self.counters is not None:
            self.counters.increment("rpc.bytes_tx", len(frame))
        await self._writer.drain()

    async def _rx_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                try:
                    kind, payload = await _read_frame(self._reader)
                except asyncio.IncompleteReadError:
                    break
                if self.counters is not None:
                    self.counters.increment("rpc.bytes_rx", len(payload))
                msg = (
                    from_wire_bin(payload)
                    if kind == "bin"
                    else json.loads(payload)
                )
                if not isinstance(msg, dict):
                    continue
                req_id = msg.get("id")
                if "item" in msg and req_id in self._streams:
                    try:
                        # backpressured put: a slow consumer stalls line
                        # reads (and via TCP, the sender) at STREAM_BUF
                        await asyncio.wait_for(
                            self._streams[req_id].put(msg["item"]),
                            STREAM_STALL_S,
                        )
                    except QueueClosedError:
                        # consumer abandoned the stream (gen() closed
                        # its queue) — possibly while we were blocked
                        # at the bound; drop the item and move on
                        self._streams.pop(req_id, None)
                    except asyncio.TimeoutError:
                        # consumer sat at the bound for STREAM_STALL_S
                        # without draining — or the generator was never
                        # even iterated (its cleanup can't run). Break
                        # THAT stream (its next get raises) rather than
                        # stall every reply on this client forever.
                        dead = self._streams.pop(req_id, None)
                        if dead is not None:
                            dead.close()
                elif msg.get("end") and req_id in self._streams:
                    self._streams.pop(req_id).put_nowait(
                        _STREAM_END, force=True
                    )
                elif req_id in self._streams and (
                    "error" in msg or "result" in msg
                ):
                    # server treated the subscription as a plain call (bad
                    # method / non-stream handler): fail the stream instead
                    # of hanging the subscriber forever
                    self._streams.pop(req_id).put_nowait(
                        _STREAM_ERR, force=True
                    )
                elif req_id in self._pending:
                    fut = self._pending.pop(req_id)
                    if not fut.done():
                        if "error" in msg:
                            fut.set_exception(RpcError(msg["error"]))
                        else:
                            fut.set_result(msg.get("result"))
        except (ConnectionError, ValueError, asyncio.IncompleteReadError,
                asyncio.LimitOverrunError):
            # ValueError covers JSONDecodeError, UnicodeDecodeError AND
            # WireDecodeError/WireFrameError — a corrupt frame from a
            # hostile/broken server takes the clean connection-lost
            # path, same as the server side. LimitOverrunError (NOT a
            # ValueError) is readuntil's overlong-JSON-line signal: the
            # old readline() converted it to ValueError, _read_frame's
            # readuntil raises it directly
            pass
        except asyncio.CancelledError:
            raise
        finally:
            self._fail_all(RpcTransportError("connection lost"))

    async def call(
        self, method: str, params: Any = None, timeout: float = 30.0
    ) -> Any:
        if self._writer is None:
            raise RpcTransportError("not connected")
        req_id = self._next_id
        self._next_id += 1
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[req_id] = fut
        try:
            self._write_msg(
                {"id": req_id, "method": method, "params": params or {}}
            )
            await self._writer.drain()
        except BaseException as e:
            # transport failure mid-send (e.g. a TLS reject surfacing at
            # drain): deregister the slot AND settle the future — a
            # racing _fail_all may already have parked an exception on
            # it, which would otherwise never be retrieved
            self._pending.pop(req_id, None)
            if fut.done():
                fut.exception()
            else:
                fut.cancel()
            if isinstance(e, ConnectionError):
                # callers see one exception type for "call failed",
                # whether the transport died before, during or after
                # the send (RpcError docstring contract)
                raise RpcTransportError(f"transport failed: {e}") from e
            raise
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError as e:
            self._pending.pop(req_id, None)  # don't leak the slot
            raise RpcTransportError(
                f"call {method!r} timed out after {timeout}s"
            ) from e

    async def notify(self, method: str, params: Any = None) -> int:
        """Fire-and-forget. Returns the frame size written, so callers
        doing byte accounting (KvStore flood_bytes) get the real wire
        cost on either codec."""
        if self._writer is None:
            raise RpcTransportError("not connected")
        n = self._write_msg({"method": method, "params": params or {}})
        await self._writer.drain()
        return n

    async def subscribe(
        self, method: str, params: Any = None
    ) -> AsyncIterator[Any]:
        """Server-push stream; iterate until the server ends it."""
        if self._writer is None:
            raise RpcTransportError("not connected")
        req_id = self._next_id
        self._next_id += 1
        # messaging-seam queue (OR004): bounded, block policy — the rx
        # loop's awaited put is the backpressure point
        q: RQueue = RQueue(
            name=f"rpc.stream.{req_id}", maxsize=STREAM_BUF, policy="block"
        )
        self._streams[req_id] = q
        self._write_msg({"id": req_id, "method": method, "params": params or {}})
        await self._writer.drain()

        async def gen():
            try:
                while True:
                    try:
                        item = await q.get()
                    except QueueClosedError:
                        # the rx loop declared this consumer stalled
                        # (STREAM_STALL_S at the bound) and broke the
                        # stream to protect the rest of the client
                        raise RpcTransportError(
                            "stream dropped: consumer stalled past "
                            "the buffer bound"
                        ) from None
                    if item is _STREAM_END:
                        return
                    if item is _STREAM_ERR:
                        raise RpcTransportError("stream broken")
                    yield item
            finally:
                # consumer stopped iterating (break / aclose / GC):
                # deregister AND close the queue, waking an rx loop
                # blocked on `await q.put(...)` — otherwise one
                # abandoned stream at the bound would stall every
                # reply on this client forever
                if self._streams.pop(req_id, None) is not None:
                    q.close()

        return gen()


class _Sentinel:
    pass


_STREAM_END = _Sentinel()
_STREAM_ERR = _Sentinel()
