"""The breeze command tree (reference: openr/py/openr/cli/commands/ †).

Each command opens one RPC connection to the node's ctrl server, makes
the query, pretty-prints, and exits — the same stateless model as the
reference's thrift-per-invocation CLI. Output is plain text tables
(reference: breeze's printing.py table helpers †).
"""

from __future__ import annotations

import asyncio
import json

import click

from openr_tpu.common.constants import (
    ADJ_DB_MARKER,
    CTRL_PORT,
    PREFIX_DB_MARKER,
    parse_adj_key,
)
from openr_tpu.rpc import RpcClient, RpcError
from openr_tpu.types.serde import from_wire
from openr_tpu.types.topology import AdjacencyDatabase, PrefixDatabase


# ------------------------------------------------------------------ plumbing


def _run(ctx: click.Context, method: str, params: dict | None = None):
    """One connect → call → close round trip."""
    host = ctx.obj["host"]
    port = ctx.obj["port"]

    async def go():
        cli_ = RpcClient(host=host, port=port, ssl=ctx.obj.get("ssl"))
        await cli_.connect(timeout=ctx.obj["timeout"])
        try:
            return await cli_.call(method, params or {}, timeout=ctx.obj["timeout"])
        finally:
            await cli_.close()

    try:
        return asyncio.run(go())
    except (ConnectionError, OSError) as e:
        raise click.ClickException(
            f"cannot reach ctrl server at {host}:{port}: {e}"
        ) from e
    except RpcError as e:
        raise click.ClickException(f"rpc {method} failed: {e}") from e


def _value_bytes(raw_value: dict) -> bytes | None:
    v = raw_value.get("value")
    if isinstance(v, dict) and "__bytes__" in v:
        return bytes.fromhex(v["__bytes__"])
    return None


def _table(rows: list[list], headers: list[str]) -> str:
    widths = [
        max(len(str(headers[i])), *(len(str(r[i])) for r in rows)) if rows
        else len(str(headers[i]))
        for i in range(len(headers))
    ]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*headers), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*(str(c) for c in r)) for r in rows]
    return "\n".join(lines)


def _nh_str(nh: dict) -> str:
    s = f"{nh.get('neighbor_node') or nh.get('address')}%{nh.get('if_name')}"
    if nh.get("weight"):
        s += f" w={nh['weight']}"
    act = nh.get("mpls_action")
    if act:
        labels = act.get("push_labels") or []
        kind = {0: "PUSH", 1: "SWAP", 2: "PHP", 3: "POP"}.get(
            act.get("action"), "?"
        )
        s += f" mpls {kind}{labels if labels else ''}"
    return s


# ---------------------------------------------------------------------- root


@click.group()
@click.option("--host", default="127.0.0.1", show_default=True,
              help="ctrl server host")
@click.option("--port", default=CTRL_PORT, show_default=True, type=int,
              help="ctrl server port")
@click.option("--timeout", default=10.0, show_default=True, type=float)
@click.option("--cacert", default="", help="CA bundle for a TLS ctrl server")
@click.option("--cert", default="", help="client certificate (mutual TLS)")
@click.option("--key", default="", help="client key (mutual TLS)")
@click.pass_context
def cli(ctx, host, port, timeout, cacert, cert, key):
    """breeze — query and control a running openr_tpu node."""
    ctx.ensure_object(dict)
    ssl_ctx = None
    if cacert:
        from openr_tpu.config.config import TlsConfig
        from openr_tpu.rpc.tls import client_ssl_context

        ssl_ctx = client_ssl_context(
            TlsConfig(
                enabled=True, ca_path=cacert, cert_path=cert, key_path=key
            )
        )
    ctx.obj.update(host=host, port=port, timeout=timeout, ssl=ssl_ctx)


@cli.command()
@click.pass_context
def status(ctx):
    """Node name + initialization gates (KVSTORE_SYNCED → RIB_COMPUTED →
    FIB_SYNCED)."""
    name = _run(ctx, "get_my_node_name")
    st = _run(ctx, "get_initialization_status")
    click.echo(f"node: {name}")
    for gate in ("KVSTORE_SYNCED", "RIB_COMPUTED", "FIB_SYNCED", "INITIALIZED"):
        click.echo(f"  {gate}: {'pass' if st.get(gate) else 'PENDING'}")


@cli.command()
@click.pass_context
def version(ctx):
    """Node software version + the queried node's name (reference:
    breeze openr version †)."""
    from importlib.metadata import PackageNotFoundError
    from importlib.metadata import version as _pkg_version

    try:
        v = _pkg_version("openr-tpu")
    except PackageNotFoundError:
        # source checkout: read pyproject directly; a non-source install
        # without package metadata has neither — report "unknown", don't
        # crash (ADVICE: uncaught FileNotFoundError)
        import re
        from pathlib import Path

        try:
            txt = (
                Path(__file__).resolve().parents[2] / "pyproject.toml"
            ).read_text()
        except OSError:
            v = "unknown"
        else:
            m = re.search(r'^version = "([^"]+)"', txt, re.M)
            v = m.group(1) if m else "unknown"
    name = _run(ctx, "get_my_node_name")
    click.echo(f"openr_tpu {v} (node {name})")
    from openr_tpu.types.wirelock import locked_version

    click.echo(f"wire schema lock: v{locked_version()}")


@cli.command("tech-support")
@click.pass_context
def tech_support(ctx):
    """One-shot diagnostic roll-up (reference: breeze tech-support †):
    identity, init gates, links, adjacencies, route/prefix counts, key
    counters, and the validate verdict — everything a bug report needs
    in one paste."""
    name = _run(ctx, "get_my_node_name")
    st = _run(ctx, "get_initialization_status")
    click.echo(f"== node ==\n{name}")
    click.echo("== initialization ==")
    for gate, ok in sorted(st.items()):
        click.echo(f"  {gate}: {'pass' if ok else 'PENDING'}")

    ifaces = _run(ctx, "get_interfaces")
    click.echo("== links ==")
    click.echo(f"  node overloaded: {ifaces['is_overloaded']}")
    for i in ifaces["interfaces"]:
        click.echo(
            f"  {i['name']}: up={i.get('is_up', True)} "
            f"adjacencies={len(i.get('adjacencies', []))}"
        )

    adj = _run(ctx, "get_decision_adjacency_dbs")
    for area, dbs in sorted(adj.items()):
        n_adj = sum(len(db["adjacencies"]) for db in dbs)
        click.echo(
            f"== lsdb area {area} ==\n"
            f"  {len(dbs)} nodes, {n_adj} adjacencies"
        )

    rdb = _run(ctx, "get_route_db_computed")
    prog = _run(ctx, "get_route_db_programmed")
    click.echo(
        "== routes ==\n"
        f"  computed: {len(rdb['unicast_routes'])} unicast, "
        f"{len(rdb['mpls_routes'])} mpls\n"
        f"  programmed: {len(prog['unicast_routes'])} unicast, "
        f"{len(prog['mpls_routes'])} mpls"
    )
    advertised = _run(ctx, "get_advertised_prefixes")
    click.echo(f"  advertised prefixes: {len(advertised)}")

    counters = _run(ctx, "get_counters")
    click.echo("== counters (non-zero) ==")
    for k, v in sorted(counters.items()):
        if v:
            click.echo(f"  {k}: {v}")

    res = _run(ctx, "validate")
    click.echo("== validate ==")
    bad = [c for c in res["checks"] if not c["pass"]]
    for c in res["checks"]:
        mark = "PASS" if c["pass"] else "FAIL"
        click.echo(f"  [{mark}] {c['name']}")
    click.echo("all checks passed" if not bad else f"{len(bad)} FAILING")
    if bad:
        raise SystemExit(1)


@cli.command()
@click.pass_context
def validate(ctx):
    """End-to-end health cross-checks; exit 1 on any failure
    (reference: openr validate †)."""
    res = _run(ctx, "validate")
    for c in res["checks"]:
        mark = "PASS" if c["pass"] else "FAIL"
        detail = f"  ({c['detail']})" if c.get("detail") else ""
        click.echo(f"[{mark}] {c['name']}{detail}")
    if not res["pass"]:
        raise SystemExit(1)
    click.echo("all checks passed")


# ---------------------------------------------------------------------- wire


@cli.group()
def wire():
    """Wire/persist schema lock introspection (docs/Wire.md "Schema
    evolution")."""


@wire.command("schema")
@click.option("--dump", is_flag=True,
              help="print the node's full schema JSON instead of diffing")
@click.pass_context
def wire_schema(ctx, dump):
    """The queried node's LIVE wire/persist schema diffed against the
    operator's committed lock — run before an upgrade so version skew
    shows up as a named field-level report, not as mis-decoded frames.
    Exits 1 when the diff contains breaking drift."""
    from openr_tpu.types import wirelock

    res = _run(ctx, "get_wire_schema")
    if dump:
        click.echo(json.dumps(res["schema"], indent=2, sort_keys=True))
        return
    click.echo(
        f"node {res['node']}: lock v{res['lock_version']}, "
        f"{len(res['schema']['types'])} wire types"
    )
    lock = wirelock.load_lock()
    if lock is None:
        raise click.ClickException(
            "no local wire_schema.lock.json to diff against"
        )
    click.echo(f"local lock: v{lock['lock_version']}")
    drifts = wirelock.diff_schemas(lock, res["schema"])
    if not drifts:
        click.echo("in sync: no drift between node schema and local lock")
        return
    breaking, benign = wirelock.classify(drifts)
    for d in breaking + benign:
        click.echo(str(d))
    click.echo(f"{len(breaking)} breaking, {len(benign)} benign")
    if breaking:
        raise SystemExit(1)


# --------------------------------------------------------------------- spark


@cli.group()
def spark():
    """Neighbor discovery FSM view (reference: breeze spark †)."""


@spark.command("neighbors")
@click.pass_context
def spark_neighbors(ctx):
    """Live discovery state per neighbor, pre-LinkMonitor (FSM state,
    hold, RTT, last-heard)."""
    res = _run(ctx, "get_spark_neighbors")
    rows = [
        [n["node"], n["local_if"], n["remote_if"], n["state"], n["area"],
         n["hold_time_ms"], n["rtt_us"],
         n["last_heard_ms_ago"] if n["last_heard_ms_ago"] is not None
         else "-"]
        for n in sorted(res["neighbors"], key=lambda n: n["node"])
    ]
    click.echo(_table(
        rows,
        ["neighbor", "local-if", "remote-if", "state", "area", "hold-ms",
         "rtt-us", "heard-ms-ago"],
    ))


# ------------------------------------------------------------------- kvstore


@cli.group()
def kvstore():
    """KvStore inspection (reference: breeze kvstore †)."""


@kvstore.command("keys")
@click.option("--prefix", default="", help="key prefix filter")
@click.option("--area", default=None)
@click.pass_context
def kvstore_keys(ctx, prefix, area):
    """List keys with version/originator/ttl."""
    res = _run(ctx, "dump_kvstore", {"prefix": prefix, "area": area})
    rows = []
    for k, v in sorted(res["key_vals"].items()):
        ttl = v.get("ttl")
        rows.append([k, v.get("version"), v.get("originator_id"),
                     "inf" if ttl == -1 else ttl])
    click.echo(_table(rows, ["key", "version", "originator", "ttl_ms"]))


@kvstore.command("keyvals")
@click.argument("keys", nargs=-1, required=True)
@click.option("--area", default=None)
@click.pass_context
def kvstore_keyvals(ctx, keys, area):
    """Dump raw values for specific keys (decoded when the key is a known
    LSDB object)."""
    res = _run(ctx, "get_kvstore_keyvals", {"keys": list(keys), "area": area})
    for k, v in sorted(res["key_vals"].items()):
        click.echo(f"> {k} (v{v.get('version')}, {v.get('originator_id')})")
        blob = _value_bytes(v)
        if blob is None:
            click.echo("  <no value>")
            continue
        try:
            if k.startswith(ADJ_DB_MARKER):
                click.echo(json.dumps(
                    _jsonable_wire(blob, AdjacencyDatabase), indent=2))
            elif k.startswith(PREFIX_DB_MARKER):
                click.echo(json.dumps(
                    _jsonable_wire(blob, PrefixDatabase), indent=2))
            else:
                click.echo(f"  {blob!r}")
        except Exception:  # noqa: BLE001 — fall back to raw bytes
            click.echo(f"  {blob!r}")


def _jsonable_wire(blob: bytes, cls):
    from openr_tpu.types.serde import to_jsonable

    return to_jsonable(from_wire(blob, cls))


@kvstore.command("adj")
@click.option("--area", default=None)
@click.pass_context
def kvstore_adj(ctx, area):
    """Adjacency databases as advertised in the KvStore."""
    res = _run(ctx, "dump_kvstore", {"prefix": ADJ_DB_MARKER, "area": area})
    rows = []
    for k, v in sorted(res["key_vals"].items()):
        node = parse_adj_key(k)
        blob = _value_bytes(v)
        if node is None or blob is None:
            continue
        db = from_wire(blob, AdjacencyDatabase)
        for adj in db.adjacencies:
            rows.append([
                node, adj.other_node_name, adj.if_name, adj.other_if_name,
                adj.metric, "overloaded" if db.is_overloaded else "",
            ])
    click.echo(_table(
        rows, ["node", "neighbor", "local-if", "remote-if", "metric", "flags"]
    ))


@kvstore.command("prefixes")
@click.option("--area", default=None)
@click.pass_context
def kvstore_prefixes(ctx, area):
    """Prefix databases as advertised in the KvStore."""
    res = _run(ctx, "dump_kvstore", {"prefix": PREFIX_DB_MARKER, "area": area})
    rows = []
    for k, v in sorted(res["key_vals"].items()):
        blob = _value_bytes(v)
        if blob is None:
            continue
        db = from_wire(blob, PrefixDatabase)
        for e in db.prefix_entries:
            rows.append([db.this_node_name, str(e.prefix),
                         e.forwarding_type.name, e.forwarding_algorithm.name])
    click.echo(_table(rows, ["node", "prefix", "fwd-type", "fwd-algo"]))


@kvstore.command("peers")
@click.option("--area", default=None)
@click.pass_context
def kvstore_peers(ctx, area):
    """Flooding peers per area."""
    res = _run(ctx, "get_kvstore_peers", {"area": area})
    for p in res["peers"]:
        click.echo(p)


@kvstore.command("set-key")
@click.argument("key")
@click.argument("value")
@click.option("--area", default=None)
@click.option("--ttl", default=None, type=int, help="ttl ms (default: ∞)")
@click.option(
    "--version", default=None, type=int,
    help="explicit version (default: current+1, so the write wins)",
)
@click.pass_context
def kvstore_set_key(ctx, key, value, area, ttl, version):
    """Debug write: originate KEY=VALUE as 'breeze' (reference: breeze
    kvstore set-key †). Defaults to version current+1 so the merge total
    order (version, originator, hash) accepts and floods it."""
    from openr_tpu.types.kvstore import TTL_INFINITY

    if version is None:
        cur = _run(
            ctx, "get_kvstore_keyvals", {"keys": [key], "area": area}
        )["key_vals"]
        version = int(cur.get(key, {}).get("version", 0)) + 1
    raw = {
        "version": version,
        "originator_id": "breeze",
        "value": {"__bytes__": value.encode().hex()},
        "ttl": ttl if ttl is not None else TTL_INFINITY,
        "ttl_version": 0,
    }
    res = _run(
        ctx, "set_kvstore_keyvals", {"key_vals": {key: raw}, "area": area}
    )
    if not res.get("accepted", {}).get(key, res.get("ok")):
        click.echo(
            f"REJECTED: {key} v{version} lost the merge (key moved "
            "underneath us — retry without --version)"
        )
        raise SystemExit(1)
    click.echo(f"set {key} v{version}")


@kvstore.command("erase-key")
@click.argument("key")
@click.option("--area", default=None)
@click.option("--ttl", default=1000, show_default=True, type=int,
              help="tombstone lifetime ms")
@click.pass_context
def kvstore_erase_key(ctx, key, area, ttl):
    """Debug erase: re-originate KEY at version current+1 with a short
    finite ttl, so the winning tombstone floods network-wide and then
    expires out of every store (reference: breeze kvstore erase-key †,
    same advertise-then-expire mechanism)."""
    cur = _run(
        ctx, "get_kvstore_keyvals", {"keys": [key], "area": area}
    )["key_vals"]
    if key not in cur:
        click.echo(f"{key}: not present")
        raise SystemExit(1)
    raw = {
        "version": int(cur[key].get("version", 0)) + 1,
        "originator_id": "breeze",
        "value": cur[key].get("value"),
        "ttl": ttl,
        "ttl_version": 0,
    }
    res = _run(
        ctx, "set_kvstore_keyvals", {"key_vals": {key: raw}, "area": area}
    )
    if not res.get("accepted", {}).get(key, res.get("ok")):
        click.echo(f"REJECTED: {key} moved underneath us — retry")
        raise SystemExit(1)
    click.echo(f"erase {key}: tombstone v{raw['version']} ttl={ttl}ms")


@kvstore.command("alloc")
@click.option("--area", default=None)
@click.pass_context
def kvstore_alloc(ctx, area):
    """Elected prefix-allocator claims (reference: breeze kvstore
    alloc †): slot index → owning node, from the `allocprefix:` range
    election keys."""
    res = _run(ctx, "dump_kvstore", {"prefix": "allocprefix:", "area": area})
    rows = []
    for k, v in sorted(res["key_vals"].items()):
        owner = _value_bytes(v)
        rows.append([
            k.split(":", 1)[1],
            owner.decode(errors="replace") if owner else "?",
            v.get("version"),
        ])
    click.echo(_table(rows, ["slot", "owner", "version"]))


@kvstore.command("snoop")
@click.option("--prefix", default="", help="key prefix filter")
@click.option("--area", default=None)
@click.option("--duration", default=0.0, show_default=True, type=float,
              help="stop after N seconds (0 = until interrupted)")
@click.pass_context
def kvstore_snoop(ctx, prefix, area, duration):
    """Live-watch KvStore publications (reference: breeze kvstore
    snoop †): prints each flooded delta as it arrives. Ctrl-C (or
    --duration) to stop."""

    async def go():
        cli_ = RpcClient(
            host=ctx.obj["host"], port=ctx.obj["port"],
            ssl=ctx.obj.get("ssl"),
        )
        await cli_.connect(timeout=ctx.obj["timeout"])
        try:
            stream = await cli_.subscribe(
                "subscribe_kvstore",
                {"prefix": prefix, "area": area, "snapshot": False},
            )
            loop = asyncio.get_running_loop()
            t_end = loop.time() + duration if duration else None
            while True:
                timeout = (
                    max(0.0, t_end - loop.time()) if t_end else None
                )
                try:
                    item = await asyncio.wait_for(
                        anext(stream), timeout=timeout
                    )
                except (
                    # asyncio.TimeoutError is NOT builtin TimeoutError
                    # until 3.11 — catching only the builtin crashed
                    # --duration expiry on 3.10
                    asyncio.TimeoutError,
                    TimeoutError,
                    StopAsyncIteration,
                ):
                    return
                for k, v in sorted(item.get("key_vals", {}).items()):
                    click.echo(
                        f"{k} v{v.get('version')} "
                        f"from {v.get('originator_id')} "
                        f"ttl_version={v.get('ttl_version')}"
                    )
        finally:
            await cli_.close()

    try:
        asyncio.run(go())
    except KeyboardInterrupt:
        pass


@kvstore.command("floodtopo")
@click.option("--area", default=None)
@click.pass_context
def kvstore_floodtopo(ctx, area):
    """DUAL flood-optimization spanning tree (reference: breeze kvstore
    summary / getSptInfos †)."""
    res = _run(ctx, "get_kvstore_flood_topo", {"area": area})
    if not res.get("enabled"):
        click.echo("flood optimization: disabled")
        return
    click.echo(f"flood root : {res.get('flood_root')}")
    mode = res.get("mode", "spt")
    click.echo(
        f"flood peers: {','.join(res.get('flood_peers', [])) or '-'}"
        f" ({'tree' if mode == 'spt' else 'ALL peers — tree not formed'})"
    )
    rows = [
        [r, s["dist"], s["parent"] or "-", s["state"],
         ",".join(s["children"]) or "-"]
        for r, s in sorted(res.get("roots", {}).items())
    ]
    click.echo(_table(rows, ["root", "dist", "parent", "state", "children"]))


@kvstore.command("areas")
@click.pass_context
def kvstore_areas(ctx):
    """Per-area key/peer summary (reference: getKvStoreAreaSummary †)."""
    res = _run(ctx, "get_kvstore_areas")
    rows = [
        [a, info["num_keys"], ",".join(info["peers"]) or "-"]
        for a, info in sorted(res.items())
    ]
    click.echo(_table(rows, ["area", "keys", "peers"]))


# ------------------------------------------------------------------ decision


@cli.group()
def decision():
    """Computed-RIB queries (reference: breeze decision †)."""


@decision.command("routes")
@click.pass_context
def decision_routes(ctx):
    """Routes computed by Decision (pre-FIB)."""
    res = _run(ctx, "get_route_db_computed")
    rows = [
        [r["dest"], r.get("igp_cost", ""),
         " ".join(_nh_str(nh) for nh in r["nexthops"])]
        for r in sorted(res["unicast_routes"], key=lambda r: r["dest"])
    ]
    click.echo(_table(rows, ["prefix", "cost", "nexthops"]))
    if res["mpls_routes"]:
        click.echo("")
        rows = [
            [r["top_label"], " ".join(_nh_str(nh) for nh in r["nexthops"])]
            for r in sorted(res["mpls_routes"], key=lambda r: r["top_label"])
        ]
        click.echo(_table(rows, ["label", "nexthops"]))


@decision.command("adj")
@click.pass_context
def decision_adj(ctx):
    """Decision's LSDB adjacency view."""
    res = _run(ctx, "get_decision_adjacency_dbs")
    rows = []
    for area, dbs in sorted(res.items()):
        for db in dbs:
            for adj in db["adjacencies"]:
                rows.append([area, db["this_node_name"],
                             adj["other_node_name"], adj["metric"]])
    click.echo(_table(rows, ["area", "node", "neighbor", "metric"]))


@decision.command("path")
@click.argument("dst")
@click.option("--src", default="", help="source node (default: this node)")
@click.option("--area", default="", help="restrict to one area")
@click.pass_context
def decision_path(ctx, dst, src, area):
    """Shortest path to DST from Decision's LSDB (reference: breeze
    decision path †)."""
    params = {"dst": dst}
    if src:
        params["src"] = src
    if area:
        params["area"] = area
    res = _run(ctx, "get_spf_path", params)
    if not res.get("reachable"):
        click.echo(f"{res.get('src', src)} -> {dst}: unreachable")
        raise SystemExit(1)
    hops = res["hops"]
    metrics = res.get("hop_metrics", [])
    rows = [
        [i, u, metrics[i] if i < len(metrics) else ""]
        for i, u in enumerate(hops)
    ]
    click.echo(_table(rows, ["hop", "node", "metric-to-next"]))
    click.echo(f"total cost {res['cost']} ({len(hops) - 1} hops)")


@decision.command("received-routes")
@click.pass_context
def decision_received(ctx):
    """Per-prefix advertising nodes (PrefixState view)."""
    res = _run(ctx, "get_received_routes")
    rows = []
    for area, prefixes in sorted(res.items()):
        for pfx, nodes in sorted(prefixes.items()):
            rows.append([area, pfx, ",".join(nodes)])
    click.echo(_table(rows, ["area", "prefix", "advertised-by"]))


@decision.command("rib-policy")
@click.option("--set", "set_file", default=None,
              type=click.Path(exists=True),
              help="install the RibPolicy from this JSON file")
@click.pass_context
def decision_rib_policy(ctx, set_file):
    """Show — or with --set FILE, install — the RibPolicy (reference:
    breeze decision rib-policy [--set] †). The file holds the
    `policy.RibPolicy` JSON shape: {"statements": [{"name",
    "match_prefixes", "match_tags", "default_weight",
    "area_to_weight", "neighbor_to_weight"}], "ttl_secs": N}."""
    if set_file:
        with open(set_file) as f:
            policy = json.load(f)
        _run(ctx, "set_rib_policy", {"policy": policy})
        click.echo(f"rib policy installed from {set_file}")
        return
    res = _run(ctx, "get_rib_policy")
    if not res.get("policy"):
        click.echo("no rib policy installed")
        return
    click.echo(json.dumps(res, indent=2, sort_keys=True))


# ----------------------------------------------------------------------- fib


@cli.group()
def fib():
    """Programmed-route queries (reference: breeze fib †)."""


@fib.command("routes")
@click.pass_context
def fib_routes(ctx):
    """Routes programmed into the dataplane."""
    res = _run(ctx, "get_route_db_programmed")
    rows = [
        [r["dest"], " ".join(_nh_str(nh) for nh in r["nexthops"])]
        for r in sorted(res["unicast_routes"], key=lambda r: r["dest"])
    ]
    click.echo(_table(rows, ["prefix", "nexthops"]))


@fib.command("counters")
@click.pass_context
def fib_counters(ctx):
    res = _run(ctx, "get_counters", {"prefix": "fib."})
    for k, v in sorted(res.items()):
        click.echo(f"{k}: {v:g}")


@fib.command("add")
@click.argument("prefix")
@click.argument("nexthops", nargs=-1, required=True)
@click.option("--metric", default=1, show_default=True, type=int)
@click.pass_context
def fib_add(ctx, prefix, nexthops, metric):
    """Manually program PREFIX via NEXTHOPS (each `ADDR` or `ADDR%IF`)
    under the static client table — bypasses Decision; for platform
    debugging (reference: breeze fib add-route †)."""
    nhs = []
    for nh in nexthops:
        addr, _, ifn = nh.partition("%")
        nhs.append({"address": addr, "if_name": ifn, "metric": metric})
    res = _run(ctx, "fib_add_unicast",
               {"routes": [{"prefix": prefix, "nexthops": nhs}]})
    click.echo(f"added {res['added']} route(s) to the static table")


@fib.command("del")
@click.argument("prefixes", nargs=-1, required=True)
@click.pass_context
def fib_del(ctx, prefixes):
    """Remove manually-programmed PREFIXES from the static client table
    (reference: breeze fib del-route †)."""
    res = _run(ctx, "fib_del_unicast", {"prefixes": list(prefixes)})
    # both backends treat delete-of-missing as success, so the count is
    # the REQUEST size, not confirmed removals (review finding)
    click.echo(
        f"requested deletion of {res['deleted']} prefix(es) "
        "from the static table"
    )


@fib.command("validate")
@click.pass_context
def fib_validate_cmd(ctx):
    """Compare Fib's programmed book against an actual FibService dump
    (reference: breeze fib validate †); exit 1 on divergence."""
    res = _run(ctx, "fib_validate")
    click.echo(
        f"book: {res['book_unicast']} unicast / {res['book_mpls']} mpls; "
        f"dataplane: {res['dataplane_unicast']} / {res['dataplane_mpls']}"
    )
    for label, items in (
        ("missing in dataplane", res["missing_in_dataplane"]),
        ("extra in dataplane", res["extra_in_dataplane"]),
        ("missing mpls", res["missing_mpls"]),
        ("extra mpls", res["extra_mpls"]),
    ):
        if items:
            click.echo(f"  {label}: {items[:10]}")
    if not res["pass"]:
        click.echo("FIB DIVERGED")
        raise SystemExit(1)
    click.echo("fib matches the dataplane")


@fib.command("static-routes")
@click.option("--client-id", default=None, type=int,
              help="FibService client table (default: the static table)")
@click.pass_context
def fib_static_routes(ctx, client_id):
    """Dump a FibService table by client id (default: the static table
    `fib add` writes)."""
    params = {} if client_id is None else {"client_id": client_id}
    res = _run(ctx, "get_fib_client_routes", params)
    rows = [
        [r["dest"], " ".join(_nh_str(nh) for nh in r["nexthops"])]
        for r in sorted(res["unicast_routes"], key=lambda r: str(r["dest"]))
    ]
    click.echo(_table(rows, ["prefix", "nexthops"]))


# ------------------------------------------------------------------------ lm


@cli.group()
def lm():
    """LinkMonitor state + overload / metric control (reference: breeze lm †)."""


@lm.command("links")
@click.pass_context
def lm_links(ctx):
    res = _run(ctx, "get_interfaces")
    click.echo(
        f"node {res['node']}"
        + (" [OVERLOADED]" if res["is_overloaded"] else "")
    )
    rows = []
    for i in res["interfaces"]:
        nbrs = ",".join(a["neighbor"] for a in i["adjacencies"]) or "-"
        state = "up" if i["is_up"] else "DOWN"
        if i.get("is_overloaded"):
            state += " DRAINED"
        rows.append([
            i["name"], state,
            i["metric_override"] if i["metric_override"] is not None else "",
            nbrs,
        ])
    click.echo(_table(rows, ["interface", "state", "metric-ovr", "neighbors"]))


@lm.command("set-node-overload")
@click.pass_context
def lm_set_overload(ctx):
    _run(ctx, "set_node_overload", {"overload": True})
    click.echo("node overload SET")


@lm.command("unset-node-overload")
@click.pass_context
def lm_unset_overload(ctx):
    _run(ctx, "set_node_overload", {"overload": False})
    click.echo("node overload UNSET")


@lm.command("set-link-metric")
@click.argument("interface")
@click.argument("metric", type=int)
@click.pass_context
def lm_set_link_metric(ctx, interface, metric):
    _run(ctx, "set_interface_metric", {"interface": interface, "metric": metric})
    click.echo(f"metric override {metric} set on {interface}")


@lm.command("unset-link-metric")
@click.argument("interface")
@click.pass_context
def lm_unset_link_metric(ctx, interface):
    _run(ctx, "set_interface_metric", {"interface": interface, "metric": None})
    click.echo(f"metric override cleared on {interface}")


@lm.command("set-link-overload")
@click.argument("interface")
@click.pass_context
def lm_set_link_overload(ctx, interface):
    """Soft-drain one link: advertised with is_overloaded=True, every
    solver routes around it while the adjacency stays up (reference:
    breeze lm set-link-overload †)."""
    _run(ctx, "set_interface_overload", {"interface": interface})
    click.echo(f"link overload set on {interface}")


@lm.command("unset-link-overload")
@click.argument("interface")
@click.pass_context
def lm_unset_link_overload(ctx, interface):
    _run(
        ctx, "set_interface_overload",
        {"interface": interface, "overload": False},
    )
    click.echo(f"link overload cleared on {interface}")


# ------------------------------------------------------------------ prefixmgr


@cli.group()
def prefixmgr():
    """Prefix origination (reference: breeze prefixmgr †)."""


@prefixmgr.command("view")
@click.pass_context
def prefixmgr_view(ctx):
    res = _run(ctx, "get_advertised_prefixes")
    rows = [
        [pfx, e["forwarding_type"], e["forwarding_algorithm"],
         ",".join(e.get("tags") or [])]
        for pfx, e in sorted(res.items())
    ]
    click.echo(_table(rows, ["prefix", "fwd-type", "fwd-algo", "tags"]))


@prefixmgr.command("advertise")
@click.argument("prefixes", nargs=-1, required=True)
@click.pass_context
def prefixmgr_advertise(ctx, prefixes):
    res = _run(ctx, "advertise_prefixes", {"prefixes": list(prefixes)})
    click.echo(f"advertised {res['advertised']} prefix(es)")


@prefixmgr.command("withdraw")
@click.argument("prefixes", nargs=-1, required=True)
@click.pass_context
def prefixmgr_withdraw(ctx, prefixes):
    res = _run(ctx, "withdraw_prefixes", {"prefixes": list(prefixes)})
    click.echo(f"withdrew {res['withdrawn']} prefix(es)")


# ----------------------------------------------------------------------- perf


@cli.group(invoke_without_command=True)
@click.option("--limit", default=10, show_default=True, type=int,
              help="most recent traces to render")
@click.pass_context
def perf(ctx, limit):
    """Recent convergence traces with per-stage deltas (reference:
    breeze perf †): every trace is one update's walk spark → kvstore →
    decision → fib, markers stamped at each stage. Subcommand
    `waterfall` renders sampled cross-node flood spans instead."""
    if ctx.invoked_subcommand is not None:
        return
    res = _run(ctx, "get_perf_events", {"limit": limit})
    traces = res["traces"]
    if not traces:
        click.echo("no completed convergence traces yet")
        return
    for i, tr in enumerate(traces):
        click.echo(
            f"trace {i + 1}/{len(traces)}  total {tr['total_ms']:.3f} ms  "
            f"({len(tr['events'])} events)"
        )
        rows = [
            [d["event"], e.get("node", ""), f"+{d['delta_ms']:.3f}"]
            for d, e in zip(tr["deltas_ms"], tr["events"])
        ]
        click.echo(_table(rows, ["stage", "node", "delta-ms"]))
        click.echo("")


def _scrape_endpoints(ctx, endpoints: str, method: str, params: dict):
    """Call one ctrl method on every endpoint ("host:port,host:port";
    empty = just the root --host/--port). Returns {endpoint: result};
    unreachable endpoints are reported and skipped, so one dead node
    never blanks a fleet view."""
    eps: list[tuple[str, int]] = []
    if endpoints:
        for raw in endpoints.split(","):
            host, _, port = raw.strip().rpartition(":")
            if not port.isdigit():
                raise click.ClickException(
                    f"bad endpoint {raw.strip()!r}: expected host:port"
                )
            eps.append((host or ctx.obj["host"], int(port)))
    else:
        eps.append((ctx.obj["host"], ctx.obj["port"]))

    async def one(host: str, port: int):
        cli_ = RpcClient(host=host, port=port, ssl=ctx.obj.get("ssl"))
        await cli_.connect(timeout=ctx.obj["timeout"])
        try:
            return await cli_.call(
                method, params, timeout=ctx.obj["timeout"]
            )
        finally:
            await cli_.close()

    async def go():
        results = await asyncio.gather(
            *(one(h, p) for h, p in eps), return_exceptions=True
        )
        out = {}
        for (h, p), res in zip(eps, results):
            if isinstance(res, BaseException):
                click.echo(f"# {h}:{p} unreachable: {res}", err=True)
            else:
                out[f"{h}:{p}"] = res
        return out

    return asyncio.run(go())


@perf.command("waterfall")
@click.option("--limit", default=3, show_default=True, type=int,
              help="most recent flood traces (by id) to render")
@click.option("--endpoints", default="",
              help="comma-separated host:port ctrl endpoints to scrape "
              "and assemble cluster-wide (default: just this node)")
@click.pass_context
def perf_waterfall(ctx, limit, endpoints):
    """Sampled cross-node flood spans as propagation trees + named-stage
    waterfalls (docs/Monitor.md "Flood tracing"): each trace is one
    sampled origination's walk across the flooding mesh, every hop
    attributed (kvstore / encode / wire / decision / fib)."""
    from openr_tpu.monitor import flood_trace

    per_node = _scrape_endpoints(
        ctx, endpoints, "get_flood_traces", {"limit": 200}
    )
    traces = [t for res in per_node.values() for t in res["traces"]]
    if not traces:
        click.echo("no completed flood traces yet "
                   "(is kvstore.trace_sample_every set?)")
        return
    trees = flood_trace.propagation_tree(traces)
    by_id: dict[int, list[dict]] = {}
    for t in traces:
        by_id.setdefault(t["trace_id"], []).append(t)
    # deepest / widest propagation first — a 0-hop local span is the
    # least interesting thing a cluster-wide waterfall can show
    ranked = sorted(
        trees,
        key=lambda tid: (
            trees[tid]["max_hops"], trees[tid]["completions"]
        ),
        reverse=True,
    )
    for tid in ranked[:limit]:
        tree = trees[tid]
        click.echo(
            f"trace {tid:x}  origin {tree['origin']}  "
            f"{tree['completions']} completions  "
            f"max {tree['max_hops']} hops"
        )
        for parent, child in tree["edges"]:
            click.echo(f"  {parent} -> {child}")
        # deepest completion's waterfall: the full-path breakdown
        falls = [
            w
            for w in (
                t.get("waterfall") or flood_trace.waterfall(t)
                for t in by_id[tid]
            )
            if w is not None
        ]
        if not falls:
            continue
        deep = max(falls, key=lambda w: w["hops"])
        rows = [
            [s["stage"], s["node"], f"+{s['ms']:.3f}"]
            for s in deep["stages"]
        ]
        click.echo(_table(rows, ["stage", "node", "delta-ms"]))
        click.echo(
            f"  total {deep['total_ms']:.3f} ms, attributed "
            f"{deep['attributed_ms']:.3f} ms "
            f"(coverage {deep['coverage'] * 100:.1f}%)\n"
        )


# -------------------------------------------------------------------- monitor


@cli.group()
def monitor():
    """Counters / telemetry (reference: breeze monitor †)."""


@monitor.command("counters")
@click.option("--prefix", default="", help="counter name prefix filter")
@click.pass_context
def monitor_counters(ctx, prefix):
    res = _run(ctx, "get_counters", {"prefix": prefix})
    for k, v in sorted(res.items()):
        click.echo(f"{k}: {v:g}")


@monitor.command("queues")
@click.pass_context
def monitor_queues(ctx):
    """Per-seam queue gauges: live depth, high watermark, and overflow
    policy activity (coalesced / shed / overflow / blocked) for every
    inter-module queue — the overload-control dashboard."""
    res = _run(ctx, "get_counters", {"prefix": "queue."})
    queues: dict[str, dict[str, float]] = {}
    for k, v in res.items():
        # queue.<name>.<field>
        _, name, fld = k.split(".", 2)
        queues.setdefault(name, {})[fld] = v
    fields = ["depth", "highwater", "coalesced", "shed", "overflow", "blocked"]
    rows = [
        [name, *(f"{int(vals.get(f, 0))}" for f in fields)]
        for name, vals in sorted(queues.items())
    ]
    if not rows:
        click.echo("no queue gauges yet")
        return
    click.echo(_table(rows, ["queue", *fields]))


@monitor.command("wire")
@click.pass_context
def monitor_wire(ctx):
    """Wire-level byte accounting (docs/Wire.md): rpc tx/rx volume,
    binary-upgraded connections, flood bytes + serialize-once encode
    ratio, and the delta full_sync activity."""
    rpc_c = _run(ctx, "get_counters", {"prefix": "rpc."})
    kv = _run(ctx, "get_counters", {"prefix": "kvstore."})
    floods = kv.get("kvstore.floods_sent", 0)
    fbytes = kv.get("kvstore.flood_bytes", 0)
    encodes = kv.get("kvstore.flood_encodes", 0)
    rows = [
        ["rpc.bytes_tx", f"{int(rpc_c.get('rpc.bytes_tx', 0))}"],
        ["rpc.bytes_rx", f"{int(rpc_c.get('rpc.bytes_rx', 0))}"],
        ["rpc.conns_binary", f"{int(rpc_c.get('rpc.conns_binary', 0))}"],
        ["kvstore.flood_bytes", f"{int(fbytes)}"],
        ["kvstore.floods_sent", f"{int(floods)}"],
        ["bytes/flood", f"{fbytes / floods:.1f}" if floods else "-"],
        ["kvstore.flood_encodes", f"{int(encodes)}"],
        ["encodes/flood", f"{encodes / floods:.3f}" if floods else "-"],
        [
            "kvstore.full_sync_keys_sent",
            f"{int(kv.get('kvstore.full_sync_keys_sent', 0))}",
        ],
        [
            "kvstore.full_syncs_noop",
            f"{int(kv.get('kvstore.full_syncs_noop', 0))}",
        ],
    ]
    click.echo(_table(rows, ["wire counter", "value"]))


@monitor.command("prometheus")
@click.pass_context
def monitor_prometheus(ctx):
    """Prometheus text exposition of the node's counters + windowed
    latency percentiles — what a /metrics scrape would return."""
    res = _run(ctx, "get_counters_prometheus")
    click.echo(res["text"], nl=False)


@monitor.command("fleet")
@click.option("--endpoints", default="",
              help="comma-separated host:port ctrl endpoints to scrape "
              "(default: just this node — a 1-node fleet)")
@click.option("--prefix", default="", help="counter name prefix filter")
@click.option("--top", default=0, type=int,
              help="cap the table at N rows (0 = all)")
@click.pass_context
def monitor_fleet(ctx, endpoints, prefix, top):
    """Cluster-wide counter distributions (docs/Monitor.md "Fleet
    aggregation"): scrape every endpoint's counters and render per-key
    cross-node min/p50/p99/max with the arg-max node — queue depths,
    flood fan-out, rebuild and FIB-program latencies as fleet
    percentiles instead of N separate dashboards."""
    from openr_tpu.monitor.fleet import (
        FLEET_HEADERS,
        aggregate_counters,
        fleet_rows,
    )

    per_node = _scrape_endpoints(
        ctx, endpoints, "get_counters", {"prefix": prefix}
    )
    if not per_node:
        raise click.ClickException("no endpoint reachable")
    agg = aggregate_counters(per_node, prefix=prefix)
    rows = fleet_rows(agg, limit=top)
    if not rows:
        click.echo("no counters matched")
        return
    click.echo(f"# {len(per_node)} node(s) scraped")
    click.echo(_table(rows, FLEET_HEADERS))


@monitor.command("work")
@click.pass_context
def monitor_work(ctx):
    """Steady-state work ledger (docs/Monitor.md "Work ledger"):
    per-pipeline-stage entities-touched vs delta-size with the
    proportionality ratio — cumulative and since the warm mark — plus
    the top offending stage. A stage whose steady ratio grows with
    table size is an O(routes) walk hiding in the delta path."""
    res = _run(ctx, "get_work_ledger")
    stages = res.get("stages") or []
    if not stages:
        click.echo("work ledger empty (no scoped stage has run)")
        return
    def fmt(v):
        return f"{v:g}"

    rows = []
    for s in stages:
        st = s.get("steady")
        rows.append(
            [
                s["stage"],
                fmt(s["touched"]),
                fmt(s["delta"]),
                fmt(s["rounds"]),
                fmt(s["ratio"]),
                fmt(st["ratio"]) if st else "-",
                fmt(st["worst_ratio"]) if st else "-",
            ]
        )
    click.echo(
        f"# node {res['node']}: warm_marked={res.get('warm_marked')}"
    )
    click.echo(
        _table(
            rows,
            [
                "stage", "touched", "delta", "rounds",
                "ratio", "steady-ratio", "worst-round",
            ],
        )
    )
    top = res.get("top_offender")
    if top:
        click.echo(
            f"# top offender: {top['stage']} (ratio {top['ratio']:g})"
        )


@monitor.command("flight")
@click.option("--limit", default=50, show_default=True, type=int)
@click.option("--kind", default=None, help="filter by event kind")
@click.pass_context
def monitor_flight(ctx, limit, kind):
    """This node's flight-recorder ring (docs/Monitor.md): the recent
    structured events — rebuild dispatches, flood fan-outs, queue
    highwater crossings, backoff saturations, peer transitions — that a
    post-mortem reads; dumped automatically on emulator invariant
    failures."""
    import datetime

    res = _run(ctx, "get_flight_recorder", {"limit": limit})
    events = res.get("events") or []
    if kind:
        events = [e for e in events if e["kind"] == kind]
    if not events:
        click.echo("flight recorder empty")
        return
    click.echo(
        f"# node {res['node']}: {res.get('recorded', 0)} recorded, "
        f"showing {len(events)}"
    )
    for e in events:
        ts = datetime.datetime.fromtimestamp(e["ts"]).strftime("%H:%M:%S.%f")[:-3]
        attrs = " ".join(
            f"{k}={v}" for k, v in sorted(e.get("attrs", {}).items())
        )
        click.echo(f"{ts}  {e['kind']:<26} {attrs}")


# -------------------------------------------------------------------- persist


@cli.group()
def persist():
    """Crash-consistent durable-state plane (docs/Persist.md)."""


@persist.command("status")
@click.pass_context
def persist_status(ctx):
    """Journal health and recovery provenance: on-disk size, records
    since the last compaction, last-fsync age, per-book record counts
    with content digests (the byte-parity token the crash-recovery
    invariant compares), what this boot recovered, and any armed or
    fired injected disk faults."""
    res = _run(ctx, "get_persist_status")
    if not res.get("enabled"):
        click.echo(f"node {res['node']}: persistence disabled")
        return
    rec = res.get("recovery") or {}
    rows = [
        ["dir", res["dir"]],
        ["journal_bytes", f"{res['journal_bytes']}"],
        ["journal_records", f"{res['journal_records']}"],
        ["last_fsync_age_s", f"{res['last_fsync_age_s']:.3f}"],
        ["compactions", f"{res['compactions']}"],
        ["append_errors", f"{res['append_errors']}"],
        ["wedged", f"{res['wedged']}"],
        ["recovered_snapshot", f"{rec.get('snapshot_records', 0)}"],
        ["recovered_journal", f"{rec.get('journal_records', 0)}"],
        ["recovered_truncated_bytes", f"{rec.get('truncated_bytes', 0)}"],
    ]
    click.echo(f"# node {res['node']}")
    click.echo(_table(rows, ["persist", "value"]))
    books = res.get("books") or {}
    if books:
        click.echo(
            _table(
                [
                    [name, f"{b['records']}", b["digest"][:16]]
                    for name, b in sorted(books.items())
                ],
                ["book", "records", "digest"],
            )
        )
    faults = res.get("faults") or {}
    if faults.get("armed") or faults.get("fired"):
        click.echo(f"# faults armed={faults['armed']} fired={faults['fired']}")


@persist.command("compact")
@click.option("--force", is_flag=True, help="compact even an empty journal")
@click.pass_context
def persist_compact(ctx, force):
    """Force a snapshot+journal-reset compaction now."""
    res = _run(ctx, "persist_control", {"op": "compact", "force": force})
    click.echo("compacted" if res.get("ok") else "compaction skipped/failed")


# --------------------------------------------------------------------- device


@cli.group()
def device():
    """Device telemetry: kernel cost ledger + HBM gauges
    (docs/Monitor.md "Device telemetry")."""


@device.command("kernels")
@click.pass_context
def device_kernels(ctx):
    """Kernel cost ledger joined with measured span times: per canonical
    jitted entry point, XLA's static flops / bytes-accessed / resident
    HBM, the measured `profile.<span>_ms` p50, and the achieved
    GFLOP/s / GB/s that join implies — the static-vs-achieved view the
    sparse-kernel selection heuristic reads (docs/Decision.md)."""
    res = _run(ctx, "get_device_telemetry")
    kernels = res.get("kernels") or []
    if not kernels:
        click.echo(
            "no kernel cost rows captured yet (no jitted solve has "
            "traced on this node's process)"
        )
        return

    def mb(v):
        return f"{v / 1e6:.2f}" if v else "0"

    rows = []
    for k in kernels:
        if k.get("error"):
            rows.append([k["fn"], k.get("span") or "-", "ERR", k["error"],
                         "-", "-", "-", "-"])
            continue
        rows.append(
            [
                k["fn"],
                k.get("span") or "-",
                f"{k['flops']:.3g}",
                f"{k['bytes_accessed']:.3g}",
                mb(k["resident_hbm_bytes"]),
                (
                    f"{k['span_p50_ms']:.3f}"
                    if k.get("span_p50_ms") is not None
                    else "-"
                ),
                (
                    f"{k['achieved_gflops']:g}"
                    if k.get("achieved_gflops") is not None
                    else "-"
                ),
                (
                    f"{k['achieved_gbs']:g}"
                    if k.get("achieved_gbs") is not None
                    else "-"
                ),
            ]
        )
    click.echo(
        _table(
            rows,
            ["kernel", "span", "flops", "bytes", "hbm-MB", "p50-ms",
             "GFLOP/s", "GB/s"],
        )
    )
    if res.get("shards"):
        click.echo("")
        srows = [
            [
                str(s["device"]),
                s["platform"],
                "x".join(str(d) for d in s["shard_shape"]),
                f"{s['shard_bytes'] / 1e6:.2f}",
            ]
            for s in res["shards"]
        ]
        click.echo(
            _table(srows, ["device", "platform", "shard", "MB"])
        )


@device.command("hbm")
@click.pass_context
def device_hbm(ctx):
    """Per-device HBM gauges (live / peak / limit bytes) from
    memory_stats(); degrades to an explicit note on backends without
    them (CPU)."""
    res = _run(ctx, "get_device_telemetry")
    devices = res.get("devices") or []
    if not devices:
        click.echo(
            "hbm telemetry unavailable (backend exposes no "
            "memory_stats — e.g. cpu)"
        )
        return
    rows = [
        [
            str(d["device"]),
            d["kind"],
            f"{d['hbm_bytes_in_use'] / 1e6:.1f}",
            f"{d['hbm_peak_bytes'] / 1e6:.1f}",
            f"{d['hbm_limit_bytes'] / 1e6:.1f}" if d["hbm_limit_bytes"] else "-",
        ]
        for d in devices
    ]
    click.echo(
        _table(rows, ["device", "kind", "in-use-MB", "peak-MB", "limit-MB"])
    )


@monitor.command("logs")
@click.option("--limit", default=50, show_default=True, type=int)
@click.option("--event", default=None, help="filter by event name")
@click.pass_context
def monitor_logs(ctx, limit, event):
    """Recent structured event samples (reference: breeze monitor logs †)."""
    res = _run(ctx, "get_event_logs", {"limit": limit, "event": event})
    import datetime

    for s in res:
        ts = datetime.datetime.fromtimestamp(s["ts"]).strftime("%H:%M:%S")
        attrs = " ".join(f"{k}={v}" for k, v in sorted(s["attrs"].items()))
        click.echo(f"{ts}  {s['event']:<22} {attrs}")


# --------------------------------------------------------------------- cluster


@cli.group()
def cluster():
    """Multi-process cluster views (docs/Emulator.md "Multi-process
    clusters"): one row per node-process, scraped over each process's
    own ctrl endpoint."""


@cluster.command("status")
@click.option("--endpoints", default="",
              help="comma-separated host:port ctrl endpoints — one per "
              "node-process (default: just the root --host/--port). "
              "ProcCluster.endpoints() emits this string.")
@click.pass_context
def cluster_status(ctx, endpoints):
    """Per-process liveness and health: initialized / programmed routes
    / FIB backoff (flagging saturation) / peer sync + worst peer
    backoff / worst queue highwater vs its bound. An endpoint that
    refuses the connection renders as a DOWN row instead of vanishing,
    so a crashed process is visible in the same table as its
    survivors."""
    eps = []
    for raw in endpoints.split(","):
        raw = raw.strip()
        if not raw:
            continue
        host, _, port = raw.rpartition(":")
        if not port.isdigit():
            raise click.ClickException(
                f"bad endpoint {raw!r}: expected host:port"
            )
        eps.append(f"{host or ctx.obj['host']}:{int(port)}")
    if not eps:
        eps = [f"{ctx.obj['host']}:{ctx.obj['port']}"]

    per_node = _scrape_endpoints(ctx, endpoints, "get_convergence_state", {})
    rows = []
    saturated = []
    for ep in eps:
        st = per_node.get(ep)
        if st is None:
            rows.append([ep, "-", "DOWN", "-", "-", "-", "-", "-"])
            continue
        fib = st.get("fib") or {}
        peers = st.get("peers") or []
        synced = sum(1 for p in peers if p.get("synced"))
        peer_boff = max((p.get("backoff_ms") or 0 for p in peers), default=0)
        fib_boff = fib.get("backoff_ms") or 0
        if fib.get("backoff_saturated"):
            saturated.append(f"{st['node']} fib")
        if any(p.get("backoff_error") for p in peers) and peer_boff >= 30000:
            saturated.append(f"{st['node']} peer-sync")
        cap = st.get("queue_cap") or 0
        hw = max(
            (q.get("highwater") or 0 for q in st.get("queues") or []),
            default=0,
        )
        rows.append(
            [
                ep,
                st["node"],
                "UP" if st.get("initialized") else "INIT",
                str(fib.get("programmed_unicast", 0)),
                f"{fib_boff}ms" + (" SAT" if fib.get("backoff_saturated") else ""),
                f"{synced}/{len(peers)}",
                f"{peer_boff}ms",
                f"{hw}/{cap}" if cap else str(hw),
            ]
        )
    up = sum(1 for r in rows if r[2] != "DOWN")
    click.echo(f"# {up}/{len(eps)} process(es) up")
    click.echo(
        _table(
            rows,
            ["endpoint", "node", "state", "routes", "fib-backoff",
             "peers-synced", "peer-backoff", "queue-hw"],
        )
    )
    if saturated:
        click.echo("# backoff saturated: " + ", ".join(sorted(set(saturated))))
