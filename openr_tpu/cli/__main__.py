"""`python -m openr_tpu.cli` — the breeze entry point."""

from openr_tpu.cli import cli

if __name__ == "__main__":
    cli()
