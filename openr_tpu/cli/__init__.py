"""breeze — the operator CLI (reference: openr/py/openr/cli/ †).

The reference ships a python-click CLI ("breeze") that speaks
OpenrCtrl thrift to a running node: `breeze kvstore keys`, `breeze
decision routes`, `breeze lm links`, `breeze fib routes`, … We ship the
same command tree over the ctrl RPC (openr_tpu/ctrl/). Run it as
`python -m openr_tpu.cli --port <ctrl-port> <module> <command>`.
"""

from openr_tpu.cli.breeze import cli

__all__ = ["cli"]
