"""LinkMonitor: interfaces → Spark; neighbors → adjacencies → KvStore.

reference: openr/link-monitor/LinkMonitor.{h,cpp} † —
  * consumes InterfaceEvents (netlink in the reference; the platform/
    emulator seam here), applies include/exclude regexes, link-flap
    exponential backoff damping, and tells Spark which interfaces to run
    discovery on;
  * consumes Spark NeighborEvents, maintains the adjacency set, assigns
    adjacency labels (SR), computes metrics (hop or RTT-based);
  * advertises `adj:<node>` via KvStoreClient.persist_key (throttled);
  * emits PeerEvents so KvStore opens/closes peer sync sessions;
  * node overload + per-link metric override API (breeze lm set-*).
"""

from __future__ import annotations

import logging
import re

from openr_tpu.common.backoff import ExponentialBackoff
from openr_tpu.common.constants import SR_LOCAL_RANGE, adj_key
from openr_tpu.common.eventbase import OpenrModule
from openr_tpu.common.throttle import AsyncDebounce
from openr_tpu.config import Config
from openr_tpu.kvstore.client import KvStoreClient
from openr_tpu.kvstore.kvstore import PeerEvent, PeerSpec
from openr_tpu.messaging import QueueClosedError, ReplicateQueue, RQueue
from openr_tpu.monitor import perf
from openr_tpu.types.events import (
    InterfaceInfo,
    NeighborEvent,
    NeighborEventType,
    NeighborInfo,
)
from openr_tpu.types.serde import to_wire
from openr_tpu.types.topology import Adjacency, AdjacencyDatabase

log = logging.getLogger(__name__)


class LinkMonitor(OpenrModule):
    def __init__(
        self,
        config: Config,
        spark,  # Spark (for add/remove_interface)
        kv_client: KvStoreClient,
        neighbor_events_reader: RQueue,
        peer_events_queue: ReplicateQueue,
        interface_events_reader: RQueue | None = None,
        log_samples_queue: ReplicateQueue | None = None,
        counters=None,
    ):
        super().__init__(f"{config.node_name}.linkmonitor", counters=counters)
        self.config = config
        self.node_name = config.node_name
        self.spark = spark
        self.kv_client = kv_client
        self.nbr_reader = neighbor_events_reader
        self.peer_queue = peer_events_queue
        self.if_reader = interface_events_reader
        self.log_queue = log_samples_queue

        self.interfaces: dict[str, InterfaceInfo] = {}
        self._if_backoff: dict[str, ExponentialBackoff] = {}
        # (area, neighbor, local_if) -> (NeighborInfo, adj_label)
        self.adjacencies: dict[tuple[str, str, str], tuple[NeighborInfo, int]] = {}
        self.node_overloaded = False
        self._metric_override: dict[str, int] = {}  # if_name -> metric
        self._link_overload: set[str] = set()  # if_name -> drained link
        self._next_adj_label = SR_LOCAL_RANGE[0]
        # convergence trace coalesced across the advertise debounce
        # window (several neighbor events can fold into one adj:<node>
        # publication — their markers merge into one trace)
        self._pending_perf: perf.PerfEvents | None = None
        self._advertise_debounce = AsyncDebounce(
            min_ms=10,
            max_ms=self.config.node.link_monitor.linkflap_initial_backoff_ms
            + 1000,
            fn=self.advertise_adjacencies,
            owner=self.name,
            counters=counters,
        )

    # ----------------------------------------------------------------- main

    async def main(self) -> None:
        self.spawn(self._neighbor_loop(), name=f"{self.name}.nbr")
        if self.if_reader is not None:
            self.spawn(self._interface_loop(), name=f"{self.name}.if")

    # ----------------------------------------------------------- interfaces

    def _if_allowed(self, name: str) -> bool:
        lm = self.config.node.link_monitor
        if lm.include_interface_regexes:
            if not any(
                re.fullmatch(p, name) for p in lm.include_interface_regexes
            ):
                return False
        if any(re.fullmatch(p, name) for p in lm.exclude_interface_regexes):
            return False
        return True

    async def _interface_loop(self) -> None:
        while True:
            try:
                ev = await self.if_reader.get()
            except QueueClosedError:
                return
            for info in ev.interfaces:
                self.update_interface(info)

    def update_interface(self, info: InterfaceInfo) -> None:
        """Apply one interface state change with flap damping.

        reference: LinkMonitor interface backoff (linkflap_*_backoff_ms †):
        a flapping interface waits out an exponential hold-down before
        Spark restarts discovery on it."""
        if not self._if_allowed(info.name):
            return
        lm = self.config.node.link_monitor
        prev = self.interfaces.get(info.name)
        self.interfaces[info.name] = info
        backoff = self._if_backoff.setdefault(
            info.name,
            ExponentialBackoff(
                lm.linkflap_initial_backoff_ms, lm.linkflap_max_backoff_ms
            ),
        )
        if info.is_up:
            if prev is not None and not prev.is_up:
                backoff.report_error()  # flap: down→up counts against it
            wait = backoff.time_remaining_s()
            if wait > 0:
                if self.counters is not None:
                    self.counters.increment("linkmonitor.flap_damped")
                self.spawn(self._delayed_if_up(info.name, wait))
            else:
                self.spark.add_interface(info.name)
        else:
            self.spark.remove_interface(info.name)

    async def _delayed_if_up(self, if_name: str, wait: float) -> None:
        import asyncio

        await asyncio.sleep(wait)
        info = self.interfaces.get(if_name)
        if info is not None and info.is_up and not self.stopped:
            self.spark.add_interface(if_name)

    # ------------------------------------------------------------ neighbors

    async def _neighbor_loop(self) -> None:
        while True:
            try:
                ev: NeighborEvent = await self.nbr_reader.get()
            except QueueClosedError:
                return
            self._process_neighbor_event(ev)

    def _process_neighbor_event(self, ev: NeighborEvent) -> None:
        info = ev.info
        key = (info.area, info.node_name, info.local_if)
        if ev.type in (
            NeighborEventType.NEIGHBOR_UP,
            NeighborEventType.NEIGHBOR_RESTARTED,
        ):
            label = (
                self.adjacencies[key][1]
                if key in self.adjacencies
                else self._alloc_adj_label()
            )
            self.adjacencies[key] = (info, label)
            self.peer_queue.push(
                PeerEvent(
                    area=info.area,
                    peers_to_add=[
                        PeerSpec(
                            node_name=info.node_name,
                            endpoint=self._peer_endpoint(info),
                            area=info.area,
                        )
                    ],
                )
            )
            if self.counters is not None:
                self.counters.increment("linkmonitor.neighbor_up")
            self._log_event(
                "NEIGHBOR_RESTARTED"
                if ev.type == NeighborEventType.NEIGHBOR_RESTARTED
                else "NEIGHBOR_UP",
                neighbor=info.node_name,
                interface=info.local_if, area=info.area,
            )
        elif ev.type == NeighborEventType.NEIGHBOR_DOWN:
            self.adjacencies.pop(key, None)
            # only drop the kvstore peer when no adjacency to that node
            # remains on any interface (parallel links)
            if not any(
                k[0] == info.area and k[1] == info.node_name
                for k in self.adjacencies
            ):
                self.peer_queue.push(
                    PeerEvent(
                        area=info.area, peers_to_del=[info.node_name]
                    )
                )
            if self.counters is not None:
                self.counters.increment("linkmonitor.neighbor_down")
            self._log_event("NEIGHBOR_DOWN", neighbor=info.node_name,
                            interface=info.local_if, area=info.area)
        elif ev.type == NeighborEventType.NEIGHBOR_RESTARTING:
            # graceful restart: hold the adjacency, don't re-advertise
            # (reference: GR keeps forwarding state while control restarts †)
            return
        elif ev.type == NeighborEventType.NEIGHBOR_RTT_CHANGE:
            if key in self.adjacencies:
                label = self.adjacencies[key][1]
                self.adjacencies[key] = (info, label)
            if not self.config.node.link_monitor.use_rtt_metric:
                return
        # trace bookkeeping only for events that actually reach the
        # advertise poke — the early-return branches above (GR hold,
        # ignored RTT jitter) must not leave a stale trace poisoning
        # the NEXT advertisement's convergence numbers
        if ev.perf_events is not None:
            ev.perf_events.add_perf_event(
                perf.ADJ_DB_UPDATED, node=self.node_name
            )
            self._pending_perf = (
                ev.perf_events
                if self._pending_perf is None
                else self._pending_perf.merge(ev.perf_events)
            )
        self._advertise_debounce.poke()

    def _peer_endpoint(self, info: NeighborInfo):
        """In-proc transports key peers by node name (endpoint None);
        TCP transports get (host, port)."""
        if info.kvstore_port:
            return (info.endpoint_host or "127.0.0.1", info.kvstore_port)
        return None

    def _alloc_adj_label(self) -> int:
        label = self._next_adj_label
        self._next_adj_label += 1
        if self._next_adj_label > SR_LOCAL_RANGE[1]:
            self._next_adj_label = SR_LOCAL_RANGE[0]
        return label

    # ---------------------------------------------------------- advertising

    def _metric_for(self, info: NeighborInfo) -> int:
        if info.local_if in self._metric_override:
            return self._metric_override[info.local_if]
        if self.config.node.link_monitor.use_rtt_metric and info.rtt_us:
            return max(1, info.rtt_us // 100)  # reference: rtt-based metric †
        return 1  # hop count

    def build_adjacency_db(self, area: str) -> AdjacencyDatabase:
        adjs = []
        sr = self.config.node.segment_routing
        for (a, node, local_if), (info, label) in sorted(
            self.adjacencies.items()
        ):
            if a != area:
                continue
            adjs.append(
                Adjacency(
                    other_node_name=node,
                    if_name=local_if,
                    other_if_name=info.remote_if,
                    metric=self._metric_for(info),
                    adj_label=label if sr.enable else 0,
                    rtt_us=info.rtt_us,
                    is_overloaded=local_if in self._link_overload,
                )
            )
        return AdjacencyDatabase(
            this_node_name=self.node_name,
            adjacencies=tuple(adjs),
            is_overloaded=self.node_overloaded,
            node_label=self._node_label(),
            area=area,
        )

    def _node_label(self) -> int:
        sr = self.config.node.segment_routing
        if not sr.enable:
            return 0
        if sr.node_segment_label:
            return sr.node_segment_label
        # deterministic auto-allocation refined by RangeAllocator later
        lo, hi = sr.sr_global_range
        return lo + (hash(self.node_name) % (hi - lo))

    def advertise_adjacencies(self) -> None:
        """Persist adj:<node> into every area's KvStore.

        reference: LinkMonitor::advertiseAdjacencies † via
        KvStoreClientInternal::persistKey."""
        pe, self._pending_perf = self._pending_perf, None
        for area in self.config.area_ids():
            db = self.build_adjacency_db(area)
            self.kv_client.persist_key(
                area,
                adj_key(self.node_name),
                to_wire(db),
                # finite TTL (was TTL_INFINITY): a hard-crashed node
                # that never says goodbye must fade out of every LSDB
                # by TTL, or routes through it persist forever — the
                # client refreshes live keys, so only the dead decay
                ttl_ms=self.config.node.kvstore.key_ttl_ms,
                # per-area copy: each area's publication is stamped by
                # its own downstream pipeline
                perf_events=pe.copy() if pe is not None else None,
            )
        if self.counters is not None:
            self.counters.increment("linkmonitor.adj_advertised")

    # ------------------------------------------------------------- operator

    def dump_interfaces(self) -> list[dict]:
        """Interface + adjacency view (reference: OpenrCtrl dumpLinks † /
        `breeze lm links`)."""
        out = []
        for name, info in sorted(self.interfaces.items()):
            adjs = [
                {"neighbor": node, "area": a, "remote_if": nb.remote_if,
                 "metric": self._metric_for(nb), "rtt_us": nb.rtt_us}
                for (a, node, local_if), (nb, _label) in sorted(
                    self.adjacencies.items()
                )
                if local_if == name
            ]
            out.append({
                "name": name,
                "is_up": info.is_up,
                "metric_override": self._metric_override.get(name),
                "is_overloaded": name in self._link_overload,
                "adjacencies": adjs,
            })
        return out

    def set_node_overload(self, overloaded: bool) -> None:
        """reference: OpenrCtrl setNodeOverload → LinkMonitor †."""
        if self.node_overloaded != overloaded:
            self.node_overloaded = overloaded
            self._log_event(
                "NODE_OVERLOAD_SET" if overloaded else "NODE_OVERLOAD_UNSET"
            )
            self._advertise_debounce.poke()

    def _log_event(self, event: str, **attrs) -> None:
        """Emit a structured event sample (reference: LogSample records on
        neighbor/overload transitions †)."""
        if self.log_queue is not None:
            from openr_tpu.monitor import LogSample

            self.log_queue.push(LogSample(event=event, attrs=attrs))

    def set_link_metric(self, if_name: str, metric: int | None) -> None:
        """reference: setInterfaceMetric †."""
        if metric is None:
            self._metric_override.pop(if_name, None)
        else:
            self._metric_override[if_name] = metric
        self._advertise_debounce.poke()

    def set_link_overload(self, if_name: str, overloaded: bool) -> None:
        """Drain one link: originate its adjacency with
        is_overloaded=True so every solver excludes BOTH directions
        from transit while the adjacency itself stays up (reference:
        setInterfaceOverload † — soft-drain for maintenance, distinct
        from node overload and from metric overrides). Unknown
        interfaces are rejected — a typo'd drain that silently does
        nothing is a false all-clear during maintenance."""
        if if_name not in self.interfaces:
            raise ValueError(
                f"unknown interface {if_name!r} "
                f"(have: {sorted(self.interfaces) or 'none'})"
            )
        changed = (if_name in self._link_overload) != overloaded
        if overloaded:
            self._link_overload.add(if_name)
        else:
            self._link_overload.discard(if_name)
        if changed:
            self._log_event(
                "LINK_OVERLOAD_SET" if overloaded else "LINK_OVERLOAD_UNSET",
                if_name=if_name,
            )
            self._advertise_debounce.poke()
