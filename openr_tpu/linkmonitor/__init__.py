"""LinkMonitor (reference: openr/link-monitor/ †)."""

from openr_tpu.linkmonitor.linkmonitor import LinkMonitor  # noqa: F401
