"""A mock dataplane whose tables survive process death.

A real kernel FIB outlives the routing daemon — that is what makes
warm boot meaningful. ProcCluster's nodes program a MockFibHandler
that dies with the process, so before this module every SIGKILL
restart was silently a cold boot. :class:`DurableMockFibHandler`
persists its route tables through the node's :class:`PersistPlane`
(books ``dp_unicast`` / ``dp_mpls``) and restores them on construction,
so Fib's warm-boot dump sees exactly what the "kernel" held when the
previous incarnation died — including under injected disk faults.
"""

from __future__ import annotations

import logging

from openr_tpu.fib.fib import MockFibHandler
from openr_tpu.types.network import MplsRoute, UnicastRoute
from openr_tpu.types.serde import WireDecodeError, from_wire_bin, to_wire_bin

log = logging.getLogger(__name__)

BOOK_UNICAST = "dp_unicast"
BOOK_MPLS = "dp_mpls"


def _ukey(client_id: int, dest) -> bytes:
    return f"{client_id}/{dest.prefix}".encode()


def _mkey(client_id: int, label: int) -> bytes:
    return f"{client_id}/{label}".encode()


class DurableMockFibHandler(MockFibHandler):
    def __init__(self, plane, **kwargs):
        super().__init__(**kwargs)
        self.plane = plane
        self._restore()

    def _restore(self) -> None:
        n = 0
        for key, wire in self.plane.book(BOOK_UNICAST).items():
            try:
                client_id = int(key.split(b"/", 1)[0])
                r = from_wire_bin(wire, UnicastRoute)
            except (WireDecodeError, ValueError) as exc:
                log.warning("dataplane: dropping bad unicast record: %s", exc)
                continue
            self.unicast.setdefault(client_id, {})[r.dest] = r
            n += 1
        for key, wire in self.plane.book(BOOK_MPLS).items():
            try:
                client_id = int(key.split(b"/", 1)[0])
                r = from_wire_bin(wire, MplsRoute)
            except (WireDecodeError, ValueError) as exc:
                log.warning("dataplane: dropping bad mpls record: %s", exc)
                continue
            self.mpls.setdefault(client_id, {})[r.top_label] = r
            n += 1
        if n:
            log.info("dataplane: restored %d surviving routes", n)

    # mutators journal AFTER the in-memory apply: _fail_maybe fires
    # inside super(), so an injected FibProgramError never persists

    async def add_unicast_routes(self, client_id, routes):
        await super().add_unicast_routes(client_id, routes)
        for r in routes:
            self.plane.record(
                BOOK_UNICAST, _ukey(client_id, r.dest), to_wire_bin(r)
            )

    async def delete_unicast_routes(self, client_id, prefixes):
        await super().delete_unicast_routes(client_id, prefixes)
        for p in prefixes:
            self.plane.erase(BOOK_UNICAST, _ukey(client_id, p))

    async def add_mpls_routes(self, client_id, routes):
        await super().add_mpls_routes(client_id, routes)
        for r in routes:
            self.plane.record(
                BOOK_MPLS, _mkey(client_id, r.top_label), to_wire_bin(r)
            )

    async def delete_mpls_routes(self, client_id, labels):
        await super().delete_mpls_routes(client_id, labels)
        for label in labels:
            self.plane.erase(BOOK_MPLS, _mkey(client_id, label))

    async def sync_fib(self, client_id, routes):
        await super().sync_fib(client_id, routes)
        self.plane.replace_book(
            BOOK_UNICAST,
            {_ukey(client_id, r.dest): to_wire_bin(r) for r in routes},
            prefix=f"{client_id}/".encode(),
        )

    async def sync_mpls_fib(self, client_id, routes):
        await super().sync_mpls_fib(client_id, routes)
        self.plane.replace_book(
            BOOK_MPLS,
            {_mkey(client_id, r.top_label): to_wire_bin(r) for r in routes},
            prefix=f"{client_id}/".encode(),
        )
