"""Seeded disk-fault injection for the persistence plane.

The chaos layer's named-substream pattern (emulator/chaos.py, PR 3)
extended to the disk seam: faults are **armed** (one-shot) and consumed
at the journal/snapshot edges, with offsets drawn from an injectable
RNG so a failing run replays from its seed. Kinds:

=====================  =====================================================
``torn``               next journal append writes only the first *k* bytes
                       of the frame (``at`` param, else seeded) and wedges
                       the journal — the crash-mid-write model; arm it
                       immediately before delivering SIGKILL
``corrupt``            next journal append lands with one seeded bit
                       flipped somewhere in the frame
``enospc``             next journal append raises ``OSError(ENOSPC)``
                       before any byte is written
``crash_between_rename`` next snapshot write stops after the fsynced temp
                       file, before the atomic rename (raises
                       :class:`InjectedCrash`) — old snapshot + journal
                       stay authoritative
``slow_fsync``         next fsync sleeps ``delay_s`` (default 0.05)
=====================  =====================================================
"""

from __future__ import annotations

import errno
import random
import time

KINDS = ("torn", "corrupt", "enospc", "crash_between_rename", "slow_fsync")


class InjectedCrash(RuntimeError):
    """A crash-between-rename injection point firing: the snapshot temp
    is on disk but the rename never happened."""


class DiskFaultInjector:
    """One-shot armed faults consumed at the persist plane's I/O edges."""

    def __init__(self, rng: random.Random | None = None, note=None):
        self.rng = rng or random.Random(0)
        self.note = note  # ChaosPlan.note-compatible stats hook
        self._armed: list[tuple[str, dict]] = []
        self.fired: dict[str, int] = {}

    def arm(self, kind: str, **params) -> None:
        if kind not in KINDS:
            raise ValueError(f"unknown disk fault kind {kind!r}")
        self._armed.append((kind, params))

    def _take(self, *kinds: str) -> tuple[str, dict] | None:
        for i, (kind, params) in enumerate(self._armed):
            if kind in kinds:
                del self._armed[i]
                self.fired[kind] = self.fired.get(kind, 0) + 1
                if self.note is not None:
                    self.note(f"disk.{kind}")
                return kind, params
        return None

    # ------------------------------------------------------------ I/O edges

    def on_append(self, frame: bytes) -> tuple[bytes, int | None]:
        """Filter one journal frame. Returns ``(bytes_to_write,
        torn_at)``; ``torn_at`` non-None wedges the journal. Raises
        ``OSError(ENOSPC)`` for an armed enospc fault."""
        if self._take("enospc"):
            raise OSError(errno.ENOSPC, "injected: no space left on device")
        hit = self._take("torn")
        if hit:
            k = hit[1].get("at")
            if k is None:
                k = self.rng.randrange(1, max(len(frame), 2))
            k = max(0, min(int(k), len(frame) - 1))
            return frame[:k], k
        hit = self._take("corrupt")
        if hit:
            bit = hit[1].get("bit")
            if bit is None:
                bit = self.rng.randrange(len(frame) * 8)
            buf = bytearray(frame)
            buf[bit // 8] ^= 1 << (bit % 8)
            return bytes(buf), None
        return frame, None

    def on_fsync(self) -> None:
        hit = self._take("slow_fsync")
        if hit:
            time.sleep(float(hit[1].get("delay_s", 0.05)))

    def on_rename(self) -> None:
        if self._take("crash_between_rename"):
            raise InjectedCrash("injected: crash between rename")

    # --------------------------------------------------------------- status

    def status(self) -> dict:
        return {
            "armed": [kind for kind, _ in self._armed],
            "fired": dict(self.fired),
        }
