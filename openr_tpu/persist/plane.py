"""PersistPlane: the node's durable books, mounted on the journal.

A *book* is a named ``dict[bytes, bytes]`` of durable key → value wire
bytes. Three production books ride one plane per node (docs/Persist.md):

* ``kv_orig``   — KvStoreClient's self-originated keys,
* ``pfx_entries`` / ``pfx_ranges`` — PrefixManager's redistribution and
  range books,
* ``fib``       — the programmed route table in control-plane form,

plus the mock dataplane's ``dp_unicast`` / ``dp_mpls`` (persist/
dataplane.py). Writers call :meth:`record` / :meth:`erase` at their
existing single mutation seams; both dedup against the in-memory book,
so recovery replays and steady-state re-advertisements journal nothing.
Compaction rewrites the snapshot atomically *first*, then truncates the
journal — a crash between the two leaves duplicate records, which
replay absorbs (last-wins).

In-memory state is only mutated for records that actually reached the
OS (an ENOSPC'd append drops the write and the next divergent
advertisement retries it), so the books always describe what recovery
will see — that is what makes the byte-parity invariant
(emulator/proc_invariants.py) checkable from digests alone.
"""

from __future__ import annotations

import hashlib
import logging
import os
import struct
from typing import Mapping

from openr_tpu.persist.faults import DiskFaultInjector, InjectedCrash
from openr_tpu.persist.journal import (
    OP_DEL,
    OP_SET,
    Journal,
    JournalRecord,
    atomic_write_bytes,
    encode_record,
    load_journal,
    replay_frames,
)

log = logging.getLogger(__name__)

_LEN = struct.Struct("<I")


def book_digest(book: Mapping[bytes, bytes]) -> str:
    """Order-independent content digest of one book — the byte-parity
    token the crash-recovery invariant compares across incarnations."""
    h = hashlib.sha256()
    for k in sorted(book):
        h.update(_LEN.pack(len(k)))
        h.update(k)
        v = book[k]
        h.update(_LEN.pack(len(v)))
        h.update(v)
    return h.hexdigest()


class PersistPlane:
    SNAPSHOT = "snapshot.bin"
    JOURNAL = "journal.bin"

    def __init__(
        self,
        dirpath: str,
        counters=None,
        *,
        compact_every: int = 4096,
        fsync_interval_s: float = 1.0,
        faults: DiskFaultInjector | None = None,
    ):
        os.makedirs(dirpath, exist_ok=True)
        self.dir = dirpath
        self.counters = counters
        self.compact_every = compact_every
        self.fsync_interval_s = fsync_interval_s
        self.faults = faults if faults is not None else DiskFaultInjector()
        self.books: dict[str, dict[bytes, bytes]] = {}
        self.compactions = 0
        self.append_errors = 0
        self.recovery = self._load()
        self.journal = Journal(
            os.path.join(dirpath, self.JOURNAL), faults=self.faults
        )

    # -------------------------------------------------------------- recovery

    def _load(self) -> dict:
        """Snapshot (strict — it was atomically renamed) then journal
        (torn tail truncated in place); both through the one record
        grammar. Mid-journal corruption propagates WireDecodeError."""
        snap_path = os.path.join(self.dir, self.SNAPSHOT)
        snap_records = 0
        try:
            with open(snap_path, "rb") as f:
                frames, _ = replay_frames(f.read(), strict=True)
            for rec in frames:
                self._apply(rec)
            snap_records = len(frames)
        except FileNotFoundError:
            pass
        journal_records, torn = load_journal(
            os.path.join(self.dir, self.JOURNAL)
        )
        for rec in journal_records:
            self._apply(rec)
        if self.counters is not None:
            self.counters.set(
                "persist.recovered_records",
                snap_records + len(journal_records),
            )
            self.counters.set("persist.truncated_bytes", torn)
        return {
            "snapshot_records": snap_records,
            "journal_records": len(journal_records),
            "truncated_bytes": torn,
            "books": {
                name: book_digest(book) for name, book in self.books.items()
            },
        }

    def _apply(self, rec: JournalRecord) -> None:
        book = self.books.setdefault(rec.book, {})
        if rec.op == OP_SET:
            book[rec.key] = rec.value
        else:
            book.pop(rec.key, None)

    # --------------------------------------------------------------- writes

    def book(self, name: str) -> dict[bytes, bytes]:
        """Live view of one book (treat as read-only; mutate via
        record/erase so disk stays in lockstep)."""
        return self.books.setdefault(name, {})

    def record(self, name: str, key: bytes, value: bytes) -> bool:
        """Durable upsert; False = no-op (dedup) or append failure."""
        book = self.books.setdefault(name, {})
        if book.get(key) == value:
            return False
        if not self._append(JournalRecord(name, OP_SET, key, value)):
            return False
        book[key] = value
        self._maybe_compact()
        return True

    def erase(self, name: str, key: bytes) -> bool:
        book = self.books.setdefault(name, {})
        if key not in book:
            return False
        if not self._append(JournalRecord(name, OP_DEL, key)):
            return False
        del book[key]
        self._maybe_compact()
        return True

    def replace_book(
        self, name: str, mapping: Mapping[bytes, bytes], prefix: bytes = b""
    ) -> int:
        """Make (the ``prefix`` slice of) a book equal ``mapping``,
        journaling only the difference — the full-sync seams stay
        delta-proportional on disk."""
        book = self.books.setdefault(name, {})
        stale = [
            k for k in book if k.startswith(prefix) and k not in mapping
        ]
        ops = 0
        for k in stale:
            ops += self.erase(name, k)
        for k, v in mapping.items():
            ops += self.record(name, k, v)
        return ops

    def _append(self, rec: JournalRecord) -> bool:
        try:
            ok = self.journal.append(rec)
        except OSError as exc:
            self.append_errors += 1
            if self.counters is not None:
                self.counters.increment("persist.append_errors")
            log.warning("persist: journal append failed: %s", exc)
            return False
        if not ok:  # wedged post-torn: the process is as good as dead
            self.append_errors += 1
            if self.counters is not None:
                self.counters.increment("persist.append_errors")
            return True  # crash-mid-write model: writer believes it landed
        if self.counters is not None:
            self.counters.increment("persist.appends")
            self.counters.set("persist.journal_bytes", self.journal.size)
            self.counters.set("persist.journal_records", self.journal.records)
        return True

    def _maybe_compact(self) -> None:
        """Runs AFTER the in-memory apply — the snapshot must contain
        the record whose journal entry the reset is about to drop."""
        if self.journal.wedged:
            return
        if self.journal.records >= self.compact_every:
            self.compact()
        elif self.journal.fsync_age_s() >= self.fsync_interval_s:
            self.sync()

    def sync(self) -> None:
        """Power-fail durability point (page-cache flush already makes
        every append SIGKILL-durable)."""
        if self.journal.wedged:
            return
        self.journal.sync()
        if self.counters is not None:
            self.counters.increment("persist.fsyncs")

    # ----------------------------------------------------------- compaction

    def compact(self, force: bool = False) -> bool:
        """Snapshot-then-truncate. Crash after the rename but before the
        truncate only leaves duplicate records for replay to absorb."""
        if self.journal.wedged and not force:
            return False
        out = bytearray()
        for name in sorted(self.books):
            for key in sorted(self.books[name]):
                out += encode_record(
                    JournalRecord(name, OP_SET, key, self.books[name][key])
                )
        try:
            atomic_write_bytes(
                os.path.join(self.dir, self.SNAPSHOT),
                bytes(out),
                faults=self.faults,
            )
        except (OSError, InjectedCrash) as exc:
            if self.counters is not None:
                self.counters.increment("persist.compact_errors")
            log.warning("persist: compaction aborted: %s", exc)
            return False
        self.journal.reset()
        self.compactions += 1
        if self.counters is not None:
            self.counters.increment("persist.compactions")
            self.counters.set("persist.journal_bytes", 0)
            self.counters.set("persist.journal_records", 0)
        return True

    # --------------------------------------------------------------- status

    def status(self) -> dict:
        """JSON-able operational view (ctrl ``get_persist_status`` /
        ``breeze persist status``)."""
        return {
            "dir": self.dir,
            "journal_bytes": self.journal.size,
            "journal_records": self.journal.records,
            "last_fsync_age_s": round(self.journal.fsync_age_s(), 3),
            "wedged": self.journal.wedged,
            "compactions": self.compactions,
            "append_errors": self.append_errors,
            "books": {
                name: {"records": len(book), "digest": book_digest(book)}
                for name, book in sorted(self.books.items())
            },
            "recovery": self.recovery,
            "faults": self.faults.status(),
        }

    def close(self) -> None:
        if not self.journal.wedged:
            try:
                self.sync()
            except OSError:  # pragma: no cover — best-effort on shutdown
                pass
        self.journal.close()
