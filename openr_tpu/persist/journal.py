"""Append-only binary journal: record grammar, replay, atomic snapshots.

One record on disk is::

    uvarint(len(payload)) | payload | crc32(payload) LE32

where ``payload`` is the PR 8 TLV wire form (`to_wire_bin`) of a
:class:`JournalRecord`. Snapshots reuse the identical grammar — a
snapshot file is just a compacted journal of OP_SET records — so there
is exactly one framing to fuzz and one decoder to trust.

Recovery contract (docs/Persist.md):

* a record whose length or body overruns EOF is a **torn tail** — the
  file is truncated back to the last good record boundary and replay
  returns what preceded it;
* a CRC mismatch on the **final** record is the same torn-at-crash
  case (the trailer never made it out of the page cache) — truncated;
* a CRC mismatch with further bytes following is **mid-journal
  corruption** and raises :class:`WireDecodeError` — loud, never
  silently accepted;
* a CRC-valid payload that fails TLV decode is a software/schema bug
  and also raises :class:`WireDecodeError`.

Durability discipline: appends are write+flush (page-cache durable —
survives SIGKILL), fsync rides an interval or an explicit ``sync()``
(power-fail durability); snapshots are fsync-temp → atomic-rename →
fsync-parent-dir via :func:`atomic_write_bytes`.
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from dataclasses import dataclass

from openr_tpu.types.serde import (
    WireDecodeError,
    from_wire_bin,
    register_wire_types,
    to_wire_bin,
    write_uvarint,
)

#: record operations: idempotent last-wins upsert / delete — replaying
#: a duplicate or stale record is harmless by construction.
OP_SET = 0
OP_DEL = 1

_CRC = struct.Struct("<I")


@dataclass
class JournalRecord:
    """One durable mutation: (book, op, key) plus the value for SET."""

    book: str
    op: int
    key: bytes
    value: bytes = b""


def encode_record(rec: JournalRecord) -> bytes:
    payload = to_wire_bin(rec)
    out = bytearray()
    write_uvarint(out, len(payload))
    out += payload
    out += _CRC.pack(zlib.crc32(payload) & 0xFFFFFFFF)
    return bytes(out)


class _TornTail(Exception):
    """Internal: frame overran EOF — not an error, a crash artifact."""


def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    n = 0
    while True:
        if pos >= len(data):
            raise _TornTail
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 63:
            # a runaway continuation chain is garbage, but by the torn
            # rule below it can only be salvaged when it is the tail
            raise _TornTail


def replay_frames(
    data: bytes, *, strict: bool = False
) -> tuple[list[JournalRecord], int]:
    """Decode a journal/snapshot byte string into records.

    Returns ``(records, truncated_bytes)`` where ``truncated_bytes`` is
    the torn tail the caller should cut off the file. With ``strict``
    (snapshots — atomically renamed, so a torn tail is impossible) any
    salvage condition raises :class:`WireDecodeError` instead.
    """
    records: list[JournalRecord] = []
    pos = 0
    good_end = 0
    while pos < len(data):
        start = pos
        try:
            ln, body = _read_uvarint(data, pos)
            if body + ln + _CRC.size > len(data):
                raise _TornTail
        except _TornTail:
            if strict:
                raise WireDecodeError(
                    f"snapshot: frame at offset {start} overruns EOF"
                ) from None
            break
        payload = data[body : body + ln]
        end = body + ln + _CRC.size
        if zlib.crc32(payload) & 0xFFFFFFFF != _CRC.unpack_from(data, body + ln)[0]:
            if end >= len(data) and not strict:
                break  # trailer torn at crash: salvage the prefix
            raise WireDecodeError(
                f"journal: CRC mismatch at offset {start} with "
                f"{len(data) - end} bytes following — mid-journal corruption"
            )
        records.append(from_wire_bin(payload, JournalRecord))
        pos = good_end = end
    return records, len(data) - good_end


class Journal:
    """Writer half: append-only file with flush-per-record durability.

    A torn-write fault wedges the journal (the model is a crash mid-
    write: the process is about to die, nothing after the torn record
    may reach disk); ENOSPC raises to the caller so in-memory state is
    only mutated for records that actually landed.
    """

    def __init__(self, path: str, faults=None):
        self.path = path
        self.faults = faults
        self._f = open(path, "ab")
        self.size = os.fstat(self._f.fileno()).st_size
        self.records = 0  # appended since open/compaction
        self.wedged = False
        self.last_fsync = time.monotonic()

    def append(self, rec: JournalRecord) -> bool:
        """Write one record; True when it (fully) reached the OS."""
        if self.wedged:
            return False
        frame = encode_record(rec)
        torn_at = None
        if self.faults is not None:
            frame, torn_at = self.faults.on_append(frame)  # may raise ENOSPC
        self._f.write(frame)
        self._f.flush()
        self.size += len(frame)
        if torn_at is not None:
            # crash-mid-write model: the writer believed the append
            # succeeded; nothing later may reach disk
            self.wedged = True
        self.records += 1
        return True

    def sync(self) -> None:
        if self.faults is not None:
            self.faults.on_fsync()
        self._f.flush()
        os.fsync(self._f.fileno())
        self.last_fsync = time.monotonic()

    def fsync_age_s(self) -> float:
        return time.monotonic() - self.last_fsync

    def reset(self) -> None:
        """Truncate to empty (post-compaction: the snapshot now carries
        everything)."""
        self._f.truncate(0)
        self._f.seek(0)
        self._f.flush()
        os.fsync(self._f.fileno())
        self.size = 0
        self.records = 0
        self.last_fsync = time.monotonic()

    def close(self) -> None:
        try:
            self._f.flush()
        finally:
            self._f.close()


def load_journal(path: str, *, strict: bool = False) -> tuple[list[JournalRecord], int]:
    """Replay a journal file, truncating any torn tail in place."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return [], 0
    records, torn = replay_frames(data, strict=strict)
    if torn:
        with open(path, "r+b") as f:
            f.truncate(len(data) - torn)
            f.flush()
            os.fsync(f.fileno())
    return records, torn


def atomic_write_bytes(path: str, data: bytes, faults=None) -> None:
    """The snapshot discipline: fsync-temp → atomic-rename →
    fsync-parent-dir. After return the bytes are power-fail durable; a
    crash at any point leaves either the old file or the new one,
    never a mix (recovery ignores ``*.tmp.*`` leftovers)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        if faults is not None:
            faults.on_fsync()
        os.fsync(f.fileno())
    if faults is not None:
        faults.on_rename()  # crash_between_rename raises here
    os.replace(tmp, path)
    dirfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def move_aside(path: str) -> str:
    """Park a corrupt durable file next to itself (never delete
    evidence) and return the new name."""
    n = 0
    while True:
        aside = f"{path}.corrupt" + (f".{n}" if n else "")
        if not os.path.exists(aside):
            break
        n += 1
    os.replace(path, aside)
    return aside


# wire-schema lock registration: every journal/snapshot payload is the
# TLV form of THIS record — schema drift here corrupts warm boots the
# same way flood-frame drift corrupts peers (docs/Persist.md)
register_wire_types(JournalRecord)
