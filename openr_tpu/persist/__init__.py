"""Crash-consistent durable-state plane.

reference: openr/config-store/PersistentStore.cpp † pairs graceful
restart with disk-backed state so a crashed daemon re-converges from
its own journal instead of re-learning the world. This package is that
seam for the whole node: an append-only binary journal + snapshot/
compaction engine (``journal``), the book-keeping plane modules mount
their durable state on (``plane``), seeded disk-fault injection
(``faults``), and a mock dataplane whose tables survive process death
(``dataplane``). docs/Persist.md is the grammar + recovery contract.

Every byte that must survive a crash goes through this package —
orlint rule OR014 flags raw ``open(..., "w")`` / ``os.replace`` /
``json.dump`` persistence seams elsewhere in the tree.
"""

from openr_tpu.persist.faults import DiskFaultInjector, InjectedCrash
from openr_tpu.persist.journal import (
    OP_DEL,
    OP_SET,
    Journal,
    JournalRecord,
    atomic_write_bytes,
    encode_record,
    move_aside,
    replay_frames,
)
from openr_tpu.persist.plane import PersistPlane, book_digest

__all__ = [
    "DiskFaultInjector",
    "InjectedCrash",
    "Journal",
    "JournalRecord",
    "OP_DEL",
    "OP_SET",
    "PersistPlane",
    "atomic_write_bytes",
    "book_digest",
    "encode_record",
    "move_aside",
    "replay_frames",
]
