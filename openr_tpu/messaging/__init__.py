"""Typed in-process queues: the module interconnect.

reference: openr/messaging/ReplicateQueue.h † / Queue.h † — single-writer
multi-reader replicated queue; every module-to-module arrow in the
dataflow graph is one of these. The reference runs each module on its own
folly::EventBase thread; here modules are asyncio tasks on one loop, and
the queues are the only coupling between them (same shared-nothing
design, reference: SURVEY §2 "thread-per-module concurrency").
"""

from __future__ import annotations

import asyncio
from typing import Generic, TypeVar

T = TypeVar("T")


class QueueClosedError(Exception):
    """Raised by RQueue.get() once the queue is closed and drained
    (reference: messaging/Queue.h † QueueClosedError)."""


class RQueue(Generic[T]):
    """Reader endpoint of a ReplicateQueue (reference: RQueue<T> †)."""

    def __init__(self, name: str = ""):
        self.name = name
        self._q: asyncio.Queue = asyncio.Queue()
        self._closed = False

    async def get(self) -> T:
        """Await the next item; QueueClosedError after close+drain."""
        if self._closed and self._q.empty():
            raise QueueClosedError(self.name)
        item = await self._q.get()
        if item is _CLOSE:
            self._closed = True
            raise QueueClosedError(self.name)
        return item

    def try_get(self) -> T | None:
        """Non-blocking get; None if empty (or closed)."""
        while not self._q.empty():
            item = self._q.get_nowait()
            if item is _CLOSE:
                self._closed = True
                return None
            return item
        return None

    def size(self) -> int:
        return self._q.qsize()

    @property
    def closed(self) -> bool:
        return self._closed


class _Close:
    pass


_CLOSE = _Close()


class ReplicateQueue(Generic[T]):
    """Single-writer multi-reader queue: push() replicates to every reader.

    reference: messaging/ReplicateQueue.h † — getReader(), push(),
    close(); per-reader buffering so a slow consumer can't drop another
    consumer's messages.
    """

    def __init__(self, name: str = ""):
        self.name = name
        self._readers: list[RQueue[T]] = []
        self._closed = False
        self._writes = 0

    def get_reader(self, name: str = "") -> RQueue[T]:
        if self._closed:
            raise QueueClosedError(self.name)
        r: RQueue[T] = RQueue(name or f"{self.name}.r{len(self._readers)}")
        self._readers.append(r)
        return r

    def push(self, item: T) -> int:
        """Replicate to all readers; returns replication count."""
        if self._closed:
            raise QueueClosedError(self.name)
        self._writes += 1
        for r in self._readers:
            r._q.put_nowait(item)
        return len(self._readers)

    def close(self) -> None:
        """Signal end-of-stream; readers drain then see QueueClosedError."""
        if not self._closed:
            self._closed = True
            for r in self._readers:
                r._q.put_nowait(_CLOSE)

    @property
    def num_readers(self) -> int:
        return len(self._readers)

    @property
    def num_writes(self) -> int:
        return self._writes
