"""Typed in-process queues: the module interconnect.

reference: openr/messaging/ReplicateQueue.h † / Queue.h † — single-writer
multi-reader replicated queue; every module-to-module arrow in the
dataflow graph is one of these. The reference runs each module on its own
folly::EventBase thread; here modules are asyncio tasks on one loop, and
the queues are the only coupling between them (same shared-nothing
design, reference: SURVEY §2 "thread-per-module concurrency").

Overload control (DeltaPath, PAPERS.md: churn throughput is governed by
how updates are batched and coalesced at the seams): every queue takes an
optional bound plus an overflow policy, so a producer outrunning its
consumer hits a deliberate, *measured* regime instead of unbounded RAM
growth:

  * ``block``       — backpressure: ``put_nowait`` raises
                      :class:`QueueFullError`; async producers use
                      ``await q.put(item)`` and wait for room.
  * ``coalesce``    — merge the newest item into the pending tail via a
                      caller-supplied ``coalesce_fn(tail, new) -> merged``
                      (the natural policy for mergeable deltas:
                      publications, route updates). A ``None`` return
                      means unmergeable — the item is appended past the
                      bound and counted as overflow.
  * ``shed_oldest`` — drop the oldest pending item (telemetry streams:
                      log samples, perf traces).

Every queue exports ``queue.<key>.depth`` gauges plus
``.highwater`` / ``.coalesced`` / ``.shed`` / ``.overflow`` counters
through the node's Counters registry (and so the Prometheus endpoint and
``breeze monitor queues``).
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Callable, Generic, TypeVar

T = TypeVar("T")

# overflow policies (None = unbounded, the seed behavior)
BLOCK = "block"
COALESCE = "coalesce"
SHED_OLDEST = "shed_oldest"
_POLICIES = (None, BLOCK, COALESCE, SHED_OLDEST)


class QueueClosedError(Exception):
    """Raised by RQueue.get() once the queue is closed and drained
    (reference: messaging/Queue.h † QueueClosedError)."""


class QueueFullError(Exception):
    """Raised by put_nowait() on a full ``block``-policy queue: the
    producer must apply backpressure (``await q.put(item)``) instead of
    growing the backlog."""


class _Close:
    pass


_CLOSE = _Close()


class RQueue(Generic[T]):
    """Reader endpoint of a ReplicateQueue (reference: RQueue<T> †)."""

    def __init__(
        self,
        name: str = "",
        maxsize: int = 0,
        policy: str | None = None,
        coalesce_fn: Callable[[T, T], T | None] | None = None,
        counters=None,
        counter_key: str | None = None,
    ):
        assert policy in _POLICIES, policy
        assert policy != COALESCE or coalesce_fn is not None
        self.name = name
        self.maxsize = maxsize
        self.policy = policy if maxsize > 0 else None
        self.coalesce_fn = coalesce_fn
        self.counters = counters
        self.ckey = counter_key or name
        # gauge keys precomputed: _gauge runs on EVERY put/get of the
        # hot seams — per-op f-string construction is wasted work
        self._k_depth = f"queue.{self.ckey}.depth"
        self._k_highwater = f"queue.{self.ckey}.highwater"
        self._k_blocked = f"queue.{self.ckey}.blocked"
        self._items: deque = deque()
        self._getters: deque[asyncio.Future] = deque()
        self._putters: deque[asyncio.Future] = deque()
        self._closed = False  # sentinel consumed: fully drained
        self._closing = False  # close() called: no new writes
        # lifetime stats, readable without a Counters registry (the
        # invariant checker walks these directly)
        self.highwater = 0
        self.coalesced = 0
        self.shed = 0
        self.overflow = 0

    # ------------------------------------------------------------- plumbing

    def _wake(self, waiters: deque) -> None:
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                return

    def _gauge(self) -> None:
        n = len(self._items)
        if n > self.highwater:
            self.highwater = n
            if self.counters is not None:
                self.counters.set(self._k_highwater, n)
                if self.policy is not None and n * 2 >= self.maxsize:
                    # flight recorder: a policied seam crossing half its
                    # bound with a NEW watermark is the early overload
                    # signal a post-mortem wants; rare by construction
                    # (each depth fires at most once per queue lifetime)
                    fr = getattr(self.counters, "flight_record", None)
                    if fr is not None:
                        fr(
                            "queue.highwater",
                            queue=self.ckey,
                            depth=n,
                            cap=self.maxsize,
                        )
        if self.counters is not None:
            self.counters.set(self._k_depth, n)

    def _count(self, what: str, attr: str) -> None:
        setattr(self, attr, getattr(self, attr) + 1)
        if self.counters is not None:
            self.counters.increment(f"queue.{self.ckey}.{what}")

    @property
    def full(self) -> bool:
        return self.maxsize > 0 and len(self._items) >= self.maxsize

    # ---------------------------------------------------------------- write

    def put_nowait(self, item: T, force: bool = False) -> None:
        """Enqueue one item, applying the overflow policy at the bound.
        ``force`` bypasses the bound (the close sentinel must always
        land)."""
        if (self._closed or self._closing) and not force:
            raise QueueClosedError(self.name)
        if self.full and not force:
            if self.policy == COALESCE and self._items:
                tail = self._items[-1]
                if not isinstance(tail, _Close):
                    merged = self.coalesce_fn(tail, item)
                    if merged is not None:
                        self._items[-1] = merged
                        self._count("coalesced", "coalesced")
                        self._gauge()
                        return
                # unmergeable tail (e.g. different area): admit past the
                # bound rather than lose data — counted so the soak's
                # bounded-depth invariant can see it
                self._count("overflow", "overflow")
            elif self.policy == SHED_OLDEST:
                self._items.popleft()
                self._count("shed", "shed")
            elif self.policy == BLOCK:
                raise QueueFullError(self.name)
        self._items.append(item)
        self._wake(self._getters)
        self._gauge()

    async def _wait_room(self) -> None:
        """Wait until this ``block``-policy queue has room (or closes)."""
        while (
            self.full
            and self.policy == BLOCK
            and not (self._closed or self._closing)
        ):
            fut = asyncio.get_event_loop().create_future()
            self._putters.append(fut)
            if self.counters is not None:
                self.counters.increment(self._k_blocked)
            try:
                await fut
            except asyncio.CancelledError:
                if fut.done() and not self.full:
                    # our wakeup already fired: pass it on, or room sits
                    # free while another producer sleeps
                    self._wake(self._putters)
                raise

    async def put(self, item: T) -> None:
        """Backpressured enqueue: waits for room on a full ``block``
        queue (the producer-side seam of the overload design)."""
        await self._wait_room()
        self.put_nowait(item)

    # ----------------------------------------------------------------- read

    async def get(self) -> T:
        """Await the next item; QueueClosedError after close+drain."""
        while not self._items:
            if self._closed:
                raise QueueClosedError(self.name)
            fut = asyncio.get_event_loop().create_future()
            self._getters.append(fut)
            try:
                await fut
            except asyncio.CancelledError:
                if fut.done() and self._items:
                    # our wakeup already fired: pass it on, or the item
                    # sits while another getter sleeps
                    self._wake(self._getters)
                raise
        item = self._items.popleft()
        self._wake(self._putters)
        self._gauge()
        if isinstance(item, _Close):
            self._closed = True
            raise QueueClosedError(self.name)
        return item

    def try_get(self) -> T | None:
        """Non-blocking get; None if empty (or closed)."""
        while self._items:
            item = self._items.popleft()
            self._wake(self._putters)
            self._gauge()
            if isinstance(item, _Close):
                self._closed = True
                return None
            return item
        return None

    def size(self) -> int:
        return len(self._items)

    # stdlib-compatible aliases: call sites migrated off raw
    # asyncio.Queue (OR004) keep their shape
    def qsize(self) -> int:
        return len(self._items)

    def get_nowait(self) -> T | None:
        """Alias of try_get(): next item or None when empty/closed."""
        return self.try_get()

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        """Close this reader endpoint directly (standalone RQueues, e.g.
        the rpc stream buffers): wakes any blocked producer — whose next
        ``put`` raises :class:`QueueClosedError` — and ``get`` raises it
        after the drain sentinel. Readers minted by a ReplicateQueue are
        closed via ``ReplicateQueue.close()`` instead."""
        self._close()

    def _close(self) -> None:
        self._closing = True
        self.put_nowait(_CLOSE, force=True)
        # blocked producers must not wait on a dead queue
        for fut in self._putters:
            if not fut.done():
                fut.set_result(None)
        self._putters.clear()


class ReplicateQueue(Generic[T]):
    """Single-writer multi-reader queue: push() replicates to every reader.

    reference: messaging/ReplicateQueue.h † — getReader(), push(),
    close(); per-reader buffering so a slow consumer can't drop another
    consumer's messages. With ``maxsize`` set, each reader is bounded and
    applies this queue's overflow policy independently (a slow reader
    coalesces/sheds its OWN backlog; the fast one still sees every item).
    """

    def __init__(
        self,
        name: str = "",
        maxsize: int = 0,
        policy: str | None = None,
        coalesce_fn: Callable[[T, T], T | None] | None = None,
        counters=None,
        counter_key: str | None = None,
    ):
        assert policy in _POLICIES, policy
        self.name = name
        self.maxsize = maxsize
        self.policy = policy
        self.coalesce_fn = coalesce_fn
        self.counters = counters
        self.ckey = counter_key or name
        self._readers: list[RQueue[T]] = []
        self._closed = False
        self._writes = 0

    def get_reader(self, name: str = "") -> RQueue[T]:
        if self._closed:
            raise QueueClosedError(self.name)
        r: RQueue[T] = RQueue(
            name or f"{self.name}.r{len(self._readers)}",
            maxsize=self.maxsize,
            policy=self.policy,
            coalesce_fn=self.coalesce_fn,
            counters=self.counters,
            counter_key=self.ckey,
        )
        self._readers.append(r)
        return r

    def push(self, item: T) -> int:
        """Replicate to all readers; returns replication count. Raises
        QueueFullError when a ``block``-policy reader is full — sync
        producers of block queues must use ``await put()``. The check
        runs BEFORE any delivery (no awaits in between), so a raised
        push delivered to nobody and a retry can't duplicate."""
        if self._closed:
            raise QueueClosedError(self.name)
        for r in self._readers:
            if r.full and r.policy == BLOCK and not (r._closing or r.closed):
                raise QueueFullError(r.name)
        self._writes += 1
        for r in self._readers:
            r.put_nowait(item)
        return len(self._readers)

    async def put(self, item: T) -> int:
        """Backpressured replicate: waits for room in EVERY reader before
        enqueueing anywhere, so one slow reader throttles the producer
        (the ``block`` policy's contract). The scan restarts from the
        first reader after every wait — a concurrent producer may have
        refilled an earlier reader while we slept on a later one."""
        while True:
            if self._closed:
                raise QueueClosedError(self.name)
            blocked = next(
                (
                    r
                    for r in self._readers
                    if r.full
                    and r.policy == BLOCK
                    and not (r._closing or r.closed)
                ),
                None,
            )
            if blocked is None:
                break
            await blocked._wait_room()
        self._writes += 1
        for r in self._readers:
            r.put_nowait(item)
        return len(self._readers)

    def close(self) -> None:
        """Signal end-of-stream; readers drain then see QueueClosedError."""
        if not self._closed:
            self._closed = True
            for r in self._readers:
                r._close()

    @property
    def num_readers(self) -> int:
        return len(self._readers)

    @property
    def num_writes(self) -> int:
        return self._writes

    @property
    def readers(self) -> tuple[RQueue[T], ...]:
        """Reader endpoints (the invariant checker walks their depth
        watermarks)."""
        return tuple(self._readers)
