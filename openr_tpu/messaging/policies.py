"""Coalesce functions for the bounded messaging seams.

The ``coalesce`` overflow policy (messaging/__init__.py) needs a merge
for each mergeable delta type. Both merges here build a NEW object —
the tail item is replicated to every reader of a ReplicateQueue, so
mutating it in one reader's backlog would corrupt the others'.

Correctness rests on queue order: a node's local publication stream is
emitted in merge-acceptance order, so for any key the later publication
carries a value at least as new (KvStore's merge is monotone per key) —
"newest wins" at the tail IS the version-dominant merge. Route updates
compose like Fib folds them (``Fib._fold_update``): a FULL_SYNC resets
the state, deltas apply over it.
"""

from __future__ import annotations

from openr_tpu.types.kvstore import Publication
from openr_tpu.types.routes import RouteUpdate, RouteUpdateType

# traces kept on a coalesced route update: same spirit as
# Fib.PERF_PENDING_CAP — an overload burst must not grow the trace list
_PERF_CAP = 64


def coalesce_publications(
    tail: Publication, new: Publication
) -> Publication | None:
    """Merge ``new`` into a copy of ``tail``; ``None`` when unmergeable
    (different areas — the caller admits the item past the bound and
    counts overflow)."""
    if tail.area != new.area:
        return None
    kv = dict(tail.key_vals)
    expired = dict.fromkeys(tail.expired_keys)  # ordered set
    for k, v in new.key_vals.items():
        kv[k] = v
        expired.pop(k, None)  # re-advertised after expiry: alive again
    for k in new.expired_keys:
        kv.pop(k, None)  # expired after update: dead is the final word
        expired[k] = None
    node_ids = list(tail.node_ids)
    node_ids.extend(n for n in new.node_ids if n not in node_ids)
    pe = tail.perf_events
    if new.perf_events is not None:
        pe = (
            new.perf_events.copy()
            if pe is None
            else pe.merge(new.perf_events)  # merge() returns a new trace
        )
    return Publication(
        area=tail.area,
        key_vals=kv,
        expired_keys=list(expired),
        node_ids=node_ids,
        perf_events=pe,
    )


def coalesce_route_updates(
    tail: RouteUpdate, new: RouteUpdate
) -> RouteUpdate:
    """Merge ``new`` into a copy of ``tail`` (always succeeds).

    A FULL_SYNC ``new`` supersedes everything pending; otherwise the
    delta folds over the tail exactly as Fib would fold the two in
    sequence, and the merged update keeps the tail's type (a pending
    FULL_SYNC stays a FULL_SYNC with the delta applied)."""
    perf = list(tail.perf_events)
    for pe in new.perf_events:
        if len(perf) >= _PERF_CAP:
            break
        perf.append(pe)
    if new.type == RouteUpdateType.FULL_SYNC:
        return RouteUpdate(
            type=RouteUpdateType.FULL_SYNC,
            unicast_to_update=dict(new.unicast_to_update),
            mpls_to_update=dict(new.mpls_to_update),
            perf_events=perf,
        )
    u_upd = dict(tail.unicast_to_update)
    u_del = dict.fromkeys(tail.unicast_to_delete)
    m_upd = dict(tail.mpls_to_update)
    m_del = dict.fromkeys(tail.mpls_to_delete)
    for p, e in new.unicast_to_update.items():
        u_upd[p] = e
        u_del.pop(p, None)
    for p in new.unicast_to_delete:
        u_upd.pop(p, None)
        if tail.type != RouteUpdateType.FULL_SYNC:
            u_del[p] = None
    for label, e in new.mpls_to_update.items():
        m_upd[label] = e
        m_del.pop(label, None)
    for label in new.mpls_to_delete:
        m_upd.pop(label, None)
        if tail.type != RouteUpdateType.FULL_SYNC:
            m_del[label] = None
    return RouteUpdate(
        type=tail.type,
        unicast_to_update=u_upd,
        unicast_to_delete=list(u_del),
        mpls_to_update=m_upd,
        mpls_to_delete=list(m_del),
        perf_events=perf,
    )
