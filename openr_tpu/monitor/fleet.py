"""Fleet metric aggregation: cross-node counter distributions.

Per-node counters answer "what is node X doing"; at cluster scale the
operator question is distributional — "what is the p99 queue depth
across the fleet, and which node is the max". This module turns N
per-node ``Counters.snapshot()`` dicts into per-key cross-node
distributions (min/p50/p99/max/mean + the argmax node), shared by:

  * ``breeze monitor fleet`` — scrapes ``get_counters`` from a list of
    ctrl endpoints and renders the table;
  * ``Cluster.fleet_counters()`` — the emulator hook (same math over
    the in-process nodes' registries);
  * benches/CI that gate on fleet-wide percentiles.

Percentiles here are EXACT over the per-node values (node counts are
small — thousands at most), unlike the log-bucketed within-node stat
histograms (monitor/counters.py, ~12% bucket error).
"""

from __future__ import annotations


def percentile(vals: list[float], q: float) -> float:
    """Exact nearest-rank percentile over raw values — the one
    definition shared by the fleet tables, the flood-trace attribution
    (monitor/flood_trace.py) and the emulator convergence bench
    (emulator/convergence.py); the within-node stat histograms use the
    log-bucketed approximation in monitor/counters.py instead."""
    vs = sorted(vals)
    return vs[min(len(vs) - 1, int(len(vs) * q))]


_percentile = percentile  # module-internal alias


def aggregate_counters(
    snapshots: dict[str, dict[str, float]], prefix: str = ""
) -> dict[str, dict]:
    """``{node: snapshot}`` → ``{key: distribution}``.

    Each distribution: ``{"nodes", "min", "p50", "p99", "max", "mean",
    "sum", "max_node"}``. Keys missing on a node simply don't
    contribute (a key present on 3 of 64 nodes aggregates over 3 —
    ``nodes`` says so).

    Ratio-type gauges (any ``*.ratio`` key, e.g. the work ledger's
    ``work.<stage>.ratio``) aggregate by distribution ONLY: a sum of
    per-node ratios is dimensionally meaningless, so their ``sum`` is
    ``None`` rather than a number a dashboard might graph."""
    per_key: dict[str, list[tuple[float, str]]] = {}
    for node, snap in snapshots.items():
        for k, v in snap.items():
            if prefix and not k.startswith(prefix):
                continue
            per_key.setdefault(k, []).append((float(v), node))
    out: dict[str, dict] = {}
    for k, pairs in per_key.items():
        vals = [v for v, _n in pairs]
        vmax, max_node = max(pairs, key=lambda p: p[0])
        out[k] = {
            "nodes": len(vals),
            "min": min(vals),
            "p50": _percentile(vals, 0.5),
            "p99": _percentile(vals, 0.99),
            "max": vmax,
            "mean": sum(vals) / len(vals),
            "sum": None if k.endswith(".ratio") else sum(vals),
            "max_node": max_node,
        }
    return out


def fleet_rows(
    agg: dict[str, dict], limit: int = 0
) -> list[list[str]]:
    """Render-ready rows (key, nodes, min, p50, p99, max, max-node),
    sorted by key; ``limit`` > 0 keeps the first N."""
    def fmt(v: float) -> str:
        return f"{v:g}" if v == int(v) else f"{v:.3f}"

    rows = [
        [
            k,
            str(d["nodes"]),
            fmt(d["min"]),
            fmt(d["p50"]),
            fmt(d["p99"]),
            fmt(d["max"]),
            d["max_node"],
        ]
        for k, d in sorted(agg.items())
    ]
    return rows[:limit] if limit > 0 else rows


FLEET_HEADERS = ["counter", "nodes", "min", "p50", "p99", "max", "max-node"]
