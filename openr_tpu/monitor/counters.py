"""fb303-style counters.

reference: fb303::fbData — a process-global stats registry in the
reference; here one `Counters` instance per emulated node (N nodes share a
process in tests/emulator, so it must not be a module-level singleton).
setCounter ≙ set, addStatValue ≙ add_value (keeps sum/count/min/max/last
like the reference's timeseries export, without the windowing).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class _Stat:
    sum: float = 0.0
    count: int = 0
    min: float = float("inf")
    max: float = float("-inf")
    last: float = 0.0

    def add(self, v: float) -> None:
        self.sum += v
        self.count += 1
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        self.last = v

    @property
    def avg(self) -> float:
        return self.sum / self.count if self.count else 0.0


@dataclass
class Counters:
    counters: dict[str, float] = field(default_factory=dict)
    stats: dict[str, _Stat] = field(default_factory=dict)

    def set(self, key: str, value: float) -> None:
        self.counters[key] = value

    def increment(self, key: str, delta: float = 1) -> None:
        self.counters[key] = self.counters.get(key, 0) + delta

    def get(self, key: str, default: float = 0) -> float:
        return self.counters.get(key, default)

    def add_value(self, key: str, value: float) -> None:
        self.stats.setdefault(key, _Stat()).add(value)

    def touch(self, key: str) -> None:
        """Timestamp counter (reference pattern: `<event>.time` counters)."""
        self.counters[key] = time.time()

    def snapshot(self) -> dict[str, float]:
        """Flat export (reference: getCounters() thrift API shape —
        stats expand to .sum/.count/.avg/.min/.max suffixes)."""
        out = dict(self.counters)
        for k, s in self.stats.items():
            out[f"{k}.sum"] = s.sum
            out[f"{k}.count"] = s.count
            out[f"{k}.avg"] = s.avg
            if s.count:
                out[f"{k}.min"] = s.min
                out[f"{k}.max"] = s.max
        return out
