"""fb303-style counters.

reference: fb303::fbData — a process-global stats registry in the
reference; here one `Counters` instance per emulated node (N nodes share a
process in tests/emulator, so it must not be a module-level singleton).
setCounter ≙ set, addStatValue ≙ add_value. add_value keys keep the
all-time sum/count/min/max/last the seed exported AND feed fb303-style
sliding windows (60 s / 600 s / all-time) of log-bucketed histograms, so
every latency stat exports `.p50` / `.p99` per window — the reference's
ExportedStatMapImpl + ExportedHistogramMapImpl surface
(`<key>.<stat>.<window>` counter names †).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

# Log-spaced histogram bucket upper edges, in the stat's own unit
# (latencies here are milliseconds): 10 buckets per decade (ratio
# ~1.26, so a percentile read off the geometric bucket midpoint is
# within ~12%), spanning 1 µs .. ~800 s. Values above the last edge
# land in a final overflow bucket.
_EDGES = tuple(0.001 * 10 ** (i / 10) for i in range(120))
_N_BUCKETS = len(_EDGES) + 1  # + overflow

# sliding-window layout: 10 s sub-buckets, windows in whole sub-buckets
_SUB_S = 10
WINDOWS_S = (60, 600)


def _bucket_of(v: float) -> int:
    """Index of the histogram bucket containing v (binary search over
    the static edges)."""
    lo, hi = 0, len(_EDGES)
    while lo < hi:
        mid = (lo + hi) // 2
        if v <= _EDGES[mid]:
            hi = mid
        else:
            lo = mid + 1
    return lo


def _bucket_mid(i: int) -> float:
    """Representative value for bucket i: geometric midpoint (log-spaced
    edges), edge values for the boundary buckets."""
    if i == 0:
        return _EDGES[0]
    if i >= len(_EDGES):
        return _EDGES[-1]
    return (_EDGES[i - 1] * _EDGES[i]) ** 0.5


def _percentile(counts: list[int], q: float) -> float | None:
    total = sum(counts)
    if total == 0:
        return None
    target = max(1, int(q * total + 0.5))
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= target:
            return _bucket_mid(i)
    return _bucket_mid(len(counts) - 1)


@dataclass
class _Stat:
    sum: float = 0.0
    count: int = 0
    min: float = float("inf")
    max: float = float("-inf")
    last: float = 0.0
    # all-time histogram + sliding 10 s sub-histograms (newest last);
    # sub-entries are (sub_bucket_index_of_time, counts)
    hist: list[int] = field(default_factory=lambda: [0] * _N_BUCKETS)
    subs: list[tuple[int, list[int]]] = field(default_factory=list)

    def add(self, v: float, now: float | None = None) -> None:
        self.sum += v
        self.count += 1
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        self.last = v
        b = _bucket_of(v)
        self.hist[b] += 1
        t = time.monotonic() if now is None else now
        sub = int(t // _SUB_S)
        if not self.subs or self.subs[-1][0] != sub:
            self.subs.append((sub, [0] * _N_BUCKETS))
            self._evict(sub)
        self.subs[-1][1][b] += 1

    def _evict(self, newest_sub: int) -> None:
        horizon = newest_sub - max(WINDOWS_S) // _SUB_S
        while self.subs and self.subs[0][0] < horizon:
            self.subs.pop(0)

    def window_counts(self, window_s: int, now: float | None = None) -> list[int]:
        """Merged histogram of the trailing `window_s` seconds."""
        t = time.monotonic() if now is None else now
        oldest = int(t // _SUB_S) - window_s // _SUB_S
        merged = [0] * _N_BUCKETS
        for sub, counts in self.subs:
            if sub <= oldest:
                continue
            for i, c in enumerate(counts):
                if c:
                    merged[i] += c
        return merged

    def percentile(
        self, q: float, window_s: int | None = None, now: float | None = None
    ) -> float | None:
        """q-quantile (0..1) from the bucketed histogram; None when the
        window holds no samples. window_s=None → all-time."""
        counts = (
            self.hist if window_s is None else self.window_counts(window_s, now)
        )
        return _percentile(counts, q)

    @property
    def avg(self) -> float:
        return self.sum / self.count if self.count else 0.0


@dataclass
class Counters:
    counters: dict[str, float] = field(default_factory=dict)
    stats: dict[str, _Stat] = field(default_factory=dict)
    # optional per-node FlightRecorder (monitor/flight.py), attached by
    # OpenrNode — riding the registry because every module already
    # holds a Counters, so record sites need no new constructor
    # plumbing. Excluded from snapshot()/compare: it is a post-mortem
    # ring, not a metric.
    flight: object | None = field(default=None, compare=False, repr=False)

    def flight_record(self, kind: str, **attrs) -> None:
        """Record one flight-recorder event; no-op when no recorder is
        attached (benches / bare Counters in tests)."""
        f = self.flight
        if f is not None:
            f.record(kind, **attrs)

    def set(self, key: str, value: float) -> None:
        self.counters[key] = value

    def increment(self, key: str, delta: float = 1) -> None:
        self.counters[key] = self.counters.get(key, 0) + delta

    def get(self, key: str, default: float = 0) -> float:
        return self.counters.get(key, default)

    def add_value(self, key: str, value: float, now: float | None = None) -> None:
        """Record one sample (`now` is injectable for window tests)."""
        self.stats.setdefault(key, _Stat()).add(value, now=now)

    def touch(self, key: str) -> None:
        """Timestamp counter (reference pattern: `<event>.time` counters)."""
        self.counters[key] = time.time()

    def snapshot(self, now: float | None = None) -> dict[str, float]:
        """Flat export (reference: getCounters() thrift API shape —
        stats expand to .sum/.count/.avg/.min/.max plus windowed
        `.p50`/`.p99` and `.p50.<window>`/`.p99.<window>` suffixes)."""
        out = dict(self.counters)
        for k, s in self.stats.items():
            out[f"{k}.sum"] = s.sum
            out[f"{k}.count"] = s.count
            out[f"{k}.avg"] = s.avg
            if s.count:
                out[f"{k}.min"] = s.min
                out[f"{k}.max"] = s.max
                for q, qname in ((0.5, "p50"), (0.99, "p99")):
                    v = s.percentile(q, None, now)
                    if v is not None:
                        out[f"{k}.{qname}"] = v
                    for w in WINDOWS_S:
                        v = s.percentile(q, w, now)
                        if v is not None:
                            out[f"{k}.{qname}.{w}"] = v
        return out


# --------------------------------------------------------- prometheus export


def _esc(label_value: str) -> str:
    """Prometheus label-value escaping (text exposition format: backslash,
    double-quote, newline)."""
    return (
        label_value.replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _num(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(round(float(v), 6))


def render_prometheus(
    counters: Counters, node: str, now: float | None = None
) -> str:
    """Prometheus text exposition (format 0.0.4) of one node's counters.

    Counter keys are dotted free-form strings, so they ride in a `key`
    label rather than the metric name (names allow only [a-zA-Z0-9_:]).
    Three families:

      openr_counter{node,key}                       plain counters
      openr_stat{node,key,stat[,window]}            add_value aggregates
                                                    + windowed p50/p99
      openr_latency_bucket/_sum/_count{node,key,le} all-time histogram
    """
    lines: list[str] = []
    n = _esc(node)

    lines.append("# TYPE openr_counter gauge")
    for k in sorted(counters.counters):
        lines.append(
            f'openr_counter{{node="{n}",key="{_esc(k)}"}} '
            f"{_num(counters.counters[k])}"
        )

    lines.append("# TYPE openr_stat gauge")
    for k in sorted(counters.stats):
        s = counters.stats[k]
        ek = _esc(k)
        base = (
            ("count", float(s.count)),
            ("sum", s.sum),
            ("avg", s.avg),
        )
        for stat, v in base:
            lines.append(
                f'openr_stat{{node="{n}",key="{ek}",stat="{stat}"}} {_num(v)}'
            )
        if not s.count:
            continue
        for q, qname in ((0.5, "p50"), (0.99, "p99")):
            v = s.percentile(q, None, now)
            if v is not None:
                lines.append(
                    f'openr_stat{{node="{n}",key="{ek}",stat="{qname}",'
                    f'window="all"}} {_num(v)}'
                )
            for w in WINDOWS_S:
                v = s.percentile(q, w, now)
                if v is not None:
                    lines.append(
                        f'openr_stat{{node="{n}",key="{ek}",stat="{qname}",'
                        f'window="{w}s"}} {_num(v)}'
                    )

    lines.append("# TYPE openr_latency histogram")
    for k in sorted(counters.stats):
        s = counters.stats[k]
        if not s.count:
            continue
        ek = _esc(k)
        acc = 0
        for i, c in enumerate(s.hist[: len(_EDGES)]):
            if not c:
                continue  # one line per OCCUPIED bucket: dense enough to
                # parse, sparse enough to read (120 empty les elided);
                # cumulative values stay exact since empties add 0
            acc += c
            lines.append(
                f'openr_latency_bucket{{node="{n}",key="{ek}",'
                f'le="{_num(_EDGES[i])}"}} {acc}'
            )
        lines.append(
            f'openr_latency_bucket{{node="{n}",key="{ek}",le="+Inf"}} '
            f"{s.count}"
        )
        lines.append(
            f'openr_latency_sum{{node="{n}",key="{ek}"}} {_num(s.sum)}'
        )
        lines.append(
            f'openr_latency_count{{node="{n}",key="{ek}"}} {s.count}'
        )
    return "\n".join(lines) + "\n"
