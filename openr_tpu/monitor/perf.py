"""PerfEvents: per-update convergence tracing across the pipeline.

reference: openr/common/Types.thrift † PerfEvents / openr/common/Util.h †
addPerfEvent — the reference attaches an ordered (eventDescr, unixTs)
marker list to every update flowing spark → kvstore → decision → fib, and
`breeze perf` renders the per-stage deltas. That trace, not solver
throughput, is how operators measure convergence (also the metric DeltaPath
argues for, PAPERS.md 1808.06893). Here the record rides the existing
queue payloads (NeighborEvent → Publication → RouteUpdate) and completed
traces land in Monitor's perf ring.

Stage marker vocabulary (every name used by a stamp call MUST appear in
docs/Monitor.md — ci.sh lints this):

  NEIGHBOR_EVENT      Spark emitted a neighbor up/down/restart event
  ADJ_DB_UPDATED      LinkMonitor folded it into the adjacency set
  KVSTORE_FLOODED     KvStore accepted + published the adj/prefix update
  DECISION_RECEIVED   Decision buffered the publication
  DECISION_DEBOUNCED  the debounce window fired; rebuild started
  REBUILD_FULL        the rebuild took the from-scratch path (SPF solves)
  REBUILD_PREFIX_ONLY the rebuild took the dirty-scoped prefix-only path
                      (zero SPF solves — cached artifacts re-assembled)
  REBUILD_TOPO_DELTA  the rebuild warm-started from the cached solve
                      (bounded-region recompute; zero full area solves)
  SPF_SOLVE_DONE      SPF solve + RIB assembly + diff finished
  ROUTE_UPDATE_SENT   the route delta was pushed toward Fib
  FIB_PROGRAMMED      Fib programmed the delta into the dataplane

Timestamps are time.monotonic_ns(): exact for deltas within one
process (the emulator, and each real node's own pipeline), but NOT
comparable across hosts — a trace flooded over the TCP transport mixes
clock domains, so cross-host deltas are only indicative of ordering,
never of duration (the reference uses unix timestamps and accepts NTP
skew instead; we keep exact in-process deltas, the quantity the
benchmarks and the windowed convergence stat are built on).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

NEIGHBOR_EVENT = "NEIGHBOR_EVENT"
ADJ_DB_UPDATED = "ADJ_DB_UPDATED"
KVSTORE_FLOODED = "KVSTORE_FLOODED"
DECISION_RECEIVED = "DECISION_RECEIVED"
DECISION_DEBOUNCED = "DECISION_DEBOUNCED"
REBUILD_FULL = "REBUILD_FULL"
REBUILD_PREFIX_ONLY = "REBUILD_PREFIX_ONLY"
REBUILD_TOPO_DELTA = "REBUILD_TOPO_DELTA"
SPF_SOLVE_DONE = "SPF_SOLVE_DONE"
ROUTE_UPDATE_SENT = "ROUTE_UPDATE_SENT"
FIB_PROGRAMMED = "FIB_PROGRAMMED"

# canonical spark→fib stage order; doubles as the doc-lint source of
# truth. REBUILD_FULL / REBUILD_PREFIX_ONLY / REBUILD_TOPO_DELTA are
# alternatives at the same stage position — exactly one of them is
# stamped per rebuild, recording which pipeline the debounced batch took.
ALL_MARKERS = (
    NEIGHBOR_EVENT,
    ADJ_DB_UPDATED,
    KVSTORE_FLOODED,
    DECISION_RECEIVED,
    DECISION_DEBOUNCED,
    REBUILD_FULL,
    REBUILD_PREFIX_ONLY,
    REBUILD_TOPO_DELTA,
    SPF_SOLVE_DONE,
    ROUTE_UPDATE_SENT,
    FIB_PROGRAMMED,
)

# one trace never legitimately exceeds the full stage vocabulary by much
# (merges can duplicate early stages); cap so a pathological merge loop
# can't grow a trace without bound. Merges stop short of the cap so the
# downstream stage stamps always fit — a full trace evicts its
# second-oldest marker rather than dropping the new stamp, keeping both
# the origin timestamp and the completing FIB_PROGRAMMED marker.
MAX_EVENTS_PER_TRACE = 64
_MERGE_CAP = MAX_EVENTS_PER_TRACE - 8  # headroom for the stage vocabulary


@dataclass
class PerfEvent:
    """One stage marker (reference: PerfEvent † — eventDescr + unixTs;
    ts here is monotonic nanoseconds, which deltas need and wall time
    doesn't give)."""

    event: str
    ts_ns: int = 0
    node: str = ""


@dataclass
class PerfEvents:
    """Ordered marker list carried on queue payloads.

    reference: PerfEvents †. Markers are appended in stamp order;
    `deltas()` yields the per-stage breakdown operators read."""

    events: list[PerfEvent] = field(default_factory=list)

    @classmethod
    def start(cls, event: str, node: str = "") -> "PerfEvents":
        pe = cls()
        pe.add_perf_event(event, node=node)
        return pe

    def add_perf_event(
        self, event: str, node: str = "", ts_ns: int | None = None
    ) -> None:
        """Stamp one stage marker (reference: addPerfEvent †)."""
        if len(self.events) >= MAX_EVENTS_PER_TRACE:
            # evict the second-oldest, never the origin or the new stamp:
            # total_ms stays origin→newest and the trace still completes
            self.events.pop(1)
        self.events.append(
            PerfEvent(
                event=event,
                ts_ns=time.monotonic_ns() if ts_ns is None else ts_ns,
                node=node,
            )
        )

    def copy(self) -> "PerfEvents":
        """Independent snapshot. Every consumer that stamps a trace on
        its own schedule (local Decision/Fib vs the per-peer flood
        pump, one advertisement per area) must take its own copy —
        sharing the mutable list leaks one pipeline's markers into
        another's trace."""
        return PerfEvents(events=list(self.events))

    def merge(self, other: "PerfEvents") -> "PerfEvents":
        """Combine two traces (e.g. several coalesced neighbor events
        feeding one advertisement): union of markers, timestamp order.
        The merge of stable-sorted streams keeps stamp order for equal
        timestamps."""
        ev = sorted([*self.events, *other.events], key=lambda e: e.ts_ns)
        if len(ev) > _MERGE_CAP:
            # same invariant as add_perf_event's eviction: keep the
            # origin marker and the NEWEST stamps, drop the middle
            ev = [ev[0], *ev[-(_MERGE_CAP - 1):]]
        return PerfEvents(events=ev)

    def deltas(self) -> list[tuple[str, float]]:
        """Per-stage (event, ms-since-previous-marker); first stage is 0."""
        out: list[tuple[str, float]] = []
        prev: int | None = None
        for e in self.events:
            out.append(
                (e.event, 0.0 if prev is None else (e.ts_ns - prev) / 1e6)
            )
            prev = e.ts_ns
        return out

    def total_ms(self) -> float:
        if len(self.events) < 2:
            return 0.0
        return (self.events[-1].ts_ns - self.events[0].ts_ns) / 1e6

    def last_event(self) -> str:
        return self.events[-1].event if self.events else ""

    def to_jsonable(self) -> dict:
        """Operator-facing encoding used by get_perf_events."""
        return {
            "events": [
                {"event": e.event, "ts_ns": e.ts_ns, "node": e.node}
                for e in self.events
            ],
            "deltas_ms": [
                {"event": ev, "delta_ms": round(d, 3)}
                for ev, d in self.deltas()
            ],
            "total_ms": round(self.total_ms(), 3),
        }
