"""PerfEvents: per-update convergence tracing across the pipeline.

reference: openr/common/Types.thrift † PerfEvents / openr/common/Util.h †
addPerfEvent — the reference attaches an ordered (eventDescr, unixTs)
marker list to every update flowing spark → kvstore → decision → fib, and
`breeze perf` renders the per-stage deltas. That trace, not solver
throughput, is how operators measure convergence (also the metric DeltaPath
argues for, PAPERS.md 1808.06893). Here the record rides the existing
queue payloads (NeighborEvent → Publication → RouteUpdate) and completed
traces land in Monitor's perf ring.

Stage marker vocabulary (every name used by a stamp call MUST appear in
docs/Monitor.md — ci.sh lints this):

  NEIGHBOR_EVENT      Spark emitted a neighbor up/down/restart event
  ADJ_DB_UPDATED      LinkMonitor folded it into the adjacency set
  KVSTORE_FLOODED     KvStore accepted + published the adj/prefix update
  DECISION_RECEIVED   Decision buffered the publication
  DECISION_DEBOUNCED  the debounce window fired; rebuild started
  REBUILD_FULL        the rebuild took the from-scratch path (SPF solves)
  REBUILD_PREFIX_ONLY the rebuild took the dirty-scoped prefix-only path
                      (zero SPF solves — cached artifacts re-assembled)
  REBUILD_TOPO_DELTA  the rebuild warm-started from the cached solve
                      (bounded-region recompute; zero full area solves)
  SPF_SOLVE_DONE      SPF solve + RIB assembly + diff finished
  ROUTE_UPDATE_SENT   the route delta was pushed toward Fib
  FIB_PROGRAMMED      Fib programmed the delta into the dataplane

Timestamps are time.monotonic_ns(): exact for deltas within one
process (the emulator, and each real node's own pipeline), but NOT
comparable across hosts — a trace flooded over the TCP transport mixes
clock domains, so cross-host deltas are only indicative of ordering,
never of duration (the reference uses unix timestamps and accepts NTP
skew instead; we keep exact in-process deltas, the quantity the
benchmarks and the windowed convergence stat are built on).

Cross-node flood spans (docs/Monitor.md "Flood tracing"): a SAMPLED
origination (KvStore traces every Nth locally-originated publication,
seeded — KvStoreConfig.trace_sample_every) additionally carries a
:class:`HopSpan` chain — origin node + origination stamp, then one span
per flooding hop with rx / fan-out-enqueue / tx stamps. The fields ride
`PerfEvents` as APPENDED wire fields, so the PR 8 binary evolution rules
make them a zero-negotiation change: an old peer skips them, a new peer
defaults them. Every node on the flood path completes its own span at
FIB_PROGRAMMED; the collector (`monitor/flood_trace.py`,
`emulator/tracing.py`, `breeze perf waterfall`) reassembles the
completed spans cluster-wide into a propagation tree with a per-hop
named-stage waterfall.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

NEIGHBOR_EVENT = "NEIGHBOR_EVENT"
ADJ_DB_UPDATED = "ADJ_DB_UPDATED"
KVSTORE_FLOODED = "KVSTORE_FLOODED"
DECISION_RECEIVED = "DECISION_RECEIVED"
DECISION_DEBOUNCED = "DECISION_DEBOUNCED"
REBUILD_FULL = "REBUILD_FULL"
REBUILD_PREFIX_ONLY = "REBUILD_PREFIX_ONLY"
REBUILD_TOPO_DELTA = "REBUILD_TOPO_DELTA"
SPF_SOLVE_DONE = "SPF_SOLVE_DONE"
ROUTE_UPDATE_SENT = "ROUTE_UPDATE_SENT"
FIB_PROGRAMMED = "FIB_PROGRAMMED"

# canonical spark→fib stage order; doubles as the doc-lint source of
# truth. REBUILD_FULL / REBUILD_PREFIX_ONLY / REBUILD_TOPO_DELTA are
# alternatives at the same stage position — exactly one of them is
# stamped per rebuild, recording which pipeline the debounced batch took.
ALL_MARKERS = (
    NEIGHBOR_EVENT,
    ADJ_DB_UPDATED,
    KVSTORE_FLOODED,
    DECISION_RECEIVED,
    DECISION_DEBOUNCED,
    REBUILD_FULL,
    REBUILD_PREFIX_ONLY,
    REBUILD_TOPO_DELTA,
    SPF_SOLVE_DONE,
    ROUTE_UPDATE_SENT,
    FIB_PROGRAMMED,
)

# one trace never legitimately exceeds the full stage vocabulary by much
# (merges can duplicate early stages); cap so a pathological merge loop
# can't grow a trace without bound. Merges stop short of the cap so the
# downstream stage stamps always fit — a full trace evicts its
# second-oldest marker rather than dropping the new stamp, keeping both
# the origin timestamp and the completing FIB_PROGRAMMED marker.
MAX_EVENTS_PER_TRACE = 64
_MERGE_CAP = MAX_EVENTS_PER_TRACE - 8  # headroom for the stage vocabulary


@dataclass
class PerfEvent:
    """One stage marker (reference: PerfEvent † — eventDescr + unixTs;
    ts here is monotonic nanoseconds, which deltas need and wall time
    doesn't give)."""

    event: str
    ts_ns: int = 0
    node: str = ""


@dataclass
class HopSpan:
    """One flooding hop of a sampled cross-node trace.

    ``rx_ns`` is when this node received the flood (the origination
    stamp on hop 0); ``enq_ns`` when the node fanned the update out
    toward its peers (KvStore `_flood`); ``tx_ns`` when the wire frame
    was encoded/shipped (serialize-once encodes at fan-out time, so
    enq≈tx on the binary path — pump wait shows up in the next hop's
    wire stage). 0 = never stamped (e.g. a leaf with no onward peers).
    All stamps share the STAMPING node's monotonic clock; cross-node
    deltas are only exact when the nodes share a clock (in-process
    emulator — the regime the waterfall is built for)."""

    node: str = ""
    hop: int = 0
    rx_ns: int = 0
    enq_ns: int = 0
    tx_ns: int = 0


class FloodSpan:
    """Working (unpacked) form of the flood-span extension: trace
    identity + the HopSpan chain. On the wire this travels as ONE
    compact packed bytes field (`PerfEvents.span_bin`) — see the pack
    format below — because a generic per-field dataclass encoding of
    the chain measured ~3x the whole publication's wire-seam cost,
    which would defeat the "tracing stays affordable" sampling story."""

    __slots__ = ("trace_id", "origin", "origin_ts_ns", "hops")

    def __init__(
        self,
        trace_id: int = 0,
        origin: str = "",
        origin_ts_ns: int = 0,
        hops: list[HopSpan] | None = None,
    ):
        self.trace_id = trace_id
        self.origin = origin
        self.origin_ts_ns = origin_ts_ns
        self.hops = hops if hops is not None else []


# ---- packed span codec -------------------------------------------------
#
#   [ver=0x01]
#   uvarint trace_id
#   uvarint len(origin) + utf8
#   uvarint origin_ts_ns
#   uvarint nhops, then per hop (hop index = position):
#     uvarint len(node) + utf8
#     zigzag(rx - prev_rx)            prev_rx = origin_ts for hop 0
#     uvarint enq_code                0 = unset, else zigzag(enq-rx)+1
#     uvarint tx_code                 0 = unset, else zigzag(tx-enq|rx)+1
#
# Same-clock stamps make the deltas small (1-4 byte varints); zigzag
# keeps cross-clock-domain (multi-host) spans decodable, just fat.
# An unknown version byte decodes as "no span" — the extension is
# observability, never worth a frame rejection.

_SPAN_VER = 0x01


def _w_uv(out: bytearray, n: int) -> None:
    while n > 0x7F:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)


def _r_uv(buf: bytes, pos: int) -> tuple[int, int]:
    n = 0
    shift = 0
    while True:
        b = buf[pos]  # IndexError on truncation → caller drops the span
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 77:
            raise ValueError("span varint too long")


def _zz(v: int) -> int:
    return (v << 1) if v >= 0 else ((-v << 1) - 1)


def _unzz(u: int) -> int:
    return (u >> 1) if not u & 1 else -((u + 1) >> 1)


def pack_span(span: FloodSpan) -> bytes:
    out = bytearray((_SPAN_VER,))
    _w_uv(out, span.trace_id)
    ob = span.origin.encode()
    _w_uv(out, len(ob))
    out += ob
    _w_uv(out, span.origin_ts_ns)
    _w_uv(out, len(span.hops))
    prev_rx = span.origin_ts_ns
    for h in span.hops:
        nb = h.node.encode()
        _w_uv(out, len(nb))
        out += nb
        _w_uv(out, _zz(h.rx_ns - prev_rx))
        prev_rx = h.rx_ns
        _w_uv(out, _zz(h.enq_ns - h.rx_ns) + 1 if h.enq_ns else 0)
        base = h.enq_ns or h.rx_ns
        _w_uv(out, _zz(h.tx_ns - base) + 1 if h.tx_ns else 0)
    return bytes(out)


def unpack_span(blob: bytes) -> FloodSpan | None:
    """None on empty/unknown-version/corrupt input — a span is
    best-effort observability, never a decode failure."""
    if not blob or blob[0] != _SPAN_VER:
        return None
    try:
        pos = 1
        trace_id, pos = _r_uv(blob, pos)
        n, pos = _r_uv(blob, pos)
        origin = blob[pos : pos + n].decode()
        pos += n
        origin_ts, pos = _r_uv(blob, pos)
        nhops, pos = _r_uv(blob, pos)
        if nhops > len(blob):  # corrupt count guard
            return None
        hops: list[HopSpan] = []
        prev_rx = origin_ts
        for i in range(nhops):
            n, pos = _r_uv(blob, pos)
            node = blob[pos : pos + n].decode()
            pos += n
            d, pos = _r_uv(blob, pos)
            rx = prev_rx + _unzz(d)
            prev_rx = rx
            ec, pos = _r_uv(blob, pos)
            enq = rx + _unzz(ec - 1) if ec else 0
            tc, pos = _r_uv(blob, pos)
            tx = ((enq or rx) + _unzz(tc - 1)) if tc else 0
            hops.append(HopSpan(node, i, rx, enq, tx))
        return FloodSpan(trace_id, origin, origin_ts, hops)
    except (IndexError, ValueError, UnicodeDecodeError):
        return None


def _cap_events(ev: list[PerfEvent], cap: int) -> list[PerfEvent]:
    """Trim a marker list to ~`cap` keeping (a) the origin, (b) the
    newest stamps, and (c) at least ONE stamp per node — the per-hop
    keep-one guard: a sampled multi-hop trace whose interior nodes only
    contributed one marker each must not lose them to the eviction
    policy, or the waterfall silently drops interior hops. May exceed
    `cap` by the number of distinct nodes outside the kept tail — i.e.
    bounded by the flood path length, which is exactly the information
    being preserved."""
    if len(ev) <= cap:
        return list(ev)
    keep: set[int] = {0, len(ev) - 1}
    seen: set[str] = set()
    for i, e in enumerate(ev):  # earliest marker of each node (its rx-ish)
        if e.node not in seen:
            seen.add(e.node)
            keep.add(i)
    i = len(ev) - 1
    while len(keep) < cap and i >= 0:
        keep.add(i)
        i -= 1
    return [ev[i] for i in sorted(keep)]


@dataclass
class PerfEvents:
    """Ordered marker list carried on queue payloads.

    reference: PerfEvents †. Markers are appended in stamp order;
    `deltas()` yields the per-stage breakdown operators read.

    ``span_bin`` is the cross-node flood-span extension (module
    docstring): ONE appended wire field with a default, so both codecs
    evolve without negotiation, packed compactly (pack_span) because it
    rides every traced flood frame. The unpacked working copy is the
    transient ``_span`` (lazy; every mutation re-packs, so ``span_bin``
    is always wire-current). ``trace_id == 0`` means "not a sampled
    flood trace" — the hop stamp calls are no-ops then."""

    events: list[PerfEvent] = field(default_factory=list)
    # packed flood-span extension (appended wire field; see pack_span)
    span_bin: bytes | None = None
    # unpacked span (transient — never on the wire; serde skips _fields)
    _span: FloodSpan | None = field(default=None, compare=False, repr=False)

    @classmethod
    def start(cls, event: str, node: str = "") -> "PerfEvents":
        pe = cls()
        pe.add_perf_event(event, node=node)
        return pe

    def add_perf_event(
        self, event: str, node: str = "", ts_ns: int | None = None
    ) -> None:
        """Stamp one stage marker (reference: addPerfEvent †)."""
        if len(self.events) >= MAX_EVENTS_PER_TRACE:
            self._evict_one()
        self.events.append(
            PerfEvent(
                event=event,
                ts_ns=time.monotonic_ns() if ts_ns is None else ts_ns,
                node=node,
            )
        )

    def _evict_one(self) -> None:
        """Evict one middle marker: never the origin, never the newest,
        and never a node's LAST remaining stamp (the per-hop keep-one
        guard — interior flood hops often hold exactly one marker, and
        losing it silently drops that hop from the waterfall). Falls
        back to the second-oldest when every node is down to one."""
        counts: dict[str, int] = {}
        for e in self.events:
            counts[e.node] = counts.get(e.node, 0) + 1
        for i in range(1, len(self.events) - 1):
            if counts[self.events[i].node] > 1:
                self.events.pop(i)
                return
        self.events.pop(1)

    # ------------------------------------------------- flood hop spans

    def _get_span(self) -> FloodSpan | None:
        """Lazy unpack of the wire extension (decode cost is paid only
        by code that actually reads the span, not by every flood)."""
        if self._span is None and self.span_bin:
            self._span = unpack_span(self.span_bin)
        return self._span

    @property
    def trace_id(self) -> int:
        s = self._get_span()
        return s.trace_id if s is not None else 0

    @property
    def origin(self) -> str:
        s = self._get_span()
        return s.origin if s is not None else ""

    @property
    def origin_ts_ns(self) -> int:
        s = self._get_span()
        return s.origin_ts_ns if s is not None else 0

    @property
    def hops(self) -> list[HopSpan]:
        s = self._get_span()
        return s.hops if s is not None else []

    def begin_flood_trace(
        self, node: str, trace_id: int, ts_ns: int | None = None
    ) -> None:
        """Mark this trace as a sampled flood trace originating HERE:
        hop 0's rx stamp is the origination time (KvStore stamps this
        on every Nth accepted local origination)."""
        ts = time.monotonic_ns() if ts_ns is None else ts_ns
        self._span = FloodSpan(
            trace_id=trace_id,
            origin=node,
            origin_ts_ns=ts,
            hops=[HopSpan(node=node, hop=0, rx_ns=ts)],
        )
        self.span_bin = pack_span(self._span)

    def stamp_hop_rx(self, node: str, ts_ns: int | None = None) -> bool:
        """Append this node's hop span on flood receive. No-op (False)
        when untraced or when the node already holds a span (duplicate
        delivery suppressed by the flood loop guard upstream, but a
        merge can re-route one)."""
        s = self._get_span()
        if s is None or not s.trace_id:
            return False
        if any(h.node == node for h in s.hops):
            return False
        s.hops.append(
            HopSpan(
                node=node,
                hop=len(s.hops),
                rx_ns=time.monotonic_ns() if ts_ns is None else ts_ns,
            )
        )
        self.span_bin = pack_span(s)
        return True

    def stamp_hop_fanout(self, node: str, ts_ns: int | None = None) -> None:
        """Stamp this node's span at fan-out time (enqueue toward peers
        + encode): called by KvStore `_flood` BEFORE the serialize-once
        encode, so the stamps freeze into the shared wire frame.
        WRITE-ONCE: a later re-flood touching the same trace (e.g. a
        version-refresh of an already-fanned key) must not move the
        stamps — the frame that actually propagated carried the first
        ones, and a late re-stamp fabricates a giant enq→tx delta in
        the local completion that the shipped frames never saw."""
        s = self._get_span()
        if s is None or not s.trace_id:
            return
        for h in reversed(s.hops):
            if h.node == node:
                if h.tx_ns:
                    return
                t = time.monotonic_ns() if ts_ns is None else ts_ns
                if h.enq_ns == 0:
                    h.enq_ns = t
                h.tx_ns = t
                self.span_bin = pack_span(s)
                return

    def copy(self) -> "PerfEvents":
        """Independent snapshot. Every consumer that stamps a trace on
        its own schedule (local Decision/Fib vs the per-peer flood
        pump, one advertisement per area) must take its own copy —
        sharing the mutable list leaks one pipeline's markers into
        another's trace. The packed span bytes are immutable (every
        stamp re-packs a fresh blob), so carrying them is safe; the
        unpacked working copy stays lazy."""
        return PerfEvents(events=list(self.events), span_bin=self.span_bin)

    # wire-lean marker budget for span-carrying traces: the origin's
    # own pipeline markers are ≤ ~5 (NEIGHBOR_EVENT → KVSTORE_FLOODED)
    _LEAN_EVENT_CAP = 8

    def wire_lean(self) -> "PerfEvents":
        """Wire-bound slimming of a SPAN-carrying trace: keep only the
        origin node's markers. The hop span subsumes per-hop markers,
        but the per-peer flood coalescing merge unions every batched
        trace's events — so one sampled publication taints whole
        coalesced batches, and a deep relay ships toward _MERGE_CAP
        PerfEvent dataclasses on EVERY frame (measured 3x wire-seam
        cost at 64 nodes before this). Untraced traces pass through
        unchanged — legacy multi-origin ring traces keep their union.
        Receivers lose the merged-in FOREIGN markers; their own local
        stamps (the waterfall's terminal chain) land after receive as
        always."""
        s = self._get_span()
        if s is None:
            return self
        ev = [e for e in self.events if e.node == s.origin]
        if len(ev) == len(self.events) <= self._LEAN_EVENT_CAP:
            return self
        if len(ev) > self._LEAN_EVENT_CAP:
            # same invariant as every other trim here: keep the FIRST
            # stamp (the origin anchor) and the NEWEST stamps — the
            # most recent origin stage must survive, not the middle
            ev = [ev[0], *ev[-(self._LEAN_EVENT_CAP - 1):]]
        return PerfEvents(events=ev, span_bin=self.span_bin)

    def merge(self, other: "PerfEvents") -> "PerfEvents":
        """Combine two traces (e.g. several coalesced neighbor events
        feeding one advertisement): union of markers, timestamp order.
        The merge of stable-sorted streams keeps stamp order for equal
        timestamps.

        Flood-span identity: the merged trace keeps self's span when
        self carries one, else other's. Two DISTINCT sampled traces
        coalescing keep only the first chain — splicing two unrelated
        hop chains would fabricate a propagation path; the collector
        sees one coherent (if partial) trace instead. The packed blobs
        compare cheaply, so no unpack happens here."""
        ev = sorted([*self.events, *other.events], key=lambda e: e.ts_ns)
        if len(ev) > _MERGE_CAP:
            ev = _cap_events(ev, _MERGE_CAP)
        return PerfEvents(
            events=ev, span_bin=self.span_bin or other.span_bin
        )

    def deltas(self) -> list[tuple[str, float]]:
        """Per-stage (event, ms-since-previous-marker); first stage is 0."""
        out: list[tuple[str, float]] = []
        prev: int | None = None
        for e in self.events:
            out.append(
                (e.event, 0.0 if prev is None else (e.ts_ns - prev) / 1e6)
            )
            prev = e.ts_ns
        return out

    def total_ms(self) -> float:
        if len(self.events) < 2:
            return 0.0
        return (self.events[-1].ts_ns - self.events[0].ts_ns) / 1e6

    def last_event(self) -> str:
        return self.events[-1].event if self.events else ""

    def to_jsonable(self) -> dict:
        """Operator-facing encoding used by get_perf_events."""
        out = {
            "events": [
                {"event": e.event, "ts_ns": e.ts_ns, "node": e.node}
                for e in self.events
            ],
            "deltas_ms": [
                {"event": ev, "delta_ms": round(d, 3)}
                for ev, d in self.deltas()
            ],
            "total_ms": round(self.total_ms(), 3),
        }
        if self.trace_id:
            out["trace_id"] = self.trace_id
            out["origin"] = self.origin
            out["origin_ts_ns"] = self.origin_ts_ns
            out["hops"] = [
                {
                    "node": h.node,
                    "hop": h.hop,
                    "rx_ns": h.rx_ns,
                    "enq_ns": h.enq_ns,
                    "tx_ns": h.tx_ns,
                }
                for h in self.hops
            ]
        return out
