"""Cross-node flood-trace assembly: waterfalls, attribution, trees.

Pure functions over the *jsonable* trace dicts `PerfEvents.to_jsonable`
emits for sampled flood traces (``trace_id`` set, ``hops`` chain) — no
emulator or jax imports, so both the ctrl server and the breeze CLI can
use them directly. The emulator-side collector that walks a live
Cluster's Monitor rings is ``openr_tpu/emulator/tracing.py``.

A completed span (one node's FIB_PROGRAMMED of a sampled flood) is
attributed to NAMED stages along its whole path:

  per relay hop i:   kvstore_process  rx → fan-out enqueue (decode,
                                      store merge, local publish)
                     flood_encode     enqueue → wire encode (tx stamp)
                     wire             tx(i) → rx(i+1): socket + the
                                      sender's flood-pump wait
  terminal node:     decision_queue   rx → DECISION_RECEIVED
                     decision_debounce  → DECISION_DEBOUNCED
                     spf_solve          → SPF_SOLVE_DONE (incl. the
                                          REBUILD_* path marker)
                     route_dispatch     → ROUTE_UPDATE_SENT
                     fib_program        → FIB_PROGRAMMED

The stages telescope — consecutive deltas over one checkpoint chain —
so a clean trace's stage sum equals its end-to-end total exactly
(``coverage`` ≈ 1.0). Missing stamps or non-monotonic checkpoints
(clock-domain mixes on real multi-host deployments) leave gaps, and
coverage reports honestly how much of the total was attributed.
"""

from __future__ import annotations

from openr_tpu.monitor import perf
from openr_tpu.monitor.fleet import percentile as _percentile

#: canonical stage order (rendering + attribution tables)
STAGES: tuple[str, ...] = (
    "kvstore_process",
    "flood_encode",
    "wire",
    "decision_queue",
    "decision_debounce",
    "spf_solve",
    "route_dispatch",
    "fib_program",
)

_TERMINAL_CHAIN: tuple[tuple[str, str], ...] = (
    (perf.DECISION_RECEIVED, "decision_queue"),
    (perf.DECISION_DEBOUNCED, "decision_debounce"),
    (perf.SPF_SOLVE_DONE, "spf_solve"),
    (perf.ROUTE_UPDATE_SENT, "route_dispatch"),
    (perf.FIB_PROGRAMMED, "fib_program"),
)


def is_flood_trace(tr: dict) -> bool:
    return bool(tr.get("trace_id")) and bool(tr.get("hops"))


def waterfall(tr: dict) -> dict | None:
    """Per-hop named-stage breakdown of one completed span (jsonable
    trace dict). Returns None for untraced/uncompleted records.

    Output: ``{"trace_id", "origin", "terminal", "hops", "total_ms",
    "stages": [{"stage", "node", "ms"}...], "attributed_ms",
    "coverage"}`` — stages in checkpoint order, coverage =
    attributed/total."""
    if not is_flood_trace(tr):
        return None
    hops = sorted(tr["hops"], key=lambda h: h.get("hop", 0))
    events = tr.get("events") or []
    origin_ts = tr.get("origin_ts_ns") or hops[0].get("rx_ns", 0)
    term = hops[-1].get("node", "")
    fib_ts = next(
        (
            e["ts_ns"]
            for e in reversed(events)
            if e.get("event") == perf.FIB_PROGRAMMED
            and e.get("node") == term
        ),
        0,
    )
    if not origin_ts or not fib_ts or fib_ts < origin_ts:
        return None
    total_ms = (fib_ts - origin_ts) / 1e6
    stages: list[dict] = []
    cur = origin_ts

    def emit(stage: str, node: str, ts: int) -> None:
        nonlocal cur
        # missing stamp (0) or a backward checkpoint → skip: the gap
        # stays unattributed and shows up as coverage < 1
        if ts and ts >= cur:
            stages.append(
                {"stage": stage, "node": node, "ms": (ts - cur) / 1e6}
            )
            cur = ts

    for i, h in enumerate(hops):
        node = h.get("node", "")
        if i > 0:
            emit("wire", node, h.get("rx_ns", 0))
        if i < len(hops) - 1:
            emit("kvstore_process", node, h.get("enq_ns", 0))
            emit("flood_encode", node, h.get("tx_ns", 0))
    # terminal decision chain: first marker of each stage stamped by the
    # terminal node at/after the current checkpoint (merged traces can
    # carry repeats; monotonicity picks the right one). The terminal's
    # own fan-out stamps are skipped — that branch runs in parallel with
    # the decision path and would double-book the timeline.
    for marker, stage in _TERMINAL_CHAIN:
        ts = next(
            (
                e["ts_ns"]
                for e in events
                if e.get("event") == marker
                and e.get("node") == term
                and e["ts_ns"] >= cur
            ),
            0,
        )
        emit(stage, term, ts)
    attributed = sum(s["ms"] for s in stages)
    return {
        "trace_id": tr["trace_id"],
        "origin": tr.get("origin", ""),
        "terminal": term,
        "hops": len(hops) - 1,  # edges traversed, 0 = origin-local span
        "total_ms": round(total_ms, 3),
        "stages": [
            {**s, "ms": round(s["ms"], 3)} for s in stages
        ],
        "attributed_ms": round(attributed, 3),
        "coverage": round(attributed / total_ms, 4) if total_ms > 0 else 0.0,
    }


def attribution(traces: list[dict]) -> dict:
    """Cross-trace per-stage p50 breakdown — the `convergence_attribution`
    benchmarks report next to `convergence_p50_ms`. Stage deltas are
    summed per trace first (a 5-hop trace has 5 wire segments), then
    the p50 is taken across traces per stage."""
    falls = [w for w in (waterfall(t) for t in traces) if w is not None]
    if not falls:
        return {"traces": 0, "stages_p50_ms": {}, "coverage_p50": None}
    per_stage: dict[str, list[float]] = {}
    for w in falls:
        sums: dict[str, float] = {}
        for s in w["stages"]:
            sums[s["stage"]] = sums.get(s["stage"], 0.0) + s["ms"]
        for stage, ms in sums.items():
            per_stage.setdefault(stage, []).append(ms)
    return {
        "traces": len(falls),
        "max_hops": max(w["hops"] for w in falls),
        "total_p50_ms": round(
            _percentile([w["total_ms"] for w in falls], 0.5), 3
        ),
        "stages_p50_ms": {
            stage: round(_percentile(per_stage[stage], 0.5), 3)
            for stage in STAGES
            if stage in per_stage
        },
        "coverage_p50": round(
            _percentile([w["coverage"] for w in falls], 0.5), 4
        ),
    }


def propagation_tree(traces: list[dict]) -> dict:
    """Assemble cluster-wide completions into per-trace propagation
    trees: each completed span contributes its path's parent→child
    edges (the union over spans is the flood tree as actually walked).

    Returns ``{trace_id: {"origin", "nodes", "edges", "max_hops",
    "completions"}}`` with edges sorted for stable rendering."""
    out: dict[int, dict] = {}
    for tr in traces:
        if not is_flood_trace(tr):
            continue
        hops = sorted(tr["hops"], key=lambda h: h.get("hop", 0))
        entry = out.setdefault(
            tr["trace_id"],
            {
                "origin": tr.get("origin", ""),
                "nodes": set(),
                "edges": set(),
                "max_hops": 0,
                "completions": 0,
            },
        )
        entry["completions"] += 1
        entry["max_hops"] = max(entry["max_hops"], len(hops) - 1)
        prev = None
        for h in hops:
            node = h.get("node", "")
            entry["nodes"].add(node)
            if prev is not None:
                entry["edges"].add((prev, node))
            prev = node
    for entry in out.values():
        entry["nodes"] = sorted(entry["nodes"])
        entry["edges"] = sorted(entry["edges"])
    return out
