"""Central registry of counter / gauge / stat / marker names.

Every name a module stamps into its :class:`Counters` registry — and
every perf-trace stage marker — is declared here, so the observable
surface of the system is one reviewable module instead of string
literals scattered across the tree. ``tools/orlint`` rule **OR007**
enforces it both ways:

  * every literal (or f-string template) passed to
    ``Counters.increment/set/add_value/touch`` or
    ``PerfEvents.start/add_perf_event`` anywhere in ``openr_tpu`` must
    resolve against this registry;
  * every name in :data:`DOCUMENTED` (and every template's
    :data:`TEMPLATES` doc-form, and every marker) must appear in
    ``docs/Monitor.md`` — this subsumes the three bash-heredoc doc
    lints ci.sh used to carry.

Adding a counter: add the literal to :data:`COUNTERS` (or a template to
:data:`TEMPLATES` when the name embeds a runtime key), and — for the
operator-facing families — a row to docs/Monitor.md. docs/Linting.md
covers the policy.
"""

from __future__ import annotations

from openr_tpu.monitor import perf

# --------------------------------------------------------------- markers

#: perf-trace stage marker vocabulary (each must appear in
#: docs/Monitor.md; stamp sites may only use these).
MARKERS: tuple[str, ...] = perf.ALL_MARKERS

#: non-marker public attributes of monitor.perf that `perf.<NAME>`
#: references may legitimately touch (the OR007 attr check's allowlist).
PERF_MODULE_EXPORTS: frozenset[str] = frozenset(
    {"ALL_MARKERS", "MAX_EVENTS_PER_TRACE"}
)

# -------------------------------------------------------------- counters

#: exact counter / gauge / stat names (literal emit sites).
COUNTERS: frozenset[str] = frozenset(
    {
        # decision
        "decision.lsdb_changes",
        "decision.rebuild.full",
        "decision.rebuild.prefix_only",
        "decision.rebuild.topo_delta",
        "decision.rebuild.cached_areas",
        "decision.rebuild.area_solves",
        # merge-book fallback matrix (docs/Decision.md): scoped = the
        # delta fold patched the persistent merged RIB in place; full =
        # a first-build/policy/mismatch round re-armed it from scratch
        "decision.merge.scoped",
        "decision.merge.full",
        "decision.rebuild_ms",
        "decision.spf.solves",
        "decision.spf.warm_starts",
        "decision.spf.warm_fallbacks",
        "decision.spf.warm_region_nodes",
        "decision.spf_ms",
        "decision.spf_runs",
        "decision.spf_solve_ms",
        # decision: nexthop-group intern table size (gauge)
        "decision.nexthop_groups",
        # fib
        "fib.perf_traces_completed",
        "fib.program_ok",
        "fib.program_fail",
        "fib.program_fail_streak",
        "fib.program_ms",
        # delta-native programming (docs/Fib.md): batched chunk calls,
        # per-chunk size stat, routes written, delta-book scan size
        "fib.program_batches",
        "fib.program_batch_size",
        "fib.program_scan_routes",
        "fib.routes_programmed",
        "fib.warm_boot_reprogrammed",
        "fib.warm_boot_routes",
        # kvstore
        "kvstore.expired_keys",
        "kvstore.flood_backpressure_drops",
        "kvstore.flood_bytes",
        "kvstore.flood_decode_ms",
        "kvstore.flood_encode_ms",
        "kvstore.flood_encodes",
        "kvstore.flood_failures",
        "kvstore.flood_fanout_ms",
        # cross-node flood tracing (docs/Monitor.md "Flood tracing"):
        # sampled originations, relayed hop-span stamps, span wire bytes
        "kvstore.flood_traces_sampled",
        "kvstore.flood_hops",
        "kvstore.flood_span_bytes",
        "kvstore.flood_keys_coalesced",
        "kvstore.flood_root_missing",
        "kvstore.floods_held",
        "kvstore.floods_rate_limited",
        "kvstore.floods_received",
        "kvstore.floods_sent",
        "kvstore.full_sync_failures",
        "kvstore.full_sync_keys_sent",
        "kvstore.full_sync_probe_miss",
        "kvstore.full_syncs",
        "kvstore.full_syncs_legacy",
        "kvstore.full_syncs_noop",
        "kvstore.full_syncs_noop_served",
        "kvstore.full_syncs_served",
        "kvstore.merged_updates",
        "kvstore.peer_disconnects",
        "kvstore.peer_reconnects",
        "kvstore.peers_added",
        "kvstore.peers_rejected_bad_area",
        "kvstore.peers_removed",
        "kvclient.advertisements",
        # rpc wire accounting (rpc/core.py; every RpcServer/RpcClient
        # with a Counters registry stamps these)
        "rpc.bytes_rx",
        "rpc.bytes_tx",
        "rpc.conns_binary",
        # spark / linkmonitor
        "spark.bad_packets",
        "spark.handshake_recv",
        "spark.handshake_sent",
        "spark.heartbeat_sent",
        "spark.hello_recv",
        "spark.hello_sent",
        "spark.chaos_dropped",
        "spark.inbox_dropped",
        "spark.neighbor_down",
        "spark.neighbor_up",
        "spark.nongr_restarts_detected",
        "spark.restart_announced",
        "linkmonitor.adj_advertised",
        "linkmonitor.flap_damped",
        "linkmonitor.neighbor_down",
        "linkmonitor.neighbor_up",
        # ctrl / watchdog / monitor
        "ctrl.sub_evictions",
        "watchdog.aborts",
        "watchdog.scans",
        "watchdog.stalls",
        "monitor.convergence_ms",
        "monitor.flood_traces",
        "monitor.log_samples",
        "monitor.perf_traces",
        "monitor.perf_traces_multi_origin",
        # persist plane (persist/plane.py; docs/Persist.md): journal
        # append/compaction accounting + recovery footprint from boot
        "persist.appends",
        "persist.append_errors",
        "persist.journal_bytes",
        "persist.journal_records",
        "persist.fsyncs",
        "persist.compactions",
        "persist.compact_errors",
        "persist.recovered_records",
        "persist.truncated_bytes",
        # wire/persist schema lock (types/wirelock.py; docs/Wire.md
        # "Schema evolution"): the lock_version this node was built
        # against, stamped as a gauge at Node construction — fleet
        # monitoring catches version skew before it mis-decodes
        "wire.schema_lock_version",
        # everything else
        "configstore.corrupt",
        "configstore.stores",
        "nlifaces.events",
        "platform.errors",
        "prefix_allocator.allocations",
        "prefixmgr.advertised",
        "prefixmgr.events",
        "prefixmgr.policy_denied",
        "prefixmgr.range_chunks",
        "prefixmgr.range_prefixes",
        "prefixmgr.redistributed",
        # entry-book footprint gauge at the advertisement-sync edge —
        # a leak detector for the delta redistribution books
        "prefixmgr.redistribute.book_size",
        # common/tasks guard_task default
        "task.uncaught_exceptions",
        # jax compile ledger (monitor/compile_ledger.py; process-wide)
        "jax.compiles.total",
        "jax.transfers.host_reads",
        "jax.transfers.host_bytes",
    }
)

#: f-string templates (``*`` = runtime-interpolated segment), mapped to
#: the doc-form docs/Monitor.md uses when the family is documented
#: (None = internal family, registry membership only).
TEMPLATES: dict[str, str | None] = {
    # messaging queue gauge/counter fields — one row per field in
    # docs/Monitor.md (the queue name is free)
    "queue.*.depth": "queue.<name>.depth",
    "queue.*.highwater": "queue.<name>.highwater",
    "queue.*.blocked": "queue.<name>.blocked",
    "queue.*.coalesced": "queue.<name>.coalesced",
    "queue.*.shed": "queue.<name>.shed",
    "queue.*.overflow": "queue.<name>.overflow",
    # module-keyed lifecycle counters (OpenrModule)
    "*.fiber_crashes": None,
    "*.timer_errors": None,
    "*.task_exceptions": None,
    "*.subscribers": None,
    # decision engine substructure
    "decision.decode.*": None,
    "decision.dev_cache.*": None,
    "decision.elect.*": None,
    "decision.spf.*": None,
    # per-jitted-function compile counts (monitor/compile_ledger.py) —
    # the fn segment is the jit wrapper's name
    "jax.compiles.*": "jax.compiles.<fn>",
    # steady-state work ledger (monitor/work_ledger.py): per-pipeline-
    # stage entities-touched / delta-size / proportionality-ratio
    # gauges; the stage segment is a work_ledger.STAGES name. `.ratio`
    # is a ratio-type gauge — fleet aggregation must never sum it
    # (monitor/fleet.py).
    "work.*.touched": "work.<stage>.touched",
    "work.*.delta": "work.<stage>.delta",
    "work.*.ratio": "work.<stage>.ratio",
    # kernel cost ledger (monitor/device.py): XLA cost/memory analysis
    # of each canonical jitted entry point, exported per (fn, field)
    "jax.kernel.*.*": "jax.kernel.<fn>.<field>",
    # per-device HBM gauges (monitor/device.py sample_hbm; absent on
    # backends whose memory_stats() returns None — the CPU degradation)
    "device.*.hbm_bytes_in_use": "device.<i>.hbm_bytes_in_use",
    "device.*.hbm_peak_bytes": "device.<i>.hbm_peak_bytes",
    "device.*.hbm_limit_bytes": "device.<i>.hbm_limit_bytes",
    # annotated profiling spans' wall durations (monitor/profiling.py
    # annotate(counters=...)) — the span segment is the annotation name
    "profile.*_ms": "profile.<span>_ms",
    # platform error taxonomy
    "platform.*": None,
}

#: the queue counter FIELD vocabulary the messaging seams may emit —
#: OR007 statically cross-checks messaging/__init__.py's emit sites
#: against this set (the old ci.sh heredoc #4, now AST-based).
QUEUE_FIELDS: frozenset[str] = frozenset(
    {"depth", "highwater", "blocked", "coalesced", "shed", "overflow"}
)

#: names whose presence in docs/Monitor.md is REQUIRED (the
#: operator-facing families the retired ci.sh heredocs covered; the
#: rest of COUNTERS follows Monitor.md's generic `<module>.<what>`
#: convention and only needs registry membership).
DOCUMENTED: frozenset[str] = frozenset(
    {n for n in COUNTERS if n.startswith("decision.rebuild.")}
    | {n for n in COUNTERS if n.startswith("decision.merge.")}
    | {n for n in COUNTERS if n.startswith("decision.spf.warm_")}
    | {n for n in COUNTERS if n.startswith("prefixmgr.redistribute.")}
    | {n for n in COUNTERS if n.startswith("kvstore.flood")}
    | {n for n in COUNTERS if n.startswith("kvstore.full_sync")}
    | {n for n in COUNTERS if n.startswith("rpc.")}
    | {n for n in COUNTERS if n.startswith("fib.program")}
    | {n for n in COUNTERS if n.startswith("ctrl.sub_")}
    | {n for n in COUNTERS if n.startswith("watchdog.")}
    | {n for n in COUNTERS if n.startswith("spark.inbox_")}
    | {n for n in COUNTERS if n.startswith("jax.")}
    | {n for n in COUNTERS if n.startswith("persist.")}
    | {n for n in COUNTERS if n.startswith("wire.")}
)

#: source files exempt from the per-callsite check: the registry's own
#: mechanics (Counters expands `<stat>.sum` etc. dynamically) and the
#: messaging seams (covered by the dedicated QUEUE_FIELDS cross-check).
CALLSITE_EXEMPT: tuple[str, ...] = (
    "openr_tpu/monitor/counters.py",
    "openr_tpu/monitor/names.py",
    "openr_tpu/messaging/__init__.py",
)


def is_registered(name_or_template: str) -> bool:
    """True when a literal name or normalized f-string template resolves
    against the registry (exact counter, exact template, or a literal
    matching one template)."""
    import fnmatch

    if name_or_template in COUNTERS or name_or_template in TEMPLATES:
        return True
    if "*" in name_or_template:
        return False
    return any(
        fnmatch.fnmatchcase(name_or_template, t) for t in TEMPLATES
    )
