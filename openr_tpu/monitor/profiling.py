"""Kernel tracing surface (SURVEY §5.1: "JAX profiler + xprof traces
for the SPF kernel, plus the same counter surface").

Wraps jax.profiler so the rest of the framework never imports jax for
observability alone, and so tracing degrades to a no-op on hosts where
the backend is unavailable (the axon tunnel can be down while the CPU
control plane keeps running).

Usage:
  with profiling.trace("/tmp/spf_trace"):      # xprof trace directory
      solver.compute_routes(...)
  with profiling.annotate("spf:solve"):        # named span inside it
      ...
  with profiling.annotate("spf:solve", counters=node_counters):
      ...  # ALSO records wall ms into the `profile.spf:solve_ms` stat

bench.py honors OPENR_BENCH_TRACE=<dir> and wraps its timed iterations;
TpuSpfSolver annotates solve/assembly phases so the xprof timeline
separates device solve time from host RIB assembly.

With a :class:`Counters` registry passed, every annotated span ALSO
records its wall duration into the windowed ``profile.<span>_ms``
histogram stat — so solver phase timings land on the same Prometheus
surface (and `breeze monitor fleet` distributions) as every other
latency in the system, whether or not an xprof session is active
(docs/Monitor.md).
"""

from __future__ import annotations

import contextlib
import logging
import time

log = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(trace_dir: str | None):
    """jax.profiler.trace(trace_dir), or a no-op when dir is falsy or
    the profiler is unavailable/fails to start (unwritable directory,
    session already active, ...)."""
    if not trace_dir:
        yield
        return
    cm = None
    try:
        import jax

        cm = jax.profiler.trace(trace_dir)
        cm.__enter__()  # start_trace runs HERE — keep it under the guard
    except Exception:  # noqa: BLE001 — profiling must never break prod
        log.warning("jax profiler unavailable; tracing disabled")
        yield
        return
    try:
        yield
    finally:
        try:
            cm.__exit__(None, None, None)
        except Exception:  # noqa: BLE001 — export failure (bad dir, ...)
            log.warning("jax profiler trace export failed", exc_info=True)


def annotate(name: str, counters=None):
    """Named trace span (xprof timeline row); no-op without jax. With
    `counters`, the span's wall duration is additionally recorded into
    the ``profile.<name>_ms`` Counters histogram — device-side phase
    closure onto the common metric surface."""
    inner = _raw_annotation(name)
    if counters is None:
        return inner
    return _TimedSpan(name, counters, inner)


def _raw_annotation(name: str):
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001
        return contextlib.nullcontext()


class _TimedSpan:
    """Context manager wrapping the (possibly no-op) jax annotation with
    a wall-clock timer recorded into Counters on exit. Nested spans each
    record their own duration (the outer includes the inner, as xprof
    timelines do). Re-entrant only via fresh instances — annotate()
    returns a new one per call."""

    __slots__ = ("name", "counters", "inner", "_t0")

    def __init__(self, name: str, counters, inner):
        self.name = name
        self.counters = counters
        self.inner = inner
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        try:
            self.inner.__enter__()
        except Exception:  # noqa: BLE001 — profiling must never break prod
            self.inner = contextlib.nullcontext()
            self.inner.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        try:
            self.inner.__exit__(exc_type, exc, tb)
        except Exception:  # noqa: BLE001
            log.warning("trace annotation exit failed", exc_info=True)
        self.counters.add_value(
            f"profile.{self.name}_ms",
            (time.perf_counter() - self._t0) * 1e3,
        )
        # annotate-boundary HBM sample (docs/Monitor.md "Device
        # telemetry"): on backends with memory_stats this stamps the
        # device.<i>.hbm_* gauges right after the device work the span
        # wrapped; on CPU the first probe latches availability off and
        # this is a single flag test per span
        try:
            from openr_tpu.monitor import device as _device

            _device.sample_hbm(self.counters)
        except Exception:  # noqa: BLE001 — profiling must never break prod
            pass
        return False
