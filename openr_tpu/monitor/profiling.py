"""Kernel tracing surface (SURVEY §5.1: "JAX profiler + xprof traces
for the SPF kernel, plus the same counter surface").

Wraps jax.profiler so the rest of the framework never imports jax for
observability alone, and so tracing degrades to a no-op on hosts where
the backend is unavailable (the axon tunnel can be down while the CPU
control plane keeps running).

Usage:
  with profiling.trace("/tmp/spf_trace"):      # xprof trace directory
      solver.compute_routes(...)
  with profiling.annotate("spf:solve"):        # named span inside it
      ...

bench.py honors OPENR_BENCH_TRACE=<dir> and wraps its timed iterations;
TpuSpfSolver annotates solve/assembly phases so the xprof timeline
separates device solve time from host RIB assembly.
"""

from __future__ import annotations

import contextlib
import logging

log = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(trace_dir: str | None):
    """jax.profiler.trace(trace_dir), or a no-op when dir is falsy or
    the profiler is unavailable/fails to start (unwritable directory,
    session already active, ...)."""
    if not trace_dir:
        yield
        return
    cm = None
    try:
        import jax

        cm = jax.profiler.trace(trace_dir)
        cm.__enter__()  # start_trace runs HERE — keep it under the guard
    except Exception:  # noqa: BLE001 — profiling must never break prod
        log.warning("jax profiler unavailable; tracing disabled")
        yield
        return
    try:
        yield
    finally:
        try:
            cm.__exit__(None, None, None)
        except Exception:  # noqa: BLE001 — export failure (bad dir, ...)
            log.warning("jax profiler trace export failed", exc_info=True)


def annotate(name: str):
    """Named trace span (xprof timeline row); no-op without jax."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001
        return contextlib.nullcontext()
