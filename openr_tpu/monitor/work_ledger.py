"""Steady-state work ledger: delta-proportionality accounting across
the route dataflow.

The ROADMAP's "end-to-end dataflow deltas" goal is that a publication
delta flows as a *delta* through every pipeline stage. Before this
module only two stages were counter-asserted (``fib.program_scan_routes``
and the jit compile ledger); the remaining O(routes) walks — the
cross-area merge fold and the PrefixManager RIB redistribution — were
known only as orlint suppressions, not measured numbers. The ledger is
the measurement surface: every stage reports *entities touched* against
*delta size*, so steady state is provably delta-proportional or visibly
not (the Bounded Dijkstra work-bound framing from PAPERS.md applied as
a runtime accounting discipline).

Surfaces (same plumbing lineage as the compile ledger / device
telemetry planes):

  * :class:`WorkScope` — a cheap accounting context for hot paths:
    integer adds only, one slotted object per stage entry, **no
    per-entity allocation**. ``with work_ledger.scope("fib", n) as ws:
    ws.add(k)``.
  * ``work.<stage>.touched / .delta / .ratio`` counters exported
    through the existing Counters → Prometheus → fleet surface
    (registered in monitor/names.py, documented in docs/Monitor.md;
    ``*.ratio`` aggregates by distribution only — never summed —
    in monitor/fleet.py).
  * ``ctrl get_work_ledger`` + ``breeze monitor work`` — joined
    per-stage rows with the top offending stage, the same server-side
    join shape as ``get_device_telemetry``.
  * ``@pytest.mark.work_proportional`` — the third conftest sanitizer
    (after the asyncio and jit-compile ones): a marked test calls
    :func:`mark_warm` after warmup; the fixture fails it if any
    steady-state round touched more than ``k·delta + floor`` entities
    in any scoped stage.
  * an emulator soak invariant (emulator/invariants.py
    ``check_work_ratios``) + a ``work.ratio_breach`` flight-recorder
    event, so chaos runs catch full-table regressions with a replay
    seed attached.

Like the compile ledger, the ledger is process-global: stages are a
process-wide resource (the emulator shares one ledger across in-process
nodes, exactly as the compile ledger shares jit caches). Thread-safe:
Decision's compute runs in ``asyncio.to_thread`` workers while Fib
commits from the event loop.

Stage vocabulary (STAGES): ``dirt`` (publication classification),
``spf_full`` / ``spf_warm`` (full / topology-delta solves),
``election`` (best-prefix election), ``assembly`` (scoped prefix route
assembly), ``merge`` (the scoped cross-area book fold — delta-
proportional by construction), ``merge_full`` (the full cross-area
fold, a fallback reached only on first-build / policy / revision-
mismatch rounds — honest O(routes) like ``spf_full``, and exempt for
the same reason), ``diff`` (route-db diff), ``fib`` (delta-native FIB
programming, gated at ratio 1), ``fib_resync`` (the periodic / post-
failure / warm-boot full-table reprogram — honest O(table) with delta
0 by design, split out so a per-process ledger doesn't read the
scheduled resync as a proportionality breach),
``redistribute`` (PrefixManager RIB redistribution — delta-native:
the fold consumes the RouteUpdate delta into the best-entries book and
the advertisement sync ships only dirty prefixes), ``full_sync``
(KvStore anti-entropy compare). ``merge`` and ``redistribute`` were
the two known O(routes) stages BENCH_WORK.json quantified (ratios
6565 / 13129 at 100k prefixes); both are now delta-proportional and
gated — BENCH_WORK_r02.json pins the new baseline, and a reintroduced
full-table walk trips the sanitizer/invariant instead of an exemption.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

#: the pipeline stage vocabulary (docs/Monitor.md "Work ledger")
STAGES: tuple[str, ...] = (
    "dirt",
    "spf_full",
    "spf_warm",
    "election",
    "assembly",
    "merge",
    "merge_full",
    "diff",
    "fib",
    "fib_resync",
    "redistribute",
    "full_sync",
    # crash-recovery replay (persist/): boot-time FIB reconciliation
    # against the recovered durable book — touched is what the handler
    # reprogrammed, delta the desired-vs-durable dataplane diff, so a
    # regression to a full-table boot reprogram breaches the bound
    # (NOT in WORK_EXEMPT_STAGES; ratio gated ≈ 1 by the crash-recovery
    # smoke lane)
    "persist_replay",
)

#: sanitizer default: a steady-state round may touch up to
#: ``k * delta + floor`` entities per stage. The floor absorbs
#: per-round constants (bounded warm-start cones, fixed-size auxiliary
#: walks) that are not per-entity work.
DEFAULT_K = 8.0
DEFAULT_FLOOR = 64


@dataclass
class _StageAcct:
    """Cumulative + since-warm accounting for one stage."""

    __slots__ = (
        "touched", "delta", "rounds",
        "warm_touched", "warm_delta", "warm_rounds",
        "worst_touched", "worst_delta",
    )

    touched: int
    delta: int
    rounds: int
    # snapshot taken at mark_warm(); since-warm = current - warm_*
    warm_touched: int
    warm_delta: int
    warm_rounds: int
    # the worst single round since mark_warm(), by touched/max(delta,1)
    worst_touched: int
    worst_delta: int

    def __init__(self) -> None:
        self.touched = 0
        self.delta = 0
        self.rounds = 0
        self.warm_touched = 0
        self.warm_delta = 0
        self.warm_rounds = 0
        self.worst_touched = 0
        self.worst_delta = 0


def _ratio(touched: int | float, delta: int | float) -> float:
    return touched / max(delta, 1)


class WorkScope:
    """One stage entry's accounting context.

    Steady-state cheap by contract: entering allocates ONE slotted
    object; inside the scope the only operations are integer adds
    (``add`` batches — never call it per entity when a batch count is
    available). Exiting commits (touched, delta) to the process ledger
    under its lock. Exceptions still commit (the work happened) and
    propagate.
    """

    __slots__ = ("stage", "delta", "touched", "_ledger")

    def __init__(self, stage: str, delta_size: int = 0, ledger=None):
        self.stage = stage
        self.delta = int(delta_size)
        self.touched = 0
        self._ledger = ledger if ledger is not None else _LEDGER

    def add(self, n: int = 1) -> None:
        self.touched += n

    def set_delta(self, n: int) -> None:
        """For stages whose delta is only known mid-scope (e.g. the
        full_sync compare computes what it will ship)."""
        self.delta = int(n)

    def __enter__(self) -> "WorkScope":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._ledger.commit(self.stage, self.touched, self.delta)
        return False


class _NullScope:
    """Shared no-op scope returned while the ledger is disabled (the
    bench overhead control): zero allocation, zero lock traffic."""

    __slots__ = ()

    def add(self, n: int = 1) -> None:
        pass

    def set_delta(self, n: int) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NULL_SCOPE = _NullScope()


class WorkLedger:
    """Process-wide per-stage work accounting (see module docstring)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stages: dict[str, _StageAcct] = {s: _StageAcct() for s in STAGES}
        self.enabled = True
        self.warm_marked = False

    # ----------------------------------------------------------- record

    def scope(self, stage: str, delta_size: int = 0):
        if not self.enabled:
            return _NULL_SCOPE
        return WorkScope(stage, delta_size, ledger=self)

    def commit(self, stage: str, touched: int, delta: int) -> None:
        """Record one completed stage round. Integer adds under the
        lock; called once per scope exit, never per entity."""
        if not self.enabled:
            return
        with self._lock:
            acct = self._stages.get(stage)
            if acct is None:
                acct = self._stages.setdefault(stage, _StageAcct())
            acct.touched += touched
            acct.delta += delta
            acct.rounds += 1
            if self.warm_marked and _ratio(touched, delta) > _ratio(
                acct.worst_touched, acct.worst_delta
            ):
                acct.worst_touched = touched
                acct.worst_delta = delta

    # ------------------------------------------------------- warm marks

    def mark_warm(self) -> None:
        """Declare the warmup boundary: rounds committed after this are
        steady state — tracked per stage (since-warm totals + the worst
        single round) and judged by :meth:`steady_violations`. Same
        contract as ``compile_ledger.mark_warm()``."""
        with self._lock:
            self.warm_marked = True
            for acct in self._stages.values():
                acct.warm_touched = acct.touched
                acct.warm_delta = acct.delta
                acct.warm_rounds = acct.rounds
                acct.worst_touched = 0
                acct.worst_delta = 0

    def reset_warm(self) -> None:
        with self._lock:
            self.warm_marked = False
            for acct in self._stages.values():
                acct.warm_touched = acct.touched
                acct.warm_delta = acct.delta
                acct.warm_rounds = acct.rounds
                acct.worst_touched = 0
                acct.worst_delta = 0

    def since_warm(self) -> dict[str, dict]:
        """{stage: {touched, delta, rounds, ratio, worst_ratio}} for
        stages with steady-state rounds; empty when never marked."""
        if not self.warm_marked:
            return {}
        out: dict[str, dict] = {}
        with self._lock:
            for stage, a in self._stages.items():
                rounds = a.rounds - a.warm_rounds
                if rounds <= 0:
                    continue
                touched = a.touched - a.warm_touched
                delta = a.delta - a.warm_delta
                out[stage] = {
                    "touched": touched,
                    "delta": delta,
                    "rounds": rounds,
                    "ratio": round(_ratio(touched, delta), 3),
                    "worst_ratio": round(
                        _ratio(a.worst_touched, a.worst_delta), 3
                    ),
                    "worst_touched": a.worst_touched,
                    "worst_delta": a.worst_delta,
                }
        return out

    def steady_violations(
        self,
        k: float = DEFAULT_K,
        floor: int = DEFAULT_FLOOR,
        exempt: tuple[str, ...] = (),
    ) -> list[dict]:
        """Stages whose worst steady-state round touched more than
        ``k * delta + floor`` entities — the delta-proportionality
        contract the ``work_proportional`` sanitizer enforces. Exempt
        the stages a test legitimately drives O(routes)/O(area)
        (``spf_full``, ``merge_full``, ``full_sync`` and the full diff
        — the counter-asserted fallback class; ``merge`` and
        ``redistribute`` are delta-native and no longer exempt)."""
        out: list[dict] = []
        for stage, row in self.since_warm().items():
            if stage in exempt:
                continue
            t, d = row["worst_touched"], row["worst_delta"]
            if t > k * d + floor:
                out.append(
                    {
                        "stage": stage,
                        "touched": t,
                        "delta": d,
                        "ratio": round(_ratio(t, d), 2),
                        "bound": round(k * d + floor, 1),
                    }
                )
        out.sort(key=lambda r: -r["ratio"])
        return out

    # ---------------------------------------------------------- queries

    def rows(self) -> list[dict]:
        """Per-stage joined rows (cumulative + since-warm), the ctrl /
        breeze table. Stages with zero rounds are omitted."""
        steady = self.since_warm()
        out: list[dict] = []
        with self._lock:
            for stage in self._stages:
                a = self._stages[stage]
                if a.rounds == 0:
                    continue
                row = {
                    "stage": stage,
                    "touched": a.touched,
                    "delta": a.delta,
                    "rounds": a.rounds,
                    "ratio": round(_ratio(a.touched, a.delta), 3),
                }
                s = steady.get(stage)
                row["steady"] = s
                out.append(row)
        # pipeline order, not alphabetical: the table reads as dataflow
        order = {s: i for i, s in enumerate(STAGES)}
        out.sort(key=lambda r: order.get(r["stage"], len(order)))
        return out

    def top_offender(self) -> dict | None:
        """The stage with the worst proportionality ratio (steady-state
        ratio when warm was marked, cumulative otherwise) — the 'where
        is my steady-state time going' headline."""
        rows = self.rows()
        if not rows:
            return None

        def key(r: dict) -> float:
            s = r.get("steady")
            return s["ratio"] if s else r["ratio"]

        worst = max(rows, key=key)
        return {"stage": worst["stage"], "ratio": key(worst)}

    def reset(self) -> None:
        """Drop all accounting (tests/benches)."""
        with self._lock:
            self._stages = {s: _StageAcct() for s in STAGES}
            self.warm_marked = False

    # ----------------------------------------------------------- export

    def export_to(self, counters) -> None:
        """Stamp every active stage into a Counters registry as
        ``work.<stage>.touched/delta/ratio`` gauges (monitor/names.py).
        Values are process-wide, like the compile ledger's."""
        for row in self.rows():
            stage = row["stage"]
            counters.set(f"work.{stage}.touched", float(row["touched"]))
            counters.set(f"work.{stage}.delta", float(row["delta"]))
            counters.set(f"work.{stage}.ratio", float(row["ratio"]))


#: the process ledger every consumer shares
_LEDGER = WorkLedger()


def ledger() -> WorkLedger:
    return _LEDGER


def scope(stage: str, delta_size: int = 0):
    """``with work_ledger.scope("merge", len(scope_set)) as ws: ...`` —
    the hot-path entry point (orlint OR013's structural contract)."""
    return _LEDGER.scope(stage, delta_size)


def commit(stage: str, touched: int, delta: int) -> None:
    """Scope-free commit for sites whose counts are already computed
    (e.g. Fib's delta-book scan)."""
    _LEDGER.commit(stage, touched, delta)


def mark_warm() -> None:
    _LEDGER.mark_warm()


def reset_warm() -> None:
    _LEDGER.reset_warm()


def since_warm() -> dict[str, dict]:
    return _LEDGER.since_warm()


def rows() -> list[dict]:
    return _LEDGER.rows()


def export_to(counters) -> None:
    _LEDGER.export_to(counters)


def reset() -> None:
    _LEDGER.reset()


def steady_violations(
    k: float = DEFAULT_K,
    floor: int = DEFAULT_FLOOR,
    exempt: tuple[str, ...] = (),
) -> list[dict]:
    return _LEDGER.steady_violations(k=k, floor=floor, exempt=exempt)


def set_enabled(on: bool) -> None:
    """Bench control: the overhead comparison runs the same workload
    with scopes no-op'd (shared null scope, zero lock traffic)."""
    _LEDGER.enabled = bool(on)


def steady_violation_report(
    k: float = DEFAULT_K,
    floor: int = DEFAULT_FLOOR,
    exempt: tuple[str, ...] = (),
) -> str | None:
    """Human-readable violation detail for the conftest sanitizer and
    the soak invariant, or None when every scoped stage stayed
    delta-proportional."""
    bad = _LEDGER.steady_violations(k=k, floor=floor, exempt=exempt)
    if not bad:
        return None
    parts = [
        f"{r['stage']}: touched {r['touched']} vs delta {r['delta']} "
        f"(ratio {r['ratio']}, bound {r['bound']})"
        for r in bad
    ]
    return "; ".join(parts)
