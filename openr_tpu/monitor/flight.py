"""Per-node flight recorder: a bounded ring of recent structured events.

A 1000-node soak failure used to mean "re-run with logging": the
invariant checker names the node that diverged, but the *history* that
led there — which rebuilds dispatched, which floods fanned out, which
queues crossed their highwater, which backoffs saturated, which peer
sessions flapped — was gone. The flight recorder keeps that history as
a cheap bounded ring per node, dumped automatically when
``emulator/invariants.py`` fails a check (the dump directory rides the
failure message next to the replay seed) or on demand over ctrl
(``get_flight_recorder`` / ``breeze monitor flight``).

Recording is wired through the node's :class:`Counters` registry
(``counters.flight_record(kind, **attrs)``) — the one object every
module already holds — so adding a record site needs no new plumbing.
Event kinds in use (documented in docs/Monitor.md):

  decision.rebuild           path, ms, traces — one per dispatched rebuild
  kvstore.flood_fanout       area, keys, expired, peers
  kvstore.peer_up/peer_down  peer, area
  kvstore.sync_failed        peer, area, error, backoff_ms, saturated
  kvstore.flood_failed       peer, error
  kvstore.flood_backpressure peer, keys dropped at the pending bound
  fib.program_fail           streak, error, backoff_ms
  fib.backoff_saturated      streak, ms
  queue.highwater            queue, depth, cap — policied seam crossed
                             half its bound with a new watermark

Kinds are free-form dotted strings (module.what); they are NOT counter
names and are not registered in monitor/names.py — the ring is a
post-mortem artifact, not a metrics surface.
"""

from __future__ import annotations

import collections
import itertools
import time
from dataclasses import dataclass, field
from typing import Any

#: default ring capacity — sized so a 64-node churn storm's tail (a few
#: hundred fan-outs + rebuilds per node) survives until the post-storm
#: invariant check runs, while 1000 nodes × capacity stays ~100 MB-scale
DEFAULT_CAPACITY = 512


@dataclass
class FlightEvent:
    """One recorded event: wall-clock + monotonic stamps, a dotted kind,
    and free-form attributes (must stay jsonable — the dump is JSON)."""

    ts: float  # epoch seconds (cross-node alignable, NTP-grade)
    mono_ns: int  # monotonic, exact within the node
    seq: int  # per-recorder sequence (ring eviction survivor ordering)
    kind: str
    attrs: dict[str, Any] = field(default_factory=dict)

    def to_jsonable(self) -> dict:
        return {
            "ts": self.ts,
            "mono_ns": self.mono_ns,
            "seq": self.seq,
            "kind": self.kind,
            "attrs": self.attrs,
        }


class FlightRecorder:
    """Bounded ring of :class:`FlightEvent`s (oldest evicted first)."""

    def __init__(self, node: str = "", capacity: int = DEFAULT_CAPACITY):
        self.node = node
        self.capacity = capacity
        self._ring: collections.deque[FlightEvent] = collections.deque(
            maxlen=capacity
        )
        self._seq = itertools.count()
        self.recorded = 0  # lifetime count (ring length saturates)

    def record(self, kind: str, **attrs: Any) -> None:
        """Append one event. Hot-path cheap: one dataclass + deque
        append; attrs should already be plain jsonable values."""
        self.recorded += 1
        self._ring.append(
            FlightEvent(
                ts=time.time(),
                mono_ns=time.monotonic_ns(),
                seq=next(self._seq),
                kind=kind,
                attrs=attrs,
            )
        )

    def dump(self, limit: int | None = None) -> list[dict]:
        """Jsonable snapshot, oldest first (the post-mortem read order).
        ``limit`` keeps only the newest N (0 = none)."""
        events = list(self._ring)
        if limit is not None and limit >= 0:
            # events[-0:] would be the WHOLE list — honor limit=0
            events = events[-limit:] if limit else []
        return [e.to_jsonable() for e in events]

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)
