"""Runtime JAX compile ledger: per-function compile counts + transfer
counters, exported through the existing Counters/Prometheus path.

The static rules (orlint OR008-OR010) catch recompile *hazards*; this
module observes the recompiles that actually happen. It hooks
``jax.config.jax_log_compiles`` — every XLA compilation logs one
"Compiling <fn> with global shapes and types ..." record from
``jax._src.interpreters.pxla`` — and parses the function name out, so a
steady-state system can assert the thing PAPER.md's determinism mandate
assumes and nothing previously checked: **after warmup, the jit cache
is hit on every solve**. A recompile under churn is a bug (a shape
leaked past the padding buckets, a static arg took a fresh value), and
through the production tunnel it costs ~100 ms+ per variant —
multiplied by chip count once the solve is sharded.

Three consumers:

  * **Counters export** — ``export_to(counters)`` stamps
    ``jax.compiles.<fn>`` per jitted function, ``jax.compiles.total``,
    and the transfer seam counters ``jax.transfers.host_reads`` /
    ``jax.transfers.host_bytes`` (recorded explicitly by the
    spf_backend materialization seams — the process-wide values ride
    each node's Counters into the Prometheus export; see
    docs/Monitor.md).
  * **Test sanitizer** — tests marked ``@pytest.mark.jit_steady_state``
    call :func:`mark_warm` after their warmup calls; the conftest
    fixture fails the test if any compile lands after the mark
    (tests/conftest.py, the compile-stability analogue of the PR 5
    asyncio sanitizer).
  * **Bench lanes** — bench.py splits per-stage first-call compile cost
    out of steady-state p50s, and the churn smoke (ci.sh) exits nonzero
    on any post-warmup steady-state compile.

The handler is process-global and idempotent to install; while
installed, the pxla logger's propagation is disabled so enabling
log_compiles does not spray WARNING lines over test/bench output (the
records still reach any handler attached directly to that logger).
"""

from __future__ import annotations

import logging
import re
import threading
from dataclasses import dataclass, field

#: the loggers jax_log_compiles raises to WARNING (jax 0.4.x):
#: pxla carries the per-compile "Compiling <fn> with global shapes ..."
#: record the ledger parses; dispatch carries the tracing/compile-time
#: chatter. Both have propagation disabled while installed so enabling
#: log_compiles does not spray the test/bench output.
_COMPILE_LOGGER = "jax._src.interpreters.pxla"
_CHATTER_LOGGERS = (_COMPILE_LOGGER, "jax._src.dispatch")

_COMPILE_RE = re.compile(r"Compiling ([\w<>.\-]+) with global shapes")


@dataclass
class LedgerSnapshot:
    """Immutable view of compile counts at a point in time."""

    per_fn: dict[str, int] = field(default_factory=dict)

    @property
    def total(self) -> int:
        return sum(self.per_fn.values())

    def delta(self, newer: "LedgerSnapshot") -> dict[str, int]:
        """{fn: new compiles} between self and `newer` (>=, per fn)."""
        out: dict[str, int] = {}
        for fn, n in newer.per_fn.items():
            d = n - self.per_fn.get(fn, 0)
            if d > 0:
                out[fn] = d
        return out


class _LedgerHandler(logging.Handler):
    def __init__(self, ledger: "CompileLedger"):
        super().__init__(level=logging.DEBUG)
        self._ledger = ledger

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — never break jax logging
            return
        m = _COMPILE_RE.search(msg)
        if m:
            self._ledger._record_compile(m.group(1))


class CompileLedger:
    """Process-wide compile/transfer accounting. Thread-safe: the
    logging handler may fire from any dispatch thread."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._compiles: dict[str, int] = {}
        self._warm: LedgerSnapshot | None = None
        self._handler: _LedgerHandler | None = None
        self._null: logging.NullHandler | None = None
        self._prev_log_compiles: bool | None = None
        self._prev_propagate: dict[str, bool] = {}
        self.host_reads = 0
        self.host_bytes = 0

    # ------------------------------------------------------------ install

    @property
    def installed(self) -> bool:
        return self._handler is not None

    def install(self) -> None:
        """Idempotent: enable jax_log_compiles and attach the parsing
        handler. Import of jax happens here, not at module import — the
        monitor package must stay importable with the backend down."""
        if self._handler is not None:
            return
        import jax

        self._prev_log_compiles = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        logger = logging.getLogger(_COMPILE_LOGGER)
        self._handler = _LedgerHandler(self)
        logger.addHandler(self._handler)
        if logger.level > logging.WARNING or logger.level == 0:
            logger.setLevel(logging.WARNING)
        # keep the (now chatty) compile records off stderr while we
        # consume them; restored on uninstall. The NullHandler matters:
        # a propagate=False logger with NO handler falls through to
        # logging.lastResort, which prints the bare message to stderr
        self._null = logging.NullHandler()
        for name in _CHATTER_LOGGERS:
            lg = logging.getLogger(name)
            self._prev_propagate[name] = lg.propagate
            lg.propagate = False
            lg.addHandler(self._null)

    def uninstall(self) -> None:
        if self._handler is None:
            return
        import jax

        logging.getLogger(_COMPILE_LOGGER).removeHandler(self._handler)
        for name, prev in self._prev_propagate.items():
            lg = logging.getLogger(name)
            lg.propagate = prev
            if self._null is not None:
                lg.removeHandler(self._null)
        self._prev_propagate = {}
        self._null = None
        if self._prev_log_compiles is not None:
            jax.config.update("jax_log_compiles", self._prev_log_compiles)
        self._handler = None

    # ----------------------------------------------------------- recording

    def _record_compile(self, fn: str) -> None:
        with self._lock:
            self._compiles[fn] = self._compiles.get(fn, 0) + 1

    def record_transfer(self, nbytes: int) -> None:
        """One device→host materialization at a transfer seam (the
        spf_backend np.asarray sites). Cheap enough to call
        unconditionally — two int adds against an actual transfer."""
        with self._lock:
            self.host_reads += 1
            self.host_bytes += int(nbytes)

    # ------------------------------------------------------------- queries

    def snapshot(self) -> LedgerSnapshot:
        with self._lock:
            return LedgerSnapshot(per_fn=dict(self._compiles))

    def compiles_of(self, fn: str) -> int:
        """Compile count of one jitted function (0 when never seen or
        the ledger is not installed) — the device-telemetry recapture
        trigger (monitor/device.py), cheap enough for hot paths."""
        with self._lock:
            return self._compiles.get(fn, 0)

    def mark_warm(self) -> None:
        """Declare warmup over: compiles after this point are
        steady-state violations (see compiles_since_warm)."""
        self._warm = self.snapshot()

    @property
    def warm_marked(self) -> bool:
        return self._warm is not None

    def reset_warm(self) -> None:
        self._warm = None

    def compiles_since_warm(self) -> dict[str, int]:
        """{fn: compiles since mark_warm()}; empty when never marked."""
        if self._warm is None:
            return {}
        return self._warm.delta(self.snapshot())

    # -------------------------------------------------------------- export

    def export_to(self, counters) -> None:
        """Stamp the ledger into a Counters registry (names registered
        in monitor/names.py; the jax.compiles.* family is documented in
        docs/Monitor.md). Values are process-wide — compilation is a
        process-global resource shared by every in-process node."""
        snap = self.snapshot()
        for fn, n in snap.per_fn.items():
            counters.set(f"jax.compiles.{fn}", n)
        counters.set("jax.compiles.total", snap.total)
        counters.set("jax.transfers.host_reads", self.host_reads)
        counters.set("jax.transfers.host_bytes", self.host_bytes)


#: the process ledger every consumer shares
_LEDGER = CompileLedger()


def ledger() -> CompileLedger:
    return _LEDGER


def install() -> CompileLedger:
    _LEDGER.install()
    return _LEDGER


def uninstall() -> None:
    _LEDGER.uninstall()


def mark_warm() -> None:
    """Module-level convenience for the test sanitizer contract: a
    ``@pytest.mark.jit_steady_state`` test calls this once its warmup
    calls are done; every compile after it fails the test."""
    _LEDGER.mark_warm()


def record_transfer(nbytes: int) -> None:
    _LEDGER.record_transfer(nbytes)


def compiles_of(fn: str) -> int:
    return _LEDGER.compiles_of(fn)


def export_to(counters) -> None:
    _LEDGER.export_to(counters)
