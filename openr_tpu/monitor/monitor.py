"""Monitor: event-log sample drain (reference: openr/monitor/Monitor.h †).

The reference's modules emit structured LogSample JSON records (neighbor
up/down, restarts, overload changes) into a LogSampleQueue; the Monitor
module drains it, merges in process-common attributes (node name, domain),
keeps a bounded recent-events buffer, and forwards to the operator's
logging pipeline. We keep the same shape: a `LogSample` dataclass, a
ReplicateQueue drain fiber, a ring buffer queryable over the ctrl API.
"""

from __future__ import annotations

import collections
import logging
import time
from dataclasses import dataclass, field
from typing import Any

from openr_tpu.common.eventbase import OpenrModule
from openr_tpu.messaging import QueueClosedError, RQueue

log = logging.getLogger(__name__)


@dataclass
class LogSample:
    """One structured event record (reference: LogSample † — string/int/
    vector key spaces collapsed into one jsonable dict here)."""

    event: str  # e.g. "NEIGHBOR_UP", "NODE_OVERLOAD"
    attrs: dict[str, Any] = field(default_factory=dict)
    ts: float = 0.0  # epoch seconds; stamped by Monitor if 0


class Monitor(OpenrModule):
    """Drains the log-sample queue into a bounded recent-event buffer,
    and the perf-events queue into a bounded recent-trace ring."""

    MAX_EVENTS = 1000  # ring size (reference keeps a bounded export buffer †)
    MAX_PERF_TRACES = 256  # completed convergence traces kept for export

    def __init__(
        self,
        config,
        log_sample_reader: RQueue,
        perf_events_reader: RQueue | None = None,
        counters=None,
    ):
        super().__init__(f"{config.node_name}.monitor", counters=counters)
        self.node_name = config.node_name
        self.reader = log_sample_reader
        self.perf_reader = perf_events_reader
        self.events: collections.deque[LogSample] = collections.deque(
            maxlen=self.MAX_EVENTS
        )
        self.perf_traces: collections.deque = collections.deque(
            maxlen=self.MAX_PERF_TRACES
        )

    async def main(self) -> None:
        self.spawn(self._drain(), name=f"{self.name}.drain")
        if self.perf_reader is not None:
            self.spawn(self._drain_perf(), name=f"{self.name}.perf")

    async def _drain(self) -> None:
        while True:
            try:
                sample = await self.reader.get()
            except QueueClosedError:
                return
            if sample.ts == 0.0:
                sample.ts = time.time()
            # common attributes merged in, as the reference does with
            # node/domain on every sample †
            sample.attrs.setdefault("node_name", self.node_name)
            self.events.append(sample)
            if self.counters:
                self.counters.increment("monitor.log_samples")
            log.debug("event %s %s", sample.event, sample.attrs)

    async def _drain_perf(self) -> None:
        """Collect completed PerfEvents traces (reference: the perf-event
        ring `breeze perf` reads †). Each completed trace also feeds the
        windowed convergence stat, so `monitor.convergence_ms.p50.60`
        is the live end-to-end convergence percentile."""
        while True:
            try:
                trace = await self.perf_reader.get()
            except QueueClosedError:
                return
            self.perf_traces.append(trace)
            if self.counters:
                self.counters.increment("monitor.perf_traces")
                if getattr(trace, "trace_id", 0):
                    # completed sampled flood span (hop-span trace) —
                    # cross-node BY CONSTRUCTION (span-traced pubs skip
                    # the per-hop markers, so the events list alone can
                    # look single-origin at a relay), counted for the
                    # cluster-wide collector and excluded from the
                    # single-node convergence stat
                    self.counters.increment("monitor.flood_traces")
                    continue
                # the windowed stat only ingests single-origin traces:
                # markers stamped on different HOSTS carry unrelated
                # monotonic epochs, so a cross-node total is ordering
                # information, not a duration (see monitor/perf.py)
                origins = {e.node for e in trace.events if e.node}
                if len(origins) <= 1:
                    self.counters.add_value(
                        "monitor.convergence_ms", trace.total_ms()
                    )
                else:
                    self.counters.increment(
                        "monitor.perf_traces_multi_origin"
                    )

    def recent(self, limit: int = 100, event: str | None = None) -> list[LogSample]:
        out = [
            s for s in self.events if event is None or s.event == event
        ]
        return out[-limit:]

    def recent_perf(self, limit: int = 20) -> list:
        """Most recent completed convergence traces, oldest first."""
        return list(self.perf_traces)[-limit:]

    def recent_flood_traces(self, limit: int = 50) -> list:
        """Most recent completed SAMPLED flood spans (hop-span traces),
        oldest first — the per-node slice the cluster-wide collector
        (ctrl get_flood_traces / emulator.tracing) assembles."""
        out = [
            t for t in self.perf_traces if getattr(t, "trace_id", 0)
        ]
        return out[-limit:]
