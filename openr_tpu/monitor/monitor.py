"""Monitor: event-log sample drain (reference: openr/monitor/Monitor.h †).

The reference's modules emit structured LogSample JSON records (neighbor
up/down, restarts, overload changes) into a LogSampleQueue; the Monitor
module drains it, merges in process-common attributes (node name, domain),
keeps a bounded recent-events buffer, and forwards to the operator's
logging pipeline. We keep the same shape: a `LogSample` dataclass, a
ReplicateQueue drain fiber, a ring buffer queryable over the ctrl API.
"""

from __future__ import annotations

import collections
import logging
import time
from dataclasses import dataclass, field
from typing import Any

from openr_tpu.common.eventbase import OpenrModule
from openr_tpu.messaging import QueueClosedError, RQueue

log = logging.getLogger(__name__)


@dataclass
class LogSample:
    """One structured event record (reference: LogSample † — string/int/
    vector key spaces collapsed into one jsonable dict here)."""

    event: str  # e.g. "NEIGHBOR_UP", "NODE_OVERLOAD"
    attrs: dict[str, Any] = field(default_factory=dict)
    ts: float = 0.0  # epoch seconds; stamped by Monitor if 0


class Monitor(OpenrModule):
    """Drains the log-sample queue into a bounded recent-event buffer."""

    MAX_EVENTS = 1000  # ring size (reference keeps a bounded export buffer †)

    def __init__(self, config, log_sample_reader: RQueue, counters=None):
        super().__init__(f"{config.node_name}.monitor", counters=counters)
        self.node_name = config.node_name
        self.reader = log_sample_reader
        self.events: collections.deque[LogSample] = collections.deque(
            maxlen=self.MAX_EVENTS
        )

    async def main(self) -> None:
        self.spawn(self._drain(), name=f"{self.name}.drain")

    async def _drain(self) -> None:
        while True:
            try:
                sample = await self.reader.get()
            except QueueClosedError:
                return
            if sample.ts == 0.0:
                sample.ts = time.time()
            # common attributes merged in, as the reference does with
            # node/domain on every sample †
            sample.attrs.setdefault("node_name", self.node_name)
            self.events.append(sample)
            if self.counters:
                self.counters.increment("monitor.log_samples")
            log.debug("event %s %s", sample.event, sample.attrs)

    def recent(self, limit: int = 100, event: str | None = None) -> list[LogSample]:
        out = [
            s for s in self.events if event is None or s.event == event
        ]
        return out[-limit:]
