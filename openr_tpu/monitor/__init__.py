"""Observability: counters, event-log samples, the Monitor module.

reference: openr/monitor/ † + the fb303 counter surface every module uses
(`fb303::fbData->setCounter/addStatValue` †).
"""

from openr_tpu.monitor import compile_ledger, device, work_ledger  # noqa: F401
from openr_tpu.monitor.counters import (  # noqa: F401
    Counters,
    render_prometheus,
)
from openr_tpu.monitor.fleet import aggregate_counters  # noqa: F401
from openr_tpu.monitor.flight import FlightEvent, FlightRecorder  # noqa: F401
from openr_tpu.monitor.monitor import LogSample, Monitor  # noqa: F401
from openr_tpu.monitor.perf import (  # noqa: F401
    HopSpan,
    PerfEvent,
    PerfEvents,
)
