"""Device telemetry plane: kernel cost ledger + HBM gauges + shard rows.

PR 10's observability plane stops at the host boundary — ``profiling``
records wall-ms spans and the PR 7 compile ledger counts compiles and
transfers, but nothing can say what a kernel *should* cost or how much
HBM it holds. This module closes the device side with three surfaces,
all riding the existing Counters/Prometheus path:

  * **Kernel cost ledger** — at trace time every canonical jitted entry
    point (``ops/`` and ``parallel/sharded_spf.py``) captures XLA's own
    static analysis of the compiled executable:
    ``lowered.compile().cost_analysis()`` (flops, bytes accessed,
    transcendentals) and ``.memory_analysis()`` (argument / output /
    temp / generated-code bytes — the executable's HBM footprint).
    Both are available on the CPU backend, so the whole surface is
    CI-testable without a TPU. Rows are keyed by the same function
    names the compile ledger parses out of ``jax_log_compiles``, and a
    row is (re)captured only when that ledger shows a fresh compile of
    the function — steady state does one dict lookup + int compare and
    never lowers, compiles, or syncs (the OR009 discipline). The AOT
    ``.compile()`` of an already-called jit function is a cache hit on
    jax 0.4.x (pinned by tests/test_device_telemetry.py under the jit
    sanitizer), so capture adds zero XLA compiles.
  * **HBM gauges** — per-device ``memory_stats()`` samples exported as
    ``device.<i>.hbm_bytes_in_use`` / ``hbm_peak_bytes`` /
    ``hbm_limit_bytes``, taken at annotate boundaries
    (monitor/profiling.py) and decision rebuild edges. CPU backends
    return ``None`` from ``memory_stats()``: the first all-None sample
    latches availability off and every later call is a single flag
    test — graceful degradation, no per-span probe cost.
  * **Shard rows** — per-device layout of a sharded output array read
    from its ``Sharding`` metadata WITHOUT touching ``shard.data``
    (which dispatches a ``_multi_slice`` program — a compile + a
    device sync). Used by the sharded-SPF span instrumentation and the
    MULTICHIP dryrun's per-device timing rows.

The joins are pure functions: :func:`efficiency_rows` merges captured
cost rows with the measured ``profile.<span>_ms`` stats into achieved
GFLOP/s / GB/s (``breeze device kernels``, ``ctrl
get_device_telemetry``). Like the compile ledger, the cost ledger is
process-global — compiled executables are a process resource shared by
every in-process node.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

from openr_tpu.monitor import compile_ledger

log = logging.getLogger(__name__)


@dataclass
class KernelCostRow:
    """One captured executable's static cost/memory analysis."""

    fn: str
    #: the profiling span whose measured wall-ms this kernel's work
    #: lands in (the efficiency join key); None = no span association
    span: str | None = None
    #: whether that span measures the work to COMPLETION (a host
    #: materialization inside the span) or only the async dispatch.
    #: Dispatch-only spans are excluded from the achieved-throughput
    #: join — dividing full-kernel flops by dispatch wall would report
    #: unphysical GFLOP/s (review finding)
    span_complete: bool = True
    flops: float = 0.0
    bytes_accessed: float = 0.0
    transcendentals: float = 0.0
    arg_bytes: int = 0
    out_bytes: int = 0
    temp_bytes: int = 0
    code_bytes: int = 0
    #: how many times this fn was (re)captured — tracks recompiles
    captures: int = 0
    shapes: str = ""
    error: str | None = None

    @property
    def resident_hbm_bytes(self) -> int:
        """The executable's device-memory footprint while running:
        arguments + outputs + XLA temp buffers + generated code."""
        return (
            self.arg_bytes + self.out_bytes + self.temp_bytes
            + self.code_bytes
        )

    def to_jsonable(self) -> dict:
        return {
            "fn": self.fn,
            "span": self.span,
            "span_complete": self.span_complete,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "transcendentals": self.transcendentals,
            "arg_bytes": self.arg_bytes,
            "out_bytes": self.out_bytes,
            "temp_bytes": self.temp_bytes,
            "code_bytes": self.code_bytes,
            "resident_hbm_bytes": self.resident_hbm_bytes,
            "captures": self.captures,
            "shapes": self.shapes,
            "error": self.error,
        }

    #: the numeric fields exported as ``jax.kernel.<fn>.<field>``
    EXPORT_FIELDS = (
        "flops", "bytes_accessed", "transcendentals", "arg_bytes",
        "out_bytes", "temp_bytes", "code_bytes", "captures",
    )


def _first_computation(cost) -> dict:
    """``Compiled.cost_analysis()`` returns a list of per-computation
    dicts on jax 0.4.x (one entry for a single-module executable) and a
    bare dict on newer lines; normalize to the entry-computation dict."""
    if isinstance(cost, (list, tuple)):
        return dict(cost[0]) if cost else {}
    return dict(cost or {})


class DeviceTelemetry:
    """Process-wide kernel cost ledger + HBM availability latch.
    Thread-safe like the compile ledger: solver calls may come from
    worker threads in benches."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._rows: dict[str, KernelCostRow] = {}
        #: compile-ledger count of fn at its last capture — the
        #: recapture trigger (a fresh compile means a fresh executable
        #: whose analysis may differ)
        self._seen_compiles: dict[str, int] = {}
        self.enabled = True
        #: tri-state HBM availability: None = unprobed, False = backend
        #: has no memory_stats (CPU), True = gauges live
        self._hbm_state: bool | None = None

    # ------------------------------------------------------------ capture

    def observe(
        self,
        name: str,
        lower,
        span: str | None = None,
        span_complete: bool = True,
    ) -> None:
        """Steady-state-cheap capture guard: (re)capture ``name`` only
        when no row exists yet or the compile ledger has counted a
        fresh compile of it since the last capture. ``lower`` is a
        zero-arg callable returning the jitted function's ``Lowered``
        (``lambda: fn.lower(*the_call_args, **statics)``) — it is only
        invoked when a capture actually happens. ``span_complete=False``
        declares the span times only the async dispatch (see
        :class:`KernelCostRow`)."""
        if not self.enabled:
            return
        compiles = compile_ledger.compiles_of(name)
        with self._lock:
            have = name in self._rows
            seen = self._seen_compiles.get(name)
        if have and (seen == compiles or compiles == 0):
            # compiles == 0: ledger not installed — fall back to
            # capture-once-per-fn (the row exists, keep it)
            return
        self.capture(name, lower, span=span, span_complete=span_complete)

    def capture(
        self,
        name: str,
        lower,
        span: str | None = None,
        span_complete: bool = True,
    ) -> KernelCostRow:
        """Unconditionally capture ``name``'s cost/memory analysis and
        record it (the MULTICHIP dryrun uses this directly to get one
        row per mesh). Never raises: analysis failures land as an
        error row so telemetry can't break a solve."""
        row = KernelCostRow(fn=name, span=span, span_complete=span_complete)
        try:
            lowered = lower()
            compiled = lowered.compile()
            cost = _first_computation(compiled.cost_analysis())
            row.flops = float(cost.get("flops", 0.0))
            row.bytes_accessed = float(cost.get("bytes accessed", 0.0))
            row.transcendentals = float(cost.get("transcendentals", 0.0))
            mem = compiled.memory_analysis()
            if mem is not None:
                row.arg_bytes = int(
                    getattr(mem, "argument_size_in_bytes", 0)
                )
                row.out_bytes = int(getattr(mem, "output_size_in_bytes", 0))
                row.temp_bytes = int(getattr(mem, "temp_size_in_bytes", 0))
                row.code_bytes = int(
                    getattr(mem, "generated_code_size_in_bytes", 0)
                )
            avals = getattr(lowered, "in_avals", None)
            if avals is not None:
                try:
                    import jax

                    row.shapes = ",".join(
                        str(getattr(a, "shape", "?"))
                        for a in jax.tree_util.tree_leaves(avals)
                    )
                except Exception:  # noqa: BLE001 — cosmetic only
                    row.shapes = ""
        except Exception as e:  # noqa: BLE001 — telemetry must not break prod
            row.error = f"{type(e).__name__}: {e}"
            log.warning("kernel cost capture failed for %s: %s", name, e)
        with self._lock:
            prev = self._rows.get(name)
            row.captures = (prev.captures if prev else 0) + 1
            self._rows[name] = row
            self._seen_compiles[name] = compile_ledger.compiles_of(name)
        return row

    # ------------------------------------------------------------ queries

    def kernel_rows(self) -> dict[str, KernelCostRow]:
        with self._lock:
            return dict(self._rows)

    def reset(self) -> None:
        """Drop every captured row and the HBM latch (tests)."""
        with self._lock:
            self._rows.clear()
            self._seen_compiles.clear()
            self._hbm_state = None

    # ------------------------------------------------------------- export

    def export_to(self, counters) -> None:
        """Stamp every captured row into a Counters registry as
        ``jax.kernel.<fn>.<field>`` gauges (registered in
        monitor/names.py, documented in docs/Monitor.md). Values are
        process-wide, like the compile ledger's."""
        for name, row in self.kernel_rows().items():
            if row.error is not None:
                continue
            for fld in KernelCostRow.EXPORT_FIELDS:
                counters.set(f"jax.kernel.{name}.{fld}", getattr(row, fld))

    # ---------------------------------------------------------------- hbm

    def sample_hbm(self, counters=None) -> list[dict] | None:
        """Per-device ``memory_stats()`` rows, or None when the backend
        exposes none (CPU). With ``counters``, live/peak/limit bytes are
        also stamped as ``device.<i>.*`` gauges. The first all-None
        sample latches availability off so annotate-boundary sampling
        costs one flag test per span on CPU."""
        if self._hbm_state is False:
            return None
        try:
            import jax

            devices = jax.local_devices()
        except Exception:  # noqa: BLE001 — backend down ≠ telemetry crash
            # do NOT latch: a transient init failure (the down-tunnel
            # window) must not disable HBM gauges for the process
            # lifetime once the backend recovers (review finding); the
            # permanent latch is reserved for backends that enumerate
            # fine and genuinely expose no memory_stats (CPU)
            return None
        rows: list[dict] = []
        any_stats = False
        any_errors = False
        for i, d in enumerate(devices):
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001 — per-device degradation
                stats = None
                any_errors = True
            if not stats:
                continue
            any_stats = True
            in_use = int(stats.get("bytes_in_use", 0))
            peak = int(stats.get("peak_bytes_in_use", in_use))
            limit = int(stats.get("bytes_limit", 0))
            rows.append(
                {
                    "device": i,
                    "kind": getattr(d, "device_kind", d.platform),
                    "platform": d.platform,
                    "hbm_bytes_in_use": in_use,
                    "hbm_peak_bytes": peak,
                    "hbm_limit_bytes": limit,
                }
            )
            if counters is not None:
                counters.set(f"device.{i}.hbm_bytes_in_use", in_use)
                counters.set(f"device.{i}.hbm_peak_bytes", peak)
                counters.set(f"device.{i}.hbm_limit_bytes", limit)
        if not any_stats:
            if not any_errors:
                # every device answered "no stats" — the CPU shape:
                # latch off so later samples are one flag test
                self._hbm_state = False
            return None
        self._hbm_state = True
        return rows

    @property
    def hbm_available(self) -> bool | None:
        return self._hbm_state

    def hbm_in_use_mb(self) -> float | None:
        """Summed live HBM across local devices in MB, or None on
        backends without memory_stats — the soak watermark's sample
        (emulator/soak.py SoakConfig.hbm_slack_mb)."""
        rows = self.sample_hbm()
        if rows is None:
            return None
        return sum(r["hbm_bytes_in_use"] for r in rows) / 1e6


# ----------------------------------------------------------- pure joins


def efficiency_rows(
    rows: dict[str, KernelCostRow], snapshot: dict[str, float]
) -> list[dict]:
    """Join captured cost rows with measured span stats into achieved
    throughput: for each kernel whose ``span`` has a recorded
    ``profile.<span>_ms`` stat AND measures the work to completion
    (``span_complete``), compute GFLOP/s and GB/s against the span's
    p50 wall time. A completed span's wall includes host work
    (dispatch, transfer) around the kernel, so achieved numbers are
    honest lower bounds on device utilization; a dispatch-only span
    (async return, e.g. the sharded solve) reports its p50 but NO
    achieved rate — flops over dispatch wall would be unphysical.
    Pure function: feed it any snapshot (ctrl computes it
    server-side)."""
    out: list[dict] = []
    for name in sorted(rows):
        row = rows[name]
        d = row.to_jsonable()
        p50 = count = None
        if row.span:
            p50 = snapshot.get(f"profile.{row.span}_ms.p50")
            count = snapshot.get(f"profile.{row.span}_ms.count")
        d["span_p50_ms"] = p50
        d["span_count"] = int(count) if count else 0
        if row.span_complete and p50 and p50 > 0:
            sec = p50 / 1e3
            d["achieved_gflops"] = round(row.flops / sec / 1e9, 3)
            d["achieved_gbs"] = round(row.bytes_accessed / sec / 1e9, 3)
        else:
            d["achieved_gflops"] = None
            d["achieved_gbs"] = None
        out.append(d)
    return out


def shard_rows(arr) -> list[dict]:
    """Per-device shard layout of a sharded array from its Sharding
    metadata only — never ``shard.data`` (that dispatches a
    ``_multi_slice`` program: an XLA compile the steady-state gate
    would rightly flag, plus a device sync)."""
    try:
        sharding = arr.sharding
        shape = arr.shape
        itemsize = arr.dtype.itemsize
        shard_shape = sharding.shard_shape(shape)
        nbytes = itemsize
        for s in shard_shape:
            nbytes *= s
        rows = []
        for dev, idx in sharding.devices_indices_map(shape).items():
            index = [
                [
                    0 if sl.start is None else int(sl.start),
                    dim if sl.stop is None else int(sl.stop),
                ]
                for sl, dim in zip(idx, shape)
            ]
            rows.append(
                {
                    "device": dev.id,
                    "platform": dev.platform,
                    "index": index,
                    "shard_shape": list(shard_shape),
                    "shard_bytes": nbytes,
                }
            )
        rows.sort(key=lambda r: r["device"])
        return rows
    except Exception as e:  # noqa: BLE001 — metadata-only best effort
        log.debug("shard_rows unavailable: %s", e)
        return []


#: the process telemetry every consumer shares
_TELEMETRY = DeviceTelemetry()


def telemetry() -> DeviceTelemetry:
    return _TELEMETRY


def observe(
    name: str,
    lower,
    span: str | None = None,
    span_complete: bool = True,
) -> None:
    _TELEMETRY.observe(name, lower, span=span, span_complete=span_complete)


def capture(
    name: str,
    lower,
    span: str | None = None,
    span_complete: bool = True,
) -> KernelCostRow:
    return _TELEMETRY.capture(
        name, lower, span=span, span_complete=span_complete
    )


def kernel_rows() -> dict[str, KernelCostRow]:
    return _TELEMETRY.kernel_rows()


def export_to(counters) -> None:
    _TELEMETRY.export_to(counters)


def sample_hbm(counters=None) -> list[dict] | None:
    return _TELEMETRY.sample_hbm(counters)


def hbm_in_use_mb() -> float | None:
    return _TELEMETRY.hbm_in_use_mb()
