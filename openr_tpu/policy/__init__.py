"""Route policy (reference: openr/policy/ † + RibPolicy in OpenrCtrl.thrift †)."""

from openr_tpu.policy.policy import (  # noqa: F401
    PolicyManager,
    PolicyStatement,
    RibPolicy,
    RibPolicyStatement,
    RouteMap,
    RouteMapTerm,
)
