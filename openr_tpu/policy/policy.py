"""Policy engines: origination-time transforms and Decision-side RibPolicy.

reference:
  * openr/policy/PolicyManager † — match/transform applied when prefixes
    are originated or redistributed (PrefixManager seam): match on tags /
    prefix list, then accept (optionally rewriting metrics/tags) or deny.
  * RibPolicy in openr/if/OpenrCtrl.thrift † — Decision-side weight
    policy with a TTL: statements match routes (by prefix or tag) and
    assign per-nexthop UCMP weights from area / neighbor maps; weight 0
    removes the nexthop. Applied by Decision after route computation
    (Decision::processRibPolicyUpdate / RibPolicy::applyPolicy †).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace

from openr_tpu.decision.ksp import normalize_weights
from openr_tpu.types.network import IpPrefix
from openr_tpu.types.routes import RouteDatabase
from openr_tpu.types.topology import PrefixEntry, PrefixMetrics

# ---------------------------------------------------------------- origination


@dataclass(frozen=True)
class PolicyStatement:
    """One origination policy rule (reference: PolicyStatement †).

    Matching: empty matcher field = wildcard. `match_tags` matches if the
    entry carries ANY of the tags; `match_prefixes` matches exact prefix
    or any subnet of a listed prefix.
    """

    name: str = ""
    match_tags: tuple[str, ...] = ()
    match_prefixes: tuple[str, ...] = ()
    action_accept: bool = True
    set_path_preference: int | None = None
    set_source_preference: int | None = None
    set_distance_increment: int | None = None  # distance += N (redistribution)
    add_tags: tuple[str, ...] = ()

    def matches(self, entry: PrefixEntry) -> bool:
        if self.match_tags and not (set(self.match_tags) & set(entry.tags)):
            return False
        if self.match_prefixes:
            net = entry.prefix.network
            ok = False
            for p in self.match_prefixes:
                pn = IpPrefix.make(p).network
                if pn.version == net.version and net.subnet_of(pn):
                    ok = True
                    break
            if not ok:
                return False
        return True

    def apply(self, entry: PrefixEntry) -> PrefixEntry | None:
        if not self.action_accept:
            return None
        m = entry.metrics
        if self.set_path_preference is not None:
            m = replace(m, path_preference=self.set_path_preference)
        if self.set_source_preference is not None:
            m = replace(m, source_preference=self.set_source_preference)
        if self.set_distance_increment is not None:
            m = replace(m, distance=m.distance + self.set_distance_increment)
        tags = tuple(dict.fromkeys((*entry.tags, *self.add_tags)))
        return replace(entry, metrics=m, tags=tags)


@dataclass
class PolicyManager:
    """First-match-wins statement list (reference: PolicyManager †).
    `default_accept` governs entries no statement matches."""

    statements: tuple[PolicyStatement, ...] = ()
    default_accept: bool = True

    def apply(self, entry: PrefixEntry) -> PrefixEntry | None:
        """None = denied (do not originate)."""
        for st in self.statements:
            if st.matches(entry):
                return st.apply(entry)
        return entry if self.default_accept else None


# ------------------------------------------------------------------ RibPolicy


@dataclass(frozen=True)
class RibPolicyStatement:
    """reference: RibPolicyStatement † — matcher + RouteActionWeight."""

    name: str = ""
    match_prefixes: tuple[str, ...] = ()
    match_tags: tuple[str, ...] = ()
    default_weight: int = 1
    area_to_weight: dict[str, int] = field(default_factory=dict)
    neighbor_to_weight: dict[str, int] = field(default_factory=dict)

    def matches(self, entry) -> bool:
        if self.match_tags:
            tags = entry.best_entry.tags if entry.best_entry else ()
            if not (set(self.match_tags) & set(tags)):
                return False
        if self.match_prefixes:
            net = entry.prefix.network
            return any(
                (pn := IpPrefix.make(p).network).version == net.version
                and net.subnet_of(pn)
                for p in self.match_prefixes
            )
        return True

    def weight_for(self, nh) -> int:
        if nh.neighbor_node in self.neighbor_to_weight:
            return self.neighbor_to_weight[nh.neighbor_node]
        if nh.area in self.area_to_weight:
            return self.area_to_weight[nh.area]
        return self.default_weight


@dataclass
class RibPolicy:
    """reference: RibPolicy † — statement list + ttl_secs. Decision holds
    at most one; `apply` mutates a computed RouteDatabase in place."""

    statements: tuple[RibPolicyStatement, ...] = ()
    ttl_secs: float = 300.0

    def __post_init__(self):
        # NOT a dataclass field: the deadline is process-local monotonic
        # time and must never travel over the wire — a deserialized policy
        # re-stamps its TTL from receipt (reference: setRibPolicy installs
        # with ttl_secs counted from the install †)
        self._expires_at = time.monotonic() + self.ttl_secs

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self._expires_at

    def apply(self, rdb: RouteDatabase) -> int:
        """Rewrite nexthop weights on matching routes; returns the number
        of routes modified. Weight 0 drops the nexthop; a route whose
        nexthops all drop is removed (reference: applyAction semantics †)."""
        if self.expired:
            return 0
        modified = 0
        for prefix in list(rdb.unicast_routes):
            entry = rdb.unicast_routes[prefix]
            st = next(
                (s for s in self.statements if s.matches(entry)), None
            )
            if st is None:
                continue
            weighted = {
                (nh.neighbor_node, nh.if_name): st.weight_for(nh)
                for nh in entry.nexthops
            }
            kept = {k: w for k, w in weighted.items() if w > 0}
            if not kept:
                del rdb.unicast_routes[prefix]
                modified += 1
                continue
            norm = normalize_weights(kept)
            new_nhs = tuple(
                sorted(
                    replace(nh, weight=norm[(nh.neighbor_node, nh.if_name)])
                    for nh in entry.nexthops
                    if (nh.neighbor_node, nh.if_name) in kept
                )
            )
            if new_nhs != entry.nexthops:
                rdb.unicast_routes[prefix] = replace(
                    entry, nexthops=new_nhs
                )
                modified += 1
        return modified
