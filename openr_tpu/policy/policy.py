"""Policy engines: origination-time transforms and Decision-side RibPolicy.

reference:
  * openr/policy/PolicyManager † — match/transform applied when prefixes
    are originated or redistributed (PrefixManager seam): match on tags /
    prefix list, then accept (optionally rewriting metrics/tags) or deny.
  * RibPolicy in openr/if/OpenrCtrl.thrift † — Decision-side weight
    policy with a TTL: statements match routes (by prefix or tag) and
    assign per-nexthop UCMP weights from area / neighbor maps; weight 0
    removes the nexthop. Applied by Decision after route computation
    (Decision::processRibPolicyUpdate / RibPolicy::applyPolicy †).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace

from openr_tpu.decision.ksp import normalize_weights
from openr_tpu.types.network import IpPrefix
from openr_tpu.types.routes import RouteDatabase
from openr_tpu.types.topology import PrefixEntry, PrefixMetrics

# ---------------------------------------------------------------- origination


@dataclass(frozen=True)
class PolicyStatement:
    """One origination policy rule (reference: PolicyStatement †).

    Matching: empty matcher field = wildcard. `match_tags` matches if the
    entry carries ANY of the tags; `match_prefixes` matches exact prefix
    or any subnet of a listed prefix.
    """

    name: str = ""
    match_tags: tuple[str, ...] = ()
    match_prefixes: tuple[str, ...] = ()
    action_accept: bool = True
    set_path_preference: int | None = None
    set_source_preference: int | None = None
    set_distance_increment: int | None = None  # distance += N (redistribution)
    add_tags: tuple[str, ...] = ()

    def matches(self, entry: PrefixEntry) -> bool:
        if self.match_tags and not (set(self.match_tags) & set(entry.tags)):
            return False
        if self.match_prefixes:
            net = entry.prefix.network
            ok = False
            for p in self.match_prefixes:
                pn = IpPrefix.make(p).network
                if pn.version == net.version and net.subnet_of(pn):
                    ok = True
                    break
            if not ok:
                return False
        return True

    def apply(self, entry: PrefixEntry) -> PrefixEntry | None:
        if not self.action_accept:
            return None
        m = entry.metrics
        if self.set_path_preference is not None:
            m = replace(m, path_preference=self.set_path_preference)
        if self.set_source_preference is not None:
            m = replace(m, source_preference=self.set_source_preference)
        if self.set_distance_increment is not None:
            m = replace(m, distance=m.distance + self.set_distance_increment)
        tags = tuple(dict.fromkeys((*entry.tags, *self.add_tags)))
        return replace(entry, metrics=m, tags=tags)


@dataclass(frozen=True)
class RouteMapTerm:
    """One numbered term of an ordered route-map.

    reference: openr/policy/ † PolicyStatement lists are evaluated in
    order; this is the full route-map shape (numbered sequence,
    permit/deny, AND-of-matchers, tag-set algebra) that network
    operators expect from the policy layer.

    Matching is the AND of every non-empty matcher:
      match_tags_any   — entry carries at least one of these tags
      match_tags_all   — entry carries every one of these tags
      match_not_tags   — entry carries none of these tags
      match_prefixes   — entry's prefix is a subnet of one listed, with
                         optional [ge, le] prefix-length bounds per item
                         ("10.0.0.0/8 ge 24 le 28" style, parsed form)
    Transforms (permit only), applied in this order:
      set_tags (replace) -> add_tags -> remove_tags, then preference /
      distance rewrites.
    """

    seq: int
    action: str = "permit"  # "permit" | "deny"
    match_tags_any: tuple[str, ...] = ()
    match_tags_all: tuple[str, ...] = ()
    match_not_tags: tuple[str, ...] = ()
    # (prefix, ge, le): ge/le = 0 means unconstrained
    match_prefixes: tuple[tuple[str, int, int], ...] = ()
    set_path_preference: int | None = None
    set_source_preference: int | None = None
    set_distance_increment: int | None = None
    set_tags: tuple[str, ...] | None = None
    add_tags: tuple[str, ...] = ()
    remove_tags: tuple[str, ...] = ()

    def __post_init__(self):
        # parse + validate the prefix matchers and freeze the tag sets
        # ONCE (redistribution applies the map per RIB prefix — doing
        # this per evaluation would be O(prefixes x terms) rebuild work,
        # and a malformed prefix must fail at build time, not on the
        # first matching entry inside PrefixManager's event loop)
        object.__setattr__(
            self,
            "_nets",
            tuple(
                (IpPrefix.make(p).network, ge, le)
                for p, ge, le in self.match_prefixes
            ),
        )
        object.__setattr__(self, "_any", frozenset(self.match_tags_any))
        object.__setattr__(self, "_all", frozenset(self.match_tags_all))
        object.__setattr__(self, "_not", frozenset(self.match_not_tags))

    def matches(self, entry: PrefixEntry, _tags=None) -> bool:
        tags = set(entry.tags) if _tags is None else _tags
        if self._any and not (self._any & tags):
            return False
        if self._all and not (self._all <= tags):
            return False
        if self._not and (self._not & tags):
            return False
        if self.match_prefixes:
            net = entry.prefix.network
            for pn, ge, le in self._nets:
                if pn.version != net.version or not net.subnet_of(pn):
                    continue
                if ge and net.prefixlen < ge:
                    continue
                if le and net.prefixlen > le:
                    continue
                return True
            return False
        return True

    def transform(self, entry: PrefixEntry) -> PrefixEntry:
        tags = list(self.set_tags) if self.set_tags is not None else list(
            entry.tags
        )
        tags += [t for t in self.add_tags if t not in tags]
        if self.remove_tags:
            drop = set(self.remove_tags)
            tags = [t for t in tags if t not in drop]
        m = entry.metrics
        if self.set_path_preference is not None:
            m = replace(m, path_preference=self.set_path_preference)
        if self.set_source_preference is not None:
            m = replace(m, source_preference=self.set_source_preference)
        if self.set_distance_increment is not None:
            m = replace(m, distance=m.distance + self.set_distance_increment)
        return replace(entry, metrics=m, tags=tuple(dict.fromkeys(tags)))


@dataclass(frozen=True)
class RouteMap:
    """Ordered route-map: terms evaluated in ascending `seq`; the FIRST
    matching term decides (permit -> transformed entry, deny -> None);
    no match falls through to `default_accept` (route-map convention:
    implicit deny).

    Earlier broad terms SHADOW later ones — covered explicitly by
    tests/test_policy.py along with fallthrough semantics.
    """

    name: str = ""
    terms: tuple[RouteMapTerm, ...] = ()
    default_accept: bool = False

    def __post_init__(self):
        object.__setattr__(
            self, "terms", tuple(sorted(self.terms, key=lambda t: t.seq))
        )
        seqs = [t.seq for t in self.terms]
        if len(set(seqs)) != len(seqs):
            raise ValueError(f"route-map {self.name!r}: duplicate seq")
        for t in self.terms:
            if t.action not in ("permit", "deny"):
                raise ValueError(
                    f"route-map {self.name!r} seq {t.seq}: bad action "
                    f"{t.action!r}"
                )

    def apply(self, entry: PrefixEntry) -> PrefixEntry | None:
        tags = set(entry.tags)  # once per entry, shared across terms
        for t in self.terms:
            if t.matches(entry, _tags=tags):
                if t.action == "deny":
                    return None
                return t.transform(entry)
        return entry if self.default_accept else None


@dataclass
class PolicyManager:
    """Origination/redistribution policy engine (reference:
    PolicyManager †). Either an ordered `route_map` (takes precedence)
    or the simpler first-match statement list; `default_accept` governs
    entries nothing matches on the statement path (the route-map has
    its own default)."""

    statements: tuple[PolicyStatement, ...] = ()
    default_accept: bool = True
    route_map: RouteMap | None = None

    def apply(self, entry: PrefixEntry) -> PrefixEntry | None:
        """None = denied (do not originate)."""
        if self.route_map is not None:
            return self.route_map.apply(entry)
        for st in self.statements:
            if st.matches(entry):
                return st.apply(entry)
        return entry if self.default_accept else None


def parse_prefix_match(spec: str) -> tuple[str, int, int]:
    """Parse "PREFIX [ge N] [le N]" into the RouteMapTerm tuple form."""
    parts = spec.split()
    prefix, ge, le = parts[0], 0, 0
    i = 1
    while i < len(parts):
        if i + 1 >= len(parts):
            raise ValueError(f"bad prefix match {spec!r}")
        kw, val = parts[i], int(parts[i + 1])
        if kw == "ge":
            ge = val
        elif kw == "le":
            le = val
        else:
            raise ValueError(f"bad prefix match {spec!r}")
        i += 2
    if ge and le and ge > le:
        raise ValueError(f"bad prefix match {spec!r}: ge > le")
    IpPrefix.make(prefix)  # validate now — not on first evaluation
    return prefix, ge, le


def build_route_map(term_configs, default_accept: bool) -> RouteMap:
    """Assemble a RouteMap from config.RouteMapTermConfig entries
    (OpenrNode's conversion seam; prefix matchers parsed here)."""
    terms = tuple(
        RouteMapTerm(
            seq=t.seq,
            action=t.action,
            match_tags_any=tuple(t.match_tags_any),
            match_tags_all=tuple(t.match_tags_all),
            match_not_tags=tuple(t.match_not_tags),
            match_prefixes=tuple(
                parse_prefix_match(p) for p in t.match_prefixes
            ),
            set_path_preference=t.set_path_preference,
            set_source_preference=t.set_source_preference,
            set_distance_increment=t.set_distance_increment,
            set_tags=tuple(t.set_tags) if t.set_tags is not None else None,
            add_tags=tuple(t.add_tags),
            remove_tags=tuple(t.remove_tags),
        )
        for t in term_configs
    )
    return RouteMap(terms=terms, default_accept=default_accept)


# ------------------------------------------------------------------ RibPolicy


@dataclass(frozen=True)
class RibPolicyStatement:
    """reference: RibPolicyStatement † — matcher + RouteActionWeight."""

    name: str = ""
    match_prefixes: tuple[str, ...] = ()
    match_tags: tuple[str, ...] = ()
    default_weight: int = 1
    area_to_weight: dict[str, int] = field(default_factory=dict)
    neighbor_to_weight: dict[str, int] = field(default_factory=dict)

    def matches(self, entry) -> bool:
        if self.match_tags:
            tags = entry.best_entry.tags if entry.best_entry else ()
            if not (set(self.match_tags) & set(tags)):
                return False
        if self.match_prefixes:
            net = entry.prefix.network
            return any(
                (pn := IpPrefix.make(p).network).version == net.version
                and net.subnet_of(pn)
                for p in self.match_prefixes
            )
        return True

    def weight_for(self, nh) -> int:
        if nh.neighbor_node in self.neighbor_to_weight:
            return self.neighbor_to_weight[nh.neighbor_node]
        if nh.area in self.area_to_weight:
            return self.area_to_weight[nh.area]
        return self.default_weight


@dataclass
class RibPolicy:
    """reference: RibPolicy † — statement list + ttl_secs. Decision holds
    at most one; `apply` mutates a computed RouteDatabase in place."""

    statements: tuple[RibPolicyStatement, ...] = ()
    ttl_secs: float = 300.0

    def __post_init__(self):
        # NOT a dataclass field: the deadline is process-local monotonic
        # time and must never travel over the wire — a deserialized policy
        # re-stamps its TTL from receipt (reference: setRibPolicy installs
        # with ttl_secs counted from the install †)
        self._expires_at = time.monotonic() + self.ttl_secs

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self._expires_at

    def apply(self, rdb: RouteDatabase) -> int:
        """Rewrite nexthop weights on matching routes; returns the number
        of routes modified. Weight 0 drops the nexthop; a route whose
        nexthops all drop is removed (reference: applyAction semantics †)."""
        if self.expired:
            return 0
        modified = 0
        for prefix in list(rdb.unicast_routes):
            entry = rdb.unicast_routes[prefix]
            st = next(
                (s for s in self.statements if s.matches(entry)), None
            )
            if st is None:
                continue
            weighted = {
                (nh.neighbor_node, nh.if_name): st.weight_for(nh)
                for nh in entry.nexthops
            }
            kept = {k: w for k, w in weighted.items() if w > 0}
            if not kept:
                del rdb.unicast_routes[prefix]
                modified += 1
                continue
            norm = normalize_weights(kept)
            new_nhs = tuple(
                sorted(
                    replace(nh, weight=norm[(nh.neighbor_node, nh.if_name)])
                    for nh in entry.nexthops
                    if (nh.neighbor_node, nh.if_name) in kept
                )
            )
            if new_nhs != entry.nexthops:
                rdb.unicast_routes[prefix] = replace(
                    entry, nexthops=new_nhs
                )
                modified += 1
        return modified
