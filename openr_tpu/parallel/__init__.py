"""Multi-chip parallelism: device meshes and sharded SPF.

The reference's "distribution" is process-level across routers (its
compute is single-threaded per node — SURVEY §2). The TPU rebuild adds the
axis the reference never had: sharding one node's (or the emulator fleet's)
SPF compute across TPU cores —

  * ``sources`` axis — batch of SPF roots, embarrassingly parallel (the
    "data parallel" axis; scales all-sources SSSP and per-node fleets).
  * ``graph`` axis — the edge list partitioned across devices, with an ICI
    `pmin` all-reduce exchanging relaxed distances each iteration (the
    "model parallel" axis; scales LSDBs beyond one chip's HBM).

Collectives ride ICI inside `shard_map`; over DCN, `jax.distributed`
initialises the same mesh across hosts (see `mesh.py`).
"""

from openr_tpu.parallel.mesh import make_mesh  # noqa: F401
from openr_tpu.parallel.sharded_spf import (  # noqa: F401
    sharded_sssp,
    sharded_sssp_padded,
    sharded_sssp_split,
)
