"""Sharded batched SSSP: sources × graph partitioning under shard_map.

The single-device kernel (`ops/spf.py`) already vectorizes over SPF roots;
here the same relax-to-fixpoint runs SPMD:

  * roots sharded over the ``sources`` mesh axis — each device solves its
    slice of roots independently (no communication);
  * the edge list sharded over the ``graph`` mesh axis — each device relaxes
    its edge partition and the partial per-node minima are combined with an
    ICI ``lax.pmin`` all-reduce every iteration (the frontier exchange; the
    moral equivalent of the reference's KvStore flood is host-side — this is
    purely the compute-plane collective).

Distances stay replicated across the ``graph`` axis (Vp·B int32 — the edge
arrays dominate HBM, which is exactly what the graph axis shards), so the
fixpoint condition is computed identically on every shard: no extra
convergence collective needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from openr_tpu.ops.spf import INF_DIST
from openr_tpu.parallel.mesh import GRAPH_AXIS, SOURCES_AXIS


def _shard_map(f, *, mesh, in_specs, out_specs):
    """Version shim: ``jax.shard_map(check_vma=)`` is the jax>=0.6
    spelling; on the 0.4.x line the API lives at
    ``jax.experimental.shard_map.shard_map`` whose ``check_rep`` checker
    has no replication rule for ``while_loop`` (NotImplementedError on
    both kernel bodies) and must be off — the varying/replication
    typing the comments below justify is enforced wherever check_vma
    exists, and the cross-version parity tests (tests/test_parallel.py)
    pin the numerics either way."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=True,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def _local_sssp(edge_src, edge_dst, edge_metric, edge_blocked, roots, num_nodes):
    """Per-device body: local edge shard, local root slice, pmin across the
    graph axis after every segmented relax."""
    metric = edge_metric.astype(jnp.int32)

    is_root_edge = edge_src[:, None] == roots[None, :]
    init_cand = jnp.where(is_root_edge, metric[:, None], INF_DIST)
    dist = jax.ops.segment_min(
        init_cand, edge_dst, num_segments=num_nodes, indices_are_sorted=True
    )
    dist = jax.lax.pmin(jnp.minimum(dist, INF_DIST), GRAPH_AXIS)
    dist = dist.at[roots, jnp.arange(roots.shape[0])].set(0)

    usable = (~edge_blocked)[:, None]

    def relax(state):
        dist, _changed, it = state
        d_src = dist[edge_src]
        cand = jnp.where(
            usable & (d_src < INF_DIST),
            jnp.minimum(d_src + metric[:, None], INF_DIST),
            INF_DIST,
        )
        new = jax.ops.segment_min(
            cand, edge_dst, num_segments=num_nodes, indices_are_sorted=True
        )
        new = jax.lax.pmin(new, GRAPH_AXIS)  # frontier exchange over ICI
        new = jnp.minimum(new, dist)
        return new, jnp.any(new < dist), it + 1

    def cond(state):
        _dist, changed, it = state
        return changed & (it < num_nodes)

    # initial `changed` must carry the same varying-manual-axes type as
    # the loop output (jnp.any over the sources-sharded dist): a literal
    # True is unvarying and check_vma rightly rejects it. Each sources
    # shard may run a different trip count — safe, because shards in the
    # same graph-axis group share the same root slice, so the pmin
    # collectives inside the loop stay aligned.
    changed0 = jnp.any(dist <= INF_DIST)  # always True, correctly varying
    dist, _, _ = jax.lax.while_loop(cond, relax, (dist, changed0, 0))
    return dist


@functools.partial(
    jax.jit, static_argnames=("mesh", "num_nodes")
)
def sharded_sssp(
    edge_src: jax.Array,  # [Ep] — Ep must divide by the graph axis size
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    edge_blocked: jax.Array,
    roots: jax.Array,  # [B] — B must divide by the sources axis size
    mesh: Mesh,
    num_nodes: int,
) -> jax.Array:
    """Returns dist [Vp, B] (B sharded over `sources`, rows replicated)."""
    fn = _shard_map(
        functools.partial(_local_sssp, num_nodes=num_nodes),
        mesh=mesh,
        in_specs=(
            P(GRAPH_AXIS),
            P(GRAPH_AXIS),
            P(GRAPH_AXIS),
            P(GRAPH_AXIS),
            P(SOURCES_AXIS),
        ),
        out_specs=P(None, SOURCES_AXIS),
    )
    return fn(edge_src, edge_dst, edge_metric, edge_blocked, roots)


def _local_split_sssp(
    base_nbr, base_wgt, ov_ids, ov_nbr, ov_wgt, node_overloaded, roots,
    vp, has_overloads,
):
    """Per-device body for the split-table kernel: this shard owns a
    contiguous row slice of the base in-neighbor tables and relaxes only
    those rows each sweep; the full distance matrix is re-assembled with
    a tiled all_gather over the graph axis (the ICI frontier exchange —
    rows replace pmin because the row partition is disjoint). The tiny
    overflow tables are replicated and relaxed identically everywhere."""
    b = roots.shape[0]
    dist = jnp.full((vp, b), INF_DIST, jnp.int32)
    dist = dist.at[roots, jnp.arange(b)].set(0)
    # the loop carry passes through an all_gather over the graph axis,
    # whose output is varying-on-graph under check_vma; the initial
    # carry must carry the same manual-axes type. (Values stay
    # replicated in fact — every shard computes identical full dist —
    # so per-shard while_loop trip counts coincide and the in-loop
    # collectives stay aligned.) pcast only exists on the check_vma
    # (jax>=0.6) line; 0.4.x's check_rep infers the carry's rep set
    # from the loop body instead, so no cast is needed there.
    if hasattr(jax.lax, "pcast"):
        dist = jax.lax.pcast(dist, GRAPH_AXIS, to="varying")

    if has_overloads:
        over_rows = node_overloaded[base_nbr]  # [vp/G, W] src-overloaded
        over_ov = node_overloaded[ov_nbr]

    def relax(nbr, wgt, over_t, dist):
        # same measured-fastest formulation as the single-device kernel
        # (d-loop of [R]-row gathers, ops/spf_split._relax_rows)
        from openr_tpu.ops.spf_split import _relax_rows

        return _relax_rows(dist, nbr, wgt, over_t, roots, has_overloads)

    def sweep(state):
        dist, _changed, it = state
        mine = relax(
            base_nbr, base_wgt, over_rows if has_overloads else None, dist
        )
        full = jax.lax.all_gather(
            mine, GRAPH_AXIS, axis=0, tiled=True
        )  # [vp, B]
        new = jnp.minimum(full, dist)
        ov_new = relax(ov_nbr, ov_wgt, over_ov if has_overloads else None, dist)
        new = new.at[ov_ids].min(ov_new)
        return new, jnp.any(new < dist), it + 1

    def cond(state):
        _dist, changed, it = state
        return changed & (it < vp)

    changed0 = jnp.any(dist <= INF_DIST)  # varying True (see _local_sssp)
    dist, _, _ = jax.lax.while_loop(cond, sweep, (dist, changed0, 0))
    # dist is replicated in value but varying in type; one identity
    # pmin proves the replication to check_vma for the P(None, sources)
    # out_spec
    return jax.lax.pmin(dist, GRAPH_AXIS)


@functools.partial(
    jax.jit, static_argnames=("mesh", "has_overloads")
)
def sharded_sssp_split(
    base_nbr: jax.Array,   # [vp, W] — vp must divide by the graph axis
    base_wgt: jax.Array,
    ov_ids: jax.Array,     # [Go] (replicated)
    ov_nbr: jax.Array,     # [Go, Wo]
    ov_wgt: jax.Array,
    node_overloaded: jax.Array,  # [vp] bool (replicated)
    roots: jax.Array,      # [B] — B must divide by the sources axis
    mesh: Mesh,
    has_overloads: bool = False,
) -> jax.Array:
    """The flagship v3 split-width kernel (ops/spf_split.py), SPMD over a
    ``sources × graph`` mesh: roots shard over ``sources`` (independent
    solves), the base in-neighbor table rows shard over ``graph`` (HBM
    scaling — the tables dominate at 100k nodes), with one tiled
    all_gather per sweep over ICI. Distances equal the single-device
    kernel's (tests/test_parallel.py)."""
    vp = base_nbr.shape[0]
    g = mesh.shape[GRAPH_AXIS]
    if vp % g:
        raise ValueError(f"vp={vp} must divide by graph axis size {g}")
    fn = _shard_map(
        functools.partial(
            _local_split_sssp, vp=vp, has_overloads=has_overloads
        ),
        mesh=mesh,
        in_specs=(
            P(GRAPH_AXIS, None),
            P(GRAPH_AXIS, None),
            P(None),
            P(None, None),
            P(None, None),
            P(None),
            P(SOURCES_AXIS),
        ),
        out_specs=P(None, SOURCES_AXIS),
    )
    return fn(
        base_nbr, base_wgt, ov_ids, ov_nbr, ov_wgt, node_overloaded, roots
    )


def sharded_sssp_padded(
    edge_src,
    edge_dst,
    edge_metric,
    edge_blocked,
    roots,
    mesh: Mesh,
    num_nodes: int,
) -> jax.Array:
    """`sharded_sssp` for arbitrary sizes: pads roots to a multiple of
    the sources axis (repeating the first root — duplicate columns are
    dropped from the result) and the edge arrays to a multiple of the
    graph axis (dead slots: INF metric, blocked). Returns [Vp, len(roots)].
    """
    s = mesh.shape[SOURCES_AXIS]
    g = mesh.shape[GRAPH_AXIS]
    b = roots.shape[0]
    bp = -(-b // s) * s
    if bp != b:
        roots = jnp.concatenate(
            [roots, jnp.broadcast_to(roots[0], (bp - b,))]
        )
    e = edge_src.shape[0]
    ep = -(-e // g) * g
    if ep != e:
        pad = ep - e
        edge_src = jnp.concatenate(
            [edge_src, jnp.zeros(pad, edge_src.dtype)]
        )
        edge_dst = jnp.concatenate(
            [edge_dst, jnp.full(pad, num_nodes - 1, edge_dst.dtype)]
        )
        edge_metric = jnp.concatenate(
            [edge_metric, jnp.full(pad, INF_DIST, edge_metric.dtype)]
        )
        edge_blocked = jnp.concatenate(
            [edge_blocked, jnp.ones(pad, edge_blocked.dtype)]
        )
    dist = sharded_sssp(
        edge_src, edge_dst, edge_metric, edge_blocked, roots, mesh, num_nodes
    )
    # kernel cost ledger (docs/Monitor.md "Device telemetry"): guarded
    # capture of the sharded edge-list kernel's cost/memory analysis
    from openr_tpu.monitor import device as device_telemetry

    device_telemetry.observe(
        "sharded_sssp",
        lambda: sharded_sssp.lower(
            edge_src, edge_dst, edge_metric, edge_blocked, roots, mesh,
            num_nodes,
        ),
        span="spf:sharded_solve",
        span_complete=False,  # dispatch-only span (async return)
    )
    return dist[:, :b]
