"""Multi-host compute plane: jax.distributed over DCN.

SURVEY §5.8: the compute-plane equivalent of the reference's NCCL/MPI
backend is XLA collectives over ICI within a host and DCN across hosts,
stitched by `jax.distributed`. The control plane (KvStore flooding,
Spark, thrift-equivalent RPC) stays host-side and needs none of this;
only the batched/all-sources SPF shapes scale across hosts, by widening
the `sources` mesh axis (no cross-host collective on the hot path) or
the `graph` axis (pmin frontier exchange rides DCN between hosts).

Wiring is env-driven so a deployment launches identical processes:

  OPENR_COORDINATOR   host:port of process 0 (presence enables multi-host)
  OPENR_NUM_PROCESSES total process count
  OPENR_PROCESS_ID    this process's index

`initialize()` is idempotent and a no-op when unset, so single-host
users never pay for it. Proven by tests/test_multihost.py: two real
processes x 4 virtual CPU devices each form one 8-device global mesh
and run the sharded SPF with cross-process collectives.
"""

from __future__ import annotations

import os

_initialized = False


def initialize(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join (or skip) the multi-host jax.distributed service.

    Returns True when running multi-host. Arguments default from the
    OPENR_* environment; with no coordinator configured this is a
    single-host no-op.
    """
    global _initialized
    if _initialized:
        return True
    coordinator = coordinator or os.environ.get("OPENR_COORDINATOR")
    if not coordinator:
        return False
    if num_processes is None:
        num_processes = int(os.environ["OPENR_NUM_PROCESSES"])
    if process_id is None:
        process_id = int(os.environ["OPENR_PROCESS_ID"])

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def global_mesh(n_graph: int = 1):
    """Mesh over ALL processes' devices (call after `initialize`).

    Axis layout follows make_mesh: `sources` major (embarrassingly
    parallel roots — put the DCN boundary here when possible), `graph`
    minor (pmin all-reduce; keep it inside one host's ICI unless the
    edge list outgrows a host).
    """
    import jax

    from openr_tpu.parallel.mesh import make_mesh

    return make_mesh(n_graph=n_graph, devices=jax.devices())


def shard_host_array(arr, mesh, spec):
    """Place an identical host array onto a (possibly multi-host) mesh.

    Every process passes the same full array; each device materializes
    only its shard. This is the LSDB distribution path: the CSR arrays
    are replicated host-side (every node owns the full LSDB — that is
    what link-state routing IS), so cross-host scatter needs no data
    exchange at all.
    """
    import jax
    from jax.sharding import NamedSharding

    return jax.device_put(arr, NamedSharding(mesh, spec))
