"""Device mesh construction for the SPF shardings."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

SOURCES_AXIS = "sources"
GRAPH_AXIS = "graph"


def make_mesh(
    n_sources: int | None = None,
    n_graph: int = 1,
    devices: list | None = None,
) -> Mesh:
    """2D mesh (sources × graph) over the available devices.

    Defaults put every device on the `sources` axis (pure batch
    parallelism — no collectives on the hot path). `n_graph > 1` carves
    devices for edge-partitioned SPF (pmin all-reduce per iteration); on
    real hardware keep `graph` on the minor axis so the all-reduce rides
    ICI neighbors.
    """
    devs = devices if devices is not None else jax.devices()
    if n_sources is None:
        n_sources = len(devs) // n_graph
    if n_sources * n_graph > len(devs):
        # a real exception, not an assert: this is reachable from
        # operator config (DecisionConfig.mesh_sources/mesh_graph) and
        # must fail loudly even under python -O
        raise ValueError(
            f"mesh {n_sources}x{n_graph} needs "
            f"{n_sources * n_graph} devices, have {len(devs)}"
        )
    arr = np.array(devs[: n_sources * n_graph]).reshape(n_sources, n_graph)
    return Mesh(arr, (SOURCES_AXIS, GRAPH_AXIS))
