"""Prefix origination authority (reference: openr/prefix-manager/ †)."""

from openr_tpu.prefixmgr.prefix_manager import (  # noqa: F401
    PrefixEvent,
    PrefixEventType,
    PrefixManager,
    PrefixSource,
)
