"""PrefixManager: the single authority for what this node advertises.

reference: openr/prefix-manager/PrefixManager.cpp † — consumes origination
requests from config (`originated_prefixes`), the API (OpenrCtrl
advertise/withdraw), and PrefixAllocator; keeps per-(source, prefix)
entries; advertises the best entry per prefix as per-prefix
`prefix:<node>:<area>:[<prefix>]` keys through KvStoreClient; withdraws by
advertising a tombstone (`delete_prefix=True`) that dies by TTL; and gates
config-originated prefixes on supporting routes being programmed in the
FIB (install_to_fib / minimum_supporting_routes), fed by Fib's
programmed-route stream.
"""

from __future__ import annotations

import enum
import logging
from dataclasses import dataclass, field

from openr_tpu.common import constants as C
from openr_tpu.common.eventbase import OpenrModule
from openr_tpu.config import Config, OriginatedPrefix
from openr_tpu.kvstore.client import KvStoreClient
from openr_tpu.messaging import QueueClosedError, RQueue
from openr_tpu.monitor import work_ledger
from openr_tpu.types.network import IpPrefix
from openr_tpu.types.routes import RouteUpdate, RouteUpdateType
from openr_tpu.types.serde import (
    WireDecodeError,
    from_wire_bin,
    to_wire,
    to_wire_bin,
)
from openr_tpu.types.topology import PrefixDatabase, PrefixEntry

log = logging.getLogger(__name__)


def _entry_book_key(source: "PrefixSource", prefix) -> bytes:
    return to_wire_bin([int(source), prefix.prefix])


def _range_book_key(source: "PrefixSource", rkey: tuple) -> bytes:
    return to_wire_bin([int(source), list(rkey)])


class PrefixSource(enum.IntEnum):
    """Origin of a prefix advertisement; higher value wins at equal prefix
    (reference: thrift PrefixType ranking in PrefixManager †)."""

    RIB = 10          # cross-area redistribution
    ALLOCATOR = 20    # PrefixAllocator elected prefix
    CONFIG = 30       # originated_prefixes in config
    API = 40          # operator advertise via OpenrCtrl


class PrefixEventType(enum.IntEnum):
    ADD_PREFIXES = 0
    WITHDRAW_PREFIXES = 1
    WITHDRAW_SOURCE = 2  # withdraw everything from one source


@dataclass
class PrefixEvent:
    """Origination request (reference: PrefixEvent † on prefixUpdatesQueue)."""

    type: PrefixEventType
    source: PrefixSource = PrefixSource.API
    entries: tuple[PrefixEntry, ...] = ()
    dest_areas: tuple[str, ...] = ()  # () = all configured areas
    # range origination (prefixmgr/ranges.py): contiguous prefix blocks
    # advertised as chunked PrefixDatabases — the book holds the range
    # descriptors, never count× PrefixEntry dataclasses. Appended field
    # (wire evolution: older peers default it to ()).
    ranges: tuple = ()


@dataclass
class _Origination:
    """Config-originated prefix with FIB gating state."""

    cfg: OriginatedPrefix
    prefix: IpPrefix = field(init=False)
    supporting: set[IpPrefix] = field(default_factory=set)
    advertised: bool = False

    def __post_init__(self):
        self.prefix = IpPrefix.make(self.cfg.prefix)

    def ready(self) -> bool:
        return len(self.supporting) >= self.cfg.minimum_supporting_routes


class PrefixManager(OpenrModule):
    #: durable books (docs/Persist.md): the redistribution/entry book
    #: and the range-origination book, journaled at their single
    #: mutation seams so a crashed node re-originates from its own disk
    ENTRY_BOOK = "pfx_entries"
    RANGE_BOOK = "pfx_ranges"

    def __init__(
        self,
        config: Config,
        kv_client: KvStoreClient,
        prefix_events_reader: RQueue | None = None,
        fib_updates_reader: RQueue | None = None,
        route_updates_reader: RQueue | None = None,
        policy=None,  # openr_tpu.policy.PolicyManager (origination policy)
        counters=None,
        persist=None,
    ):
        super().__init__(f"{config.node_name}.prefixmgr", counters=counters)
        self.policy = policy
        self.config = config
        self.node_name = config.node_name
        self.kv_client = kv_client
        self.events_reader = prefix_events_reader
        self.fib_reader = fib_updates_reader
        # Decision RIB stream for cross-area redistribution (ABR role);
        # only consumed when >1 area is configured
        self.route_reader = route_updates_reader
        # (source, prefix) -> (entry, dest_areas)
        self._entries: dict[
            tuple[PrefixSource, IpPrefix], tuple[PrefixEntry, tuple[str, ...]]
        ] = {}
        # ---- delta redistribution books ------------------------------
        # All _entries mutations flow through _entry_set/_entry_del so
        # these stay consistent; each makes a formerly O(entries) walk
        # a book read (docs/Monitor.md "Work ledger"):
        #   _best: prefix -> (source, entry, dest_areas) — the winning
        #     advertisement per prefix, maintained incrementally (the
        #     old _best_entries() full walk, as a book);
        #   _owned_count: prefix -> count of non-RIB sources — the O(1)
        #     "never shadow our own origination" probe fold_rib_update
        #     previously rebuilt from the whole book every round;
        #   _by_source: source -> set of prefixes — makes FULL_SYNC
        #     purges and WITHDRAW_SOURCE sweeps O(dropped);
        #   _dirty_adv: prefixes whose best entry (or dest areas) moved
        #     since the last _sync_advertisements — the sync consumes
        #     exactly this set, so advertisement work is O(changed).
        self._best: dict[
            IpPrefix, tuple[PrefixSource, PrefixEntry, tuple[str, ...]]
        ] = {}
        self._owned_count: dict[IpPrefix, int] = {}
        self._by_source: dict[PrefixSource, set[IpPrefix]] = {}
        self._dirty_adv: set[IpPrefix] = set()
        # (source, range key) -> (PrefixRange, dest_areas): the range
        # origination book — O(ranges), never O(prefixes)
        self._range_entries: dict[tuple, tuple] = {}
        # range key -> (PrefixRange, advertised areas) for withdrawal
        self._range_adv: dict[tuple, tuple] = {}
        # prefix -> set of areas currently advertised into
        self._advertised: dict[IpPrefix, set[str]] = {}
        self._originations: list[_Origination] = [
            _Origination(cfg=op) for op in config.node.originated_prefixes
        ]
        self.ttl_ms = config.node.kvstore.key_ttl_ms
        self.persist = persist
        if persist is not None:
            self._recover()

    def _recover(self) -> None:
        """Rebuild the entry + range books from the durable plane.

        _entry_set re-derives every incremental book (_best,
        _owned_count, _by_source) and dirties the prefixes, so main()'s
        first _sync_advertisements re-originates everything with fresh
        TTLs — no dependence on survivors' caches. Plane-side dedup
        makes the replayed record() calls no-ops on disk. Entries that
        became stale while we were down are withdrawn by the same
        machinery that retires them live: the first RIB FULL_SYNC
        purges the RIB slice, and the FIB-gating loop withdraws CONFIG
        originations whose supporting routes never return."""
        from openr_tpu.prefixmgr.ranges import PrefixRange

        n = 0
        for kb, vb in list(self.persist.book(self.ENTRY_BOOK).items()):
            try:
                src_i, _pfx = from_wire_bin(kb)
                entry_wire, areas = from_wire_bin(vb)
                entry = from_wire_bin(entry_wire, PrefixEntry)
                source = PrefixSource(src_i)
            except (WireDecodeError, ValueError, TypeError) as exc:
                log.warning(
                    "%s: dropping undecodable entry record: %s",
                    self.name, exc,
                )
                self.persist.erase(self.ENTRY_BOOK, kb)
                continue
            self._entry_set(source, entry.prefix, entry, tuple(areas))
            n += 1
        for kb, vb in list(self.persist.book(self.RANGE_BOOK).items()):
            try:
                src_i, _rk = from_wire_bin(kb)
                rng_wire, areas = from_wire_bin(vb)
                rng = from_wire_bin(rng_wire, PrefixRange)
                source = PrefixSource(src_i)
            except (WireDecodeError, ValueError, TypeError) as exc:
                log.warning(
                    "%s: dropping undecodable range record: %s",
                    self.name, exc,
                )
                self.persist.erase(self.RANGE_BOOK, kb)
                continue
            self._range_entries[(source, rng.key())] = (rng, tuple(areas))
            n += 1
        # recovered CONFIG originations must stay withdrawable by the
        # FIB-gating loop (advertised=False would strand the tombstone)
        for orig in self._originations:
            if (PrefixSource.CONFIG, orig.prefix) in self._entries:
                orig.advertised = True
        if n:
            log.info(
                "%s: recovered %d durable prefix records", self.name, n
            )

    async def main(self) -> None:
        if self.events_reader is not None:
            self.spawn(self._event_loop(), name=f"{self.name}.events")
        if self.fib_reader is not None:
            self.spawn(self._fib_loop(), name=f"{self.name}.fib")
        if self.route_reader is not None and len(self.config.area_ids()) > 1:
            self.spawn(self._rib_loop(), name=f"{self.name}.rib")
        self._sync_originations()
        self._sync_advertisements()

    # ------------------------------------------------------------- events

    async def _event_loop(self) -> None:
        while True:
            try:
                ev = await self.events_reader.get()
            except QueueClosedError:
                return
            self.process_event(ev)

    def process_event(self, ev: PrefixEvent) -> None:
        if ev.type == PrefixEventType.ADD_PREFIXES:
            for e in ev.entries:
                if self.policy is not None:
                    e = self.policy.apply(e)
                    if e is None:  # denied by origination policy
                        if self.counters:
                            self.counters.increment("prefixmgr.policy_denied")
                        continue
                self._entry_set(ev.source, e.prefix, e, ev.dest_areas)
            # ranges bypass per-entry policy: the template is the only
            # entry shape, and expanding a million members through the
            # policy engine is exactly what range origination avoids —
            # operators policy the template before handing it over
            for r in ev.ranges:
                self._range_set(ev.source, r, ev.dest_areas)
        elif ev.type == PrefixEventType.WITHDRAW_PREFIXES:
            for e in ev.entries:
                self._entry_del(ev.source, e.prefix)
            for r in ev.ranges:
                self._range_del(ev.source, r.key())
        elif ev.type == PrefixEventType.WITHDRAW_SOURCE:
            # O(dropped) via the per-source book — no full-table sweep
            for p in list(self._by_source.get(ev.source, ())):
                self._entry_del(ev.source, p)
            for key in [k for k in self._range_entries if k[0] == ev.source]:
                self._range_del(key[0], key[1])
        self._sync_advertisements()
        if self.counters:
            self.counters.increment("prefixmgr.events")

    # ------------------------------------------------------- entry books

    def _entry_set(
        self,
        source: PrefixSource,
        prefix: IpPrefix,
        entry: PrefixEntry,
        areas: tuple[str, ...],
    ) -> None:
        """Insert/replace one (source, prefix) advertisement, keeping
        every derived book consistent. O(1): the best-entry update is a
        single compare against the current winner."""
        key = (source, prefix)
        prev = self._entries.get(key)
        if prev is not None and prev[0] == entry and prev[1] == areas:
            return  # steady re-fold: nothing moved, nothing dirtied
        self._entries[key] = (entry, areas)
        if self.persist is not None:
            self.persist.record(
                self.ENTRY_BOOK,
                _entry_book_key(source, prefix),
                to_wire_bin([to_wire_bin(entry), list(areas)]),
            )
        if prev is None:
            self._by_source.setdefault(source, set()).add(prefix)
            if source != PrefixSource.RIB:
                self._owned_count[prefix] = (
                    self._owned_count.get(prefix, 0) + 1
                )
        cur = self._best.get(prefix)
        if cur is None or source >= cur[0]:
            if cur != (source, entry, areas):
                self._best[prefix] = (source, entry, areas)
                self._dirty_adv.add(prefix)

    def _entry_del(self, source: PrefixSource, prefix: IpPrefix) -> None:
        """Remove one (source, prefix) advertisement. Best re-election
        on losing the winner probes the remaining sources in descending
        preference order — a constant ≤ len(PrefixSource) probes, never
        a book walk."""
        key = (source, prefix)
        if self._entries.pop(key, None) is None:
            return
        if self.persist is not None:
            self.persist.erase(
                self.ENTRY_BOOK, _entry_book_key(source, prefix)
            )
        srcs = self._by_source.get(source)
        if srcs is not None:
            srcs.discard(prefix)
        if source != PrefixSource.RIB:
            n = self._owned_count.get(prefix, 0) - 1
            if n > 0:
                self._owned_count[prefix] = n
            else:
                self._owned_count.pop(prefix, None)
        cur = self._best.get(prefix)
        if cur is None or cur[0] != source:
            return  # a shadowed source left: the winner is unchanged
        for s in sorted(PrefixSource, reverse=True):
            nxt = self._entries.get((s, prefix))
            if nxt is not None:
                self._best[prefix] = (s, nxt[0], nxt[1])
                break
        else:
            del self._best[prefix]
        self._dirty_adv.add(prefix)

    def _range_set(self, source: PrefixSource, r, areas) -> None:
        self._range_entries[(source, r.key())] = (r, areas)
        if self.persist is not None:
            self.persist.record(
                self.RANGE_BOOK,
                _range_book_key(source, r.key()),
                to_wire_bin([to_wire_bin(r), list(areas)]),
            )

    def _range_del(self, source: PrefixSource, rkey: tuple) -> None:
        if self._range_entries.pop((source, rkey), None) is None:
            return
        if self.persist is not None:
            self.persist.erase(
                self.RANGE_BOOK, _range_book_key(source, rkey)
            )

    # ---------------------------------------------------------- fib gating

    async def _fib_loop(self) -> None:
        while True:
            try:
                upd: RouteUpdate = await self.fib_reader.get()
            except QueueClosedError:
                return
            self._fold_fib_update(upd)
            self._sync_originations()
            self._sync_advertisements()

    def _fold_fib_update(self, upd: RouteUpdate) -> None:
        for orig in self._originations:
            net = orig.prefix.network
            if upd.type == RouteUpdateType.FULL_SYNC:
                orig.supporting = set()
            for p in upd.unicast_to_update:
                if (
                    p != orig.prefix
                    and p.is_v4 == orig.prefix.is_v4
                    and p.network.subnet_of(net)
                ):
                    orig.supporting.add(p)
            for p in upd.unicast_to_delete:
                orig.supporting.discard(p)

    # ------------------------------------------- cross-area redistribution

    async def _rib_loop(self) -> None:
        while True:
            try:
                upd: RouteUpdate = await self.route_reader.get()
            except QueueClosedError:
                return
            self.fold_rib_update(upd)
            self._sync_advertisements()

    def fold_rib_update(self, upd: RouteUpdate) -> None:
        """ABR role (reference: PrefixManager route redistribution across
        areas †): a prefix learned in area X is re-advertised by this
        node into every other configured area, with `distance`
        incremented and X appended to `area_stack`. Loop prevention is
        the area_stack: never redistribute into an area the prefix has
        already traversed.
        """
        import dataclasses

        all_areas = set(self.config.area_ids())
        # work ledger `redistribute` stage: delta-native (ISSUE 17).
        # Touched = the update's own add/delete churn plus the
        # O(previously-redistributed) FULL_SYNC purge; the per-round
        # O(entries) `owned` rebuild and the per-sync `_best_entries`
        # election walk are gone — the _owned_count and _best books
        # carry them incrementally, so the ratio pins at ~1 instead of
        # the ~10^4 PR 16's BENCH_WORK.json measured for this stage.
        delta = len(upd.unicast_to_update) + len(upd.unicast_to_delete)
        with work_ledger.scope("redistribute", delta) as ws:
            if upd.type == RouteUpdateType.FULL_SYNC:
                # drop the RIB slice and re-fold from the update:
                # O(dropped) via the per-source book, not O(entries)
                rib_prefixes = list(
                    self._by_source.get(PrefixSource.RIB, ())
                )
                ws.add(len(rib_prefixes))
                for p in rib_prefixes:
                    self._entry_del(PrefixSource.RIB, p)
            ws.add(delta)
            for prefix, rib in upd.unicast_to_update.items():
                best = rib.best_entry
                if best is None:
                    continue
                # never shadow our own origination — O(1) book probe
                if prefix in self._owned_count:
                    continue
                learned = {nh.area for nh in rib.nexthops if nh.area}
                dest = tuple(
                    sorted(
                        all_areas - learned - set(best.area_stack)
                    )
                )
                if not dest:
                    self._entry_del(PrefixSource.RIB, prefix)
                    continue
                entry = dataclasses.replace(
                    best,
                    metrics=dataclasses.replace(
                        best.metrics, distance=best.metrics.distance + 1
                    ),
                    area_stack=tuple(best.area_stack)
                    + tuple(sorted(learned)),
                )
                if self.policy is not None:
                    entry = self.policy.apply(entry)
                    if entry is None:
                        if self.counters:
                            self.counters.increment(
                                "prefixmgr.policy_denied"
                            )
                        # a previously-accepted version must not linger
                        # with stale attributes once the policy rejects
                        # the update
                        self._entry_del(PrefixSource.RIB, prefix)
                        continue
                self._entry_set(PrefixSource.RIB, prefix, entry, dest)
                if self.counters:
                    self.counters.increment("prefixmgr.redistributed")
            for prefix in upd.unicast_to_delete:
                self._entry_del(PrefixSource.RIB, prefix)

    def _sync_originations(self) -> None:
        """Fold ready config originations into the entry book."""
        for orig in self._originations:
            if orig.ready():
                entry = PrefixEntry(
                    prefix=orig.prefix,
                    metrics=_metrics_for(orig.cfg),
                    forwarding_type=orig.cfg.forwarding_type,
                    forwarding_algorithm=orig.cfg.forwarding_algorithm,
                    tags=tuple(orig.cfg.tags),
                )
                self._entry_set(PrefixSource.CONFIG, orig.prefix, entry, ())
                orig.advertised = True
            elif orig.advertised:
                self._entry_del(PrefixSource.CONFIG, orig.prefix)
                orig.advertised = False

    # -------------------------------------------------------- advertisement

    def _best_entries(self) -> dict[IpPrefix, tuple[PrefixEntry, tuple[str, ...]]]:
        """Winner per prefix — a read of the incrementally-maintained
        `_best` book. The per-sync O(entries) election walk this used
        to be is gone (ISSUE 17); _entry_set/_entry_del keep the book
        exact, so this is O(prefixes-with-a-winner) dict comprehension
        with no work-ledger scope to charge."""
        return {p: (e, a) for p, (_s, e, a) in self._best.items()}

    def _sync_ranges(self) -> None:
        """Make the KvStore reflect the range origination book: each
        range becomes RANGE_CHUNK-sized per-prefix-key PrefixDatabases
        (Decision's prefix ingest handles multi-entry values natively),
        advertised once per range — a steady-state sync pass touches
        nothing, so the cost is O(changed ranges × chunks), never
        O(advertised prefixes)."""
        want = {
            rkey: (rng, areas)
            for (_src, rkey), (rng, areas) in sorted(
                self._range_entries.items()
            )
        }
        all_areas = tuple(self.config.area_ids())
        for rkey, (rng, dest_areas) in want.items():
            areas = tuple(dest_areas or all_areas)
            prev = self._range_adv.get(rkey)
            if prev is not None:
                # re-advertise only when the CONTENT moved: a re-push
                # of the same block with new template metrics or dest
                # areas must reach the KvStore (version bumps supersede
                # the old values), while a steady-state sync pass stays
                # a no-op (review finding: keying on (base, plen,
                # count) alone silently dropped template changes)
                if prev[0].template == rng.template and prev[1] == areas:
                    continue
                stale = set(prev[1]) - set(areas)
                if stale:
                    self._withdraw_range_areas(prev[0], stale)
            chunks = 0
            for area in areas:
                for first, entries in rng.chunks():
                    key = C.prefix_key(self.node_name, area, first)
                    db = PrefixDatabase(
                        this_node_name=self.node_name,
                        prefix_entries=entries,
                        area=area,
                    )
                    self.kv_client.persist_key(
                        area, key, to_wire(db), ttl_ms=self.ttl_ms
                    )
                    chunks += 1
            self._range_adv[rkey] = (rng, areas)
            if self.counters:
                self.counters.increment("prefixmgr.range_chunks", chunks)
        for rkey in [k for k in self._range_adv if k not in want]:
            rng, areas = self._range_adv.pop(rkey)
            self._withdraw_range_areas(rng, areas)
        if self.counters:
            self.counters.set(
                "prefixmgr.range_prefixes",
                sum(len(r) for r, _a in self._range_adv.values()),
            )

    def _withdraw_range_areas(self, rng, areas) -> None:
        """Tombstone every chunk of `rng` in `areas` (full withdrawal
        or the stale-area slice of a re-origination)."""
        for area in areas:
            for first, entries in rng.chunks():
                key = C.prefix_key(self.node_name, area, first)
                tombstone = PrefixDatabase(
                    this_node_name=self.node_name,
                    prefix_entries=entries,
                    area=area,
                    delete_prefix=True,
                )
                self.kv_client.persist_key(
                    area, key, to_wire(tombstone), ttl_ms=self.ttl_ms
                )
                self.kv_client.unset_key(area, key)

    def _sync_advertisements(self) -> None:
        """Make the KvStore reflect the current entry book.

        Delta-native (ISSUE 17): only prefixes dirtied since the last
        sync — best entry changed, winner withdrawn, dest areas moved —
        are (re)advertised or tombstoned. Skipping the unchanged rest
        is semantically a no-op: persist_key registered them once and
        KvStoreClient owns TTL refresh and flood self-healing from
        there, so a steady-state sync pass touches nothing.
        """
        self._sync_ranges()
        all_areas = tuple(self.config.area_ids())
        dirty = self._dirty_adv
        self._dirty_adv = set()
        with work_ledger.scope("redistribute", len(dirty)) as ws:
            ws.add(len(dirty))
            for prefix in dirty:
                best = self._best.get(prefix)
                want_areas = (
                    set(best[2] or all_areas) if best is not None else set()
                )
                adv = self._advertised.get(prefix, set())
                if best is not None:
                    # (re)advertise into every wanted area — a changed
                    # entry must re-persist everywhere it lives (the
                    # version bump supersedes the old value)
                    entry = best[1]
                    for area in want_areas:
                        key = C.prefix_key(
                            self.node_name, area, str(prefix.prefix)
                        )
                        db = PrefixDatabase(
                            this_node_name=self.node_name,
                            prefix_entries=(entry,),
                            area=area,
                        )
                        self.kv_client.persist_key(
                            area, key, to_wire(db), ttl_ms=self.ttl_ms
                        )
                for area in adv - want_areas:
                    key = C.prefix_key(
                        self.node_name, area, str(prefix.prefix)
                    )
                    tombstone = PrefixDatabase(
                        this_node_name=self.node_name,
                        prefix_entries=(PrefixEntry(prefix=prefix),),
                        area=area,
                        delete_prefix=True,
                    )
                    # advertise the tombstone once (version bump beats
                    # the old value everywhere), then stop refreshing:
                    # it dies by TTL (reference: PrefixManager
                    # deleted-entry advertisement †)
                    self.kv_client.persist_key(
                        area, key, to_wire(tombstone), ttl_ms=self.ttl_ms
                    )
                    self.kv_client.unset_key(area, key)
                if want_areas:
                    self._advertised[prefix] = want_areas
                else:
                    self._advertised.pop(prefix, None)
        if self.counters:
            self.counters.set("prefixmgr.advertised", len(self._advertised))
            # entry-book footprint at the sync edge — trips if the book
            # leaks entries the delta path should have retired
            self.counters.set(
                "prefixmgr.redistribute.book_size", len(self._entries)
            )
            # work.redistribute.* gauges refresh at the sync edge — the
            # redistribution pass's own export point (a PrefixManager
            # without a local Decision still reports its walks)
            work_ledger.export_to(self.counters)

    # ------------------------------------------------------------ accessors

    def get_advertised(self) -> dict[IpPrefix, PrefixEntry]:
        return {p: e for p, (e, _a) in self._best_entries().items()
                if p in self._advertised}


def _metrics_for(cfg: OriginatedPrefix):
    from openr_tpu.types.topology import PrefixMetrics

    return PrefixMetrics(
        path_preference=cfg.path_preference,
        source_preference=cfg.source_preference,
    )
