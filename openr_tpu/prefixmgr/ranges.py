"""Range origination: advertise a contiguous block of prefixes as ONE
object instead of minting one PrefixEntry dataclass per prefix.

The million-prefix data plane needs originators that can say "this node
owns 10.128.0.0/9 carved into /24s" without holding a million Python
objects: a :class:`PrefixRange` is a frozen descriptor (base address as
an integer, prefix length, count, one template entry carrying the
shared metrics/flags), and prefixes materialize lazily — per chunk at
advertisement time, per index on demand. PrefixManager advertises a
range as chunked per-prefix-key PrefixDatabases (RANGE_CHUNK entries
per KvStore key), so the wire and the LSDB see the normal prefix-key
shape while the origination book stays O(ranges).
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field, replace

from openr_tpu.types.network import IpPrefix
from openr_tpu.types.serde import register_wire_types
from openr_tpu.types.topology import PrefixEntry

#: prefixes per advertised PrefixDatabase chunk (one KvStore key each):
#: big enough that a 1M-prefix range is ~1k keys, small enough that one
#: chunk's decode stays well under a flood frame budget
RANGE_CHUNK = 1024


def _v4_str(addr: int) -> str:
    return (
        f"{(addr >> 24) & 0xFF}.{(addr >> 16) & 0xFF}."
        f"{(addr >> 8) & 0xFF}.{addr & 0xFF}"
    )


@dataclass(frozen=True)
class PrefixRange:
    """``count`` consecutive ``/plen`` IPv4 prefixes starting at
    ``base`` (must be ``plen``-aligned), all sharing ``template``'s
    metrics/flags. Materialization is arithmetic — no ipaddress parse
    per prefix — and lazy."""

    base: str  # network address of the first prefix, e.g. "10.128.0.0"
    plen: int
    count: int
    template: PrefixEntry = field(
        default_factory=lambda: PrefixEntry(
            prefix=IpPrefix(prefix="0.0.0.0/32")
        )
    )

    def __post_init__(self):
        base_int = int(ipaddress.IPv4Address(self.base))
        step = 1 << (32 - self.plen)
        if base_int % step:
            raise ValueError(
                f"range base {self.base} is not /{self.plen}-aligned"
            )
        if base_int + self.count * step > 1 << 32:
            raise ValueError("range overflows the v4 address space")
        object.__setattr__(self, "_base_int", base_int)
        object.__setattr__(self, "_step", step)

    def __len__(self) -> int:
        return self.count

    def prefix_at(self, i: int) -> IpPrefix:
        if not 0 <= i < self.count:
            raise IndexError(i)
        # canonical by construction: the base is aligned, so every
        # member address is its own network address — IpPrefix.make's
        # normalization would be a no-op (and a 1M-range parse bill)
        return IpPrefix(
            prefix=f"{_v4_str(self._base_int + i * self._step)}/{self.plen}"
        )

    def entry_at(self, i: int) -> PrefixEntry:
        return replace(self.template, prefix=self.prefix_at(i))

    def entries(self):
        """Lazy iterator over the range's PrefixEntry objects."""
        for i in range(self.count):
            yield self.entry_at(i)

    def chunks(self, size: int = RANGE_CHUNK):
        """Yield (first_prefix_str, tuple-of-entries) advertisement
        chunks; each becomes one per-prefix-key PrefixDatabase."""
        for lo in range(0, self.count, size):
            hi = min(lo + size, self.count)
            yield (
                str(self.prefix_at(lo).prefix),
                tuple(self.entry_at(i) for i in range(lo, hi)),
            )

    def key(self) -> tuple[str, int, int]:
        """Identity of the block (base, plen, count) — the origination
        book's dict key."""
        return (self.base, self.plen, self.count)


# wire-schema lock registration: PrefixRange is a persist-plane book
# value (pfx_ranges), so its positional contract is locked like any
# flood-frame type (docs/Persist.md)
register_wire_types(PrefixRange)
