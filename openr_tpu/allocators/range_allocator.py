"""RangeAllocator: collision-free integer election through KvStore.

reference: openr/kvstore/RangeAllocator.{h,cpp} † (historically under
allocators/) — each node claims a value v in [start, end] by writing the
key `<key_prefix><v>` with its own name as payload; the KvStore's
deterministic conflict resolution (version, then originator, then hash)
decides the winner everywhere; losers observe the winning publication and
probe the next candidate. Candidate order is a node-seeded permutation so
contention is rare even when many nodes elect simultaneously.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import math
from typing import Awaitable, Callable

from openr_tpu.common.constants import DEFAULT_AREA
from openr_tpu.common.eventbase import OpenrModule
from openr_tpu.kvstore.kvstore import KvStore
from openr_tpu.messaging import QueueClosedError, RQueue
from openr_tpu.types.kvstore import Publication, Value

log = logging.getLogger(__name__)


class RangeAllocator(OpenrModule):
    """Elect a unique integer from [start, end] for `node_name`.

    `on_allocated(value | None)` fires when the election settles (None =
    range exhausted). The allocation self-heals: if a later sync shows a
    higher-priority claimant for our value, we re-elect and re-notify.
    """

    def __init__(
        self,
        node_name: str,
        kvstore: KvStore,
        pub_reader: RQueue,
        key_prefix: str,
        start: int,
        end: int,
        on_allocated: Callable[[int | None], Awaitable | None] | None = None,
        area: str = DEFAULT_AREA,
        ttl_ms: int | None = None,
        initial_value: int | None = None,
        counters=None,
    ):
        super().__init__(f"{node_name}.range-alloc", counters=counters)
        assert start <= end
        if area not in kvstore.dbs:
            raise ValueError(
                f"range allocator area {area!r} not configured on this "
                f"node's KvStore (has: {sorted(kvstore.dbs)})"
            )
        self.node_name = node_name
        self.kvstore = kvstore
        self.pub_reader = pub_reader
        self.key_prefix = key_prefix
        self.range_start, self.range_end = start, end
        self.on_allocated = on_allocated
        self.area = area
        self.ttl_ms = ttl_ms or kvstore.config.node.kvstore.key_ttl_ms
        self.my_value: int | None = None
        # restart stickiness: try the previously-elected value first
        # (reference: PrefixAllocator loads its last index from
        # PersistentStore and seeds the election with it †)
        self._initial = (
            initial_value
            if initial_value is not None and start <= initial_value <= end
            else None
        )
        self._probe_i = 0
        self.settled = asyncio.Event()

    # ----------------------------------------------------------------- run

    async def main(self) -> None:
        self.spawn(self._watch_loop(), name=f"{self.name}.watch")
        self.run_every(1.0, self._refresh_ttl, name=f"{self.name}.ttl")
        self._probe_next()

    def _key(self, v: int) -> str:
        return f"{self.key_prefix}{v}"

    def _candidate(self, i: int) -> int:
        """i-th candidate: a node-seeded permutation walk of the range
        (stride co-prime with n, so i = 0..n-1 visits every value)."""
        n = self.range_end - self.range_start + 1
        seed = int.from_bytes(
            hashlib.sha256(self.node_name.encode()).digest()[:8], "big"
        )
        stride = (seed % n) or 1
        while math.gcd(stride, n) != 1:
            stride += 1
        return self.range_start + ((seed + i * stride) % n)

    def _claimable(self, v: int) -> bool:
        """Free, expired, or already ours."""
        cur = self.kvstore.get_key(self.area, self._key(v))
        return (
            cur is None
            or not cur.value
            or cur.value.decode() == self.node_name
        )

    def _probe_next(self) -> None:
        n = self.range_end - self.range_start + 1
        if self._initial is not None:
            v, self._initial = self._initial, None
            if self._claimable(v):
                self._claim(v)
                return
        tried = 0
        while tried < n:
            v = self._candidate(self._probe_i)
            self._probe_i += 1
            tried += 1
            if self._claimable(v):
                self._claim(v)
                return
        # every value owned by someone else
        log.warning("%s: range [%d,%d] exhausted", self.name, self.range_start, self.range_end)
        self.my_value = None
        self.settled.set()
        self._notify(None)

    def _claim(self, v: int) -> None:
        key = self._key(v)
        cur = self.kvstore.get_key(self.area, key)
        version = (cur.version + 1) if cur is not None else 1
        self.my_value = v
        accepted = self.kvstore.set_key(
            self.area,
            key,
            Value(
                version=version,
                originator_id=self.node_name,
                value=self.node_name.encode(),
                ttl=self.ttl_ms,
            ).with_hash(),
        )
        if not accepted:  # lost a same-version race locally; re-probe
            log.warning("%s: claim of %d rejected by store", self.name, v)
            self.my_value = None
            self._probe_next()
            return
        # tentatively settled; a publication showing a competing winner for
        # this key re-opens the election (reference: RangeAllocator's
        # keyValUpdated callback †)
        self.settled.set()
        self._notify(v)

    def _notify(self, v: int | None) -> None:
        if self.on_allocated is None:
            return
        res = self.on_allocated(v)
        if asyncio.iscoroutine(res):
            self.spawn(res, name=f"{self.name}.notify")

    # --------------------------------------------------------------- watch

    async def _watch_loop(self) -> None:
        while True:
            try:
                pub: Publication = await self.pub_reader.get()
            except QueueClosedError:
                return
            if pub.area != self.area:
                continue
            if self.my_value is None:
                # exhausted earlier: an expiry or ownership change (payload
                # update, not a ttl-only refresh) may have freed a value
                touched = any(
                    k.startswith(self.key_prefix) for k in pub.expired_keys
                ) or any(
                    k.startswith(self.key_prefix) and v.value is not None
                    for k, v in pub.key_vals.items()
                )
                if touched:
                    self._probe_next()
                continue
            key = self._key(self.my_value)
            if key not in pub.key_vals and key not in pub.expired_keys:
                continue
            cur = self.kvstore.get_key(self.area, key)
            if cur is None:
                self._claim(self.my_value)  # expired: re-claim
            elif cur.value is not None and cur.value.decode() != self.node_name:
                # lost the conflict — someone else owns our value now
                log.info(
                    "%s: lost value %d to %s, re-electing",
                    self.name, self.my_value, cur.value.decode(),
                )
                self.settled.clear()
                self.my_value = None
                self._probe_next()

    # refresh cadence mirrors KvStoreClient._refresh_ttls: bump only when a
    # fraction of the lifetime remains, never on every scan tick
    SCAN_PERIOD_S = 1.0

    def _refresh_ttl(self) -> None:
        if self.my_value is None:
            return
        key = self._key(self.my_value)
        cur = self.kvstore.get_key(self.area, key)
        if cur is None or cur.value is None:
            return
        db = self.kvstore.dbs.get(self.area)
        if db is not None:
            from openr_tpu.common.constants import TTL_REFRESH_FRACTION
            from openr_tpu.types.kvstore import TTL_INFINITY

            remaining = db.remaining_ttl_ms(key)
            threshold = max(
                self.ttl_ms * TTL_REFRESH_FRACTION,
                2.5 * self.SCAN_PERIOD_S * 1e3,
            )
            if remaining == TTL_INFINITY or remaining >= threshold:
                return
        if cur.originator_id == self.node_name:
            self.kvstore.set_key(
                self.area,
                key,
                Value(
                    version=cur.version,
                    originator_id=cur.originator_id,
                    value=None,  # ttl-only refresh
                    ttl=self.ttl_ms,
                    ttl_version=cur.ttl_version + 1,
                    hash=cur.hash,
                ),
            )
