"""Distributed allocators (reference: openr/allocators/ †)."""

from openr_tpu.allocators.range_allocator import RangeAllocator  # noqa: F401
from openr_tpu.allocators.prefix_allocator import PrefixAllocator  # noqa: F401
