"""PrefixAllocator: distributed, collision-free per-node prefix carving.

reference: openr/allocators/PrefixAllocator.{h,cpp} † — the configured
seed prefix (e.g. 10.0.0.0/8 with alloc_prefix_len 24) is carved into
2^(alloc_len - seed_len) equal blocks; each node elects a block index via
`RangeAllocator` and originates the resulting subnet through
PrefixManager (source = ALLOCATOR). Losing an election withdraws and
re-originates the newly won block.
"""

from __future__ import annotations

import ipaddress
import logging

from openr_tpu.common.eventbase import OpenrModule
from openr_tpu.config import Config
from openr_tpu.kvstore.kvstore import KvStore
from openr_tpu.messaging import ReplicateQueue, RQueue
from openr_tpu.allocators.range_allocator import RangeAllocator
from openr_tpu.prefixmgr import PrefixEvent, PrefixEventType, PrefixSource
from openr_tpu.types.network import IpPrefix
from openr_tpu.types.topology import PrefixEntry

log = logging.getLogger(__name__)

ALLOC_KEY_PREFIX = "allocprefix:"  # reference: Constants.h † kPrefixAllocMarker


def carve(seed: IpPrefix, alloc_len: int, index: int) -> IpPrefix:
    """The index-th /alloc_len subnet of the seed prefix."""
    net = seed.network
    sub = ipaddress.ip_network(
        (int(net.network_address) + (index << ((32 if seed.is_v4 else 128) - alloc_len)),
         alloc_len)
    )
    return IpPrefix.make(str(sub))


class PrefixAllocator(OpenrModule):
    def __init__(
        self,
        config: Config,
        kvstore: KvStore,
        pub_reader: RQueue,
        prefix_events_queue: ReplicateQueue,
        store=None,  # PersistentStore: elected index survives restart
        counters=None,
    ):
        super().__init__(f"{config.node_name}.prefix-alloc", counters=counters)
        pa = config.node.prefix_allocation
        assert pa is not None, "prefix_allocation config required"
        self.config = config
        self.node_name = config.node_name
        self.seed = IpPrefix.make(pa.seed_prefix)
        self.alloc_len = pa.alloc_prefix_len
        self.static_index = pa.static_index
        self.prefix_events = prefix_events_queue
        self.num_blocks = 1 << (self.alloc_len - self.seed.prefix_len)
        if self.static_index is not None and not (
            0 <= self.static_index < self.num_blocks
        ):
            raise ValueError(
                f"static_index {self.static_index} outside seed "
                f"{self.seed} blocks [0, {self.num_blocks})"
            )
        self.allocated: IpPrefix | None = None
        self.area = config.area_ids()[0]
        self.store = store
        # reference: PrefixAllocator seeds the election with the index it
        # persisted before restart (loadPrefixFromDisk †), so a restarting
        # node reclaims its block instead of renumbering the fleet
        saved_index = (
            store.get(self._store_key()) if store is not None else None
        )
        self.range_alloc = RangeAllocator(
            config.node_name,
            kvstore,
            pub_reader,
            key_prefix=ALLOC_KEY_PREFIX,
            start=0,
            end=self.num_blocks - 1,
            on_allocated=self._on_index,
            area=self.area,
            initial_value=saved_index,
            counters=counters,
        )

    async def main(self) -> None:
        if self.static_index is not None:
            self._on_index(self.static_index)
            return
        await self.range_alloc.start()

    async def cleanup(self) -> None:
        if self.static_index is None:
            await self.range_alloc.stop()

    def _store_key(self) -> str:
        return f"prefix-allocator.index.{self.seed}.{self.alloc_len}"

    def _on_index(self, index: int | None) -> None:
        if self.store is not None and index is not None:
            self.spawn(
                self.store.store(self._store_key(), index),
                name=f"{self.name}.persist",
            )
        old = self.allocated
        new = carve(self.seed, self.alloc_len, index) if index is not None else None
        if new == old:
            return
        if old is not None:
            self.prefix_events.push(
                PrefixEvent(
                    type=PrefixEventType.WITHDRAW_PREFIXES,
                    source=PrefixSource.ALLOCATOR,
                    entries=(PrefixEntry(prefix=old),),
                )
            )
        self.allocated = new
        if new is not None:
            log.info("%s: allocated %s (block %s)", self.name, new, index)
            self.prefix_events.push(
                PrefixEvent(
                    type=PrefixEventType.ADD_PREFIXES,
                    source=PrefixSource.ALLOCATOR,
                    entries=(PrefixEntry(prefix=new),),
                )
            )
        if self.counters:
            self.counters.increment("prefix_allocator.allocations")
