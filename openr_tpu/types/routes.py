"""RIB types: computed routes and route-update deltas.

Equivalent of the reference's Decision output types
(reference: openr/decision/RibEntry.h †, RouteUpdate.h † —
RibUnicastEntry, RibMplsEntry, DecisionRouteUpdate).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from openr_tpu.types.network import IpPrefix, MplsRoute, NextHop, UnicastRoute
from openr_tpu.types.serde import register_wire_types
from openr_tpu.types.topology import PrefixEntry


class NexthopGroup(tuple):
    """Interned ECMP nexthop set, shared across routes.

    A ``tuple`` subclass: every existing consumer of
    ``RibEntry.nexthops`` / ``UnicastRoute.nexthops`` (iteration,
    indexing, ``sorted_nexthops`` output comparison, serde's
    ``isinstance(v, (list, tuple))`` encoders, equality against plain
    tuples) keeps working unchanged. What the subclass adds is
    *identity*: groups are minted by a :class:`NexthopIntern` table
    keyed by the frozen nexthop tuple, so at a million prefixes the few
    thousand distinct ECMP sets exist ONCE — route memory collapses to
    one binding word per route, and ``==`` between two bindings of the
    same group is a pointer compare instead of an O(nexthops × fields)
    dataclass walk (what `diff_route_dbs` and Fib's desired-vs-
    programmed checks spend their time on at scale). Groups from
    DIFFERENT tables (the two engines, a re-armed artifact after a
    structural rebuild) still compare by content, so correctness never
    depends on which table minted an object.
    """

    # gid: per-table mint sequence — diagnostics only, never compared
    gid = -1

    def __new__(cls, nexthops, gid: int = -1):
        self = super().__new__(cls, nexthops)
        self.gid = gid
        return self

    def __eq__(self, other):
        if self is other:
            return True
        return tuple.__eq__(self, other)

    def __ne__(self, other):
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    __hash__ = tuple.__hash__


class NexthopIntern:
    """Per-artifact nexthop-group intern table.

    ``intern(nhs)`` returns THE group for a frozen nexthop tuple —
    the same object for every route that shares the set, for as long
    as the table lives (one table per solve artifact / solver, so the
    identity horizon matches the cross-rebuild entry caches built on
    top of it). Bounded: past ``cap`` distinct groups the table resets
    rather than growing without bound (correctness is unaffected —
    equality falls back to content)."""

    __slots__ = ("_table", "hits", "cap", "_next_gid")

    def __init__(self, cap: int = 1 << 16):
        self._table: dict[tuple, NexthopGroup] = {}
        self.hits = 0
        self.cap = cap
        self._next_gid = 0

    def intern(self, nhs) -> NexthopGroup:
        if type(nhs) is NexthopGroup:
            return nhs
        got = self._table.get(nhs)
        if got is not None:
            self.hits += 1
            return got
        if len(self._table) >= self.cap:
            self._table.clear()
        g = NexthopGroup(nhs, gid=self._next_gid)
        self._next_gid += 1
        self._table[g] = g  # tuple-keyed lookup works: same hash/eq
        return g

    def __len__(self) -> int:
        return len(self._table)


@dataclass(frozen=True, slots=True)
class RibEntry:
    """A computed unicast route with provenance.

    reference: openr/decision/RibEntry.h † RibUnicastEntry: the winning
    PrefixEntry (for policy/redistribution), the set of best-advertising
    nodes, and the ECMP/UCMP nexthop set.
    """

    prefix: IpPrefix
    # the ECMP set: a plain tuple on the scalar fallback seams, a shared
    # NexthopGroup (tuple subclass — see above) on the vectorized
    # election paths; `slots=True` because a million of these exist at
    # the data-plane scale target and the instance dict was the single
    # largest per-route allocation
    nexthops: tuple[NextHop, ...]
    best_node: str = ""
    best_nodes: tuple[str, ...] = ()
    best_entry: PrefixEntry | None = None
    igp_cost: int = 0
    # RFC 5286 loop-free alternates (neighbors whose shortest path to the
    # destination provably avoids this node); computed when
    # DecisionConfig.enable_lfa is set. Not programmed into the FIB —
    # surfaced for fast-reroute consumers (reference: legacy LFA support
    # in SpfSolver †).
    backup_nexthops: tuple[NextHop, ...] = ()

    def to_unicast_route(self) -> UnicastRoute:
        return UnicastRoute(dest=self.prefix, nexthops=self.nexthops)


@dataclass(frozen=True, slots=True)
class RibMplsEntry:
    """reference: openr/decision/RibEntry.h † RibMplsEntry."""

    label: int
    nexthops: tuple[NextHop, ...]

    def to_mpls_route(self) -> MplsRoute:
        return MplsRoute(top_label=self.label, nexthops=self.nexthops)


@dataclass
class RouteDatabase:
    """Full RIB snapshot (reference: openr/if/Types.thrift † RouteDatabase)."""

    this_node_name: str = ""
    unicast_routes: dict[IpPrefix, RibEntry] = field(default_factory=dict)
    mpls_routes: dict[int, RibMplsEntry] = field(default_factory=dict)


class RouteUpdateType(enum.IntEnum):
    INCREMENTAL = 0
    FULL_SYNC = 1


@dataclass
class RouteUpdate:
    """Delta between successive RIBs — what Decision emits and Fib consumes.

    reference: openr/decision/RouteUpdate.h † DecisionRouteUpdate
    (unicastRoutesToUpdate / unicastRoutesToDelete / mplsRoutesToUpdate /
    mplsRoutesToDelete, type).
    """

    type: RouteUpdateType = RouteUpdateType.INCREMENTAL
    unicast_to_update: dict[IpPrefix, RibEntry] = field(default_factory=dict)
    unicast_to_delete: list[IpPrefix] = field(default_factory=list)
    mpls_to_update: dict[int, RibMplsEntry] = field(default_factory=dict)
    mpls_to_delete: list[int] = field(default_factory=list)
    # convergence traces of the publications folded into this delta
    # (reference: DecisionRouteUpdate.perfEvents †); Fib stamps
    # FIB_PROGRAMMED and completes them into Monitor's ring.
    # compare=False: a trace annotates the delta, it doesn't identify it
    perf_events: list = field(default_factory=list, compare=False)

    def empty(self) -> bool:
        return not (
            self.unicast_to_update
            or self.unicast_to_delete
            or self.mpls_to_update
            or self.mpls_to_delete
        )


def diff_route_dbs(
    old: RouteDatabase,
    new: RouteDatabase,
    prefix_scope=None,
    label_scope=None,
) -> RouteUpdate:
    """Compute the delta update turning `old` into `new`.

    reference: openr/decision/Decision.cpp † (Decision computes deltas on
    rebuildRoutes; Fib re-diffs against programmed state).

    Group-aware: entry equality first short-circuits on object identity
    (the solver's cross-rebuild caches return the same frozen RibEntry
    for unchanged routes), and for changed entries the nexthop compare
    short-circuits on :class:`NexthopGroup` identity — so a scoped diff
    costs O(changed groups + changed bindings), never O(nexthops) per
    route.

    `prefix_scope` / `label_scope` (iterables of candidate keys) restrict
    the walk: only scoped keys are compared, everything else is asserted
    unchanged BY THE CALLER. Decision's prefix-only rebuilds satisfy that
    by construction — the new RIB reuses the previous RIB's entry objects
    verbatim outside the touched-prefix set — so the diff is O(|scope|)
    instead of a full O(routes) sweep. None (the default) walks
    everything.
    """
    upd = RouteUpdate()
    if old is new:
        return upd  # memoized rebuild handed back the same table
    if prefix_scope is None:
        # identity first: the solver's cross-rebuild entry caches hand
        # back the same frozen object for unchanged routes, making the
        # steady-state diff a pointer compare instead of a
        # field-by-field dataclass equality over the nexthop tuples.
        # Locals bound outside the loop: at 1M routes the walk itself
        # is the cost.
        new_u = new.unicast_routes
        old_u = old.unicast_routes
        # no-op fast path: dict equality runs entirely in C with a
        # per-value identity shortcut (PyObject_RichCompareBool), so a
        # byte-identical million-route table proves itself ~4x faster
        # than the python walk below — and a changed table bails at the
        # first divergent slot, so the aborted attempt stays cheap
        if old_u != new_u:
            old_get = old_u.get
            upd_u = upd.unicast_to_update
            for prefix, entry in new_u.items():
                prev = old_get(prefix)
                if prev is not entry and prev != entry:
                    upd_u[prefix] = entry
            # delete scan: the C-speed keys-view set compare proves the
            # common no-delete case without a million-probe python loop
            if old_u.keys() != new_u.keys():
                upd.unicast_to_delete.extend(
                    p for p in old_u if p not in new_u
                )
    else:
        for prefix in sorted(prefix_scope):  # sorted: deterministic delta
            entry = new.unicast_routes.get(prefix)
            if entry is None:
                if prefix in old.unicast_routes:
                    upd.unicast_to_delete.append(prefix)
                continue
            prev = old.unicast_routes.get(prefix)
            if prev is not entry and prev != entry:
                upd.unicast_to_update[prefix] = entry
    if label_scope is None:
        for label, mentry in new.mpls_routes.items():
            prev_m = old.mpls_routes.get(label)
            if prev_m is not mentry and prev_m != mentry:
                upd.mpls_to_update[label] = mentry
        for label in old.mpls_routes:
            if label not in new.mpls_routes:
                upd.mpls_to_delete.append(label)
    else:
        for label in sorted(label_scope):
            mentry = new.mpls_routes.get(label)
            if mentry is None:
                if label in old.mpls_routes:
                    upd.mpls_to_delete.append(label)
                continue
            prev_m = old.mpls_routes.get(label)
            if prev_m is not mentry and prev_m != mentry:
                upd.mpls_to_update[label] = mentry
    return upd


# wire-schema lock registration: RIB snapshots/deltas (ctrl export and
# the persist plane's route books)
register_wire_types(RibEntry, RibMplsEntry, RouteDatabase, RouteUpdate)
