"""RIB types: computed routes and route-update deltas.

Equivalent of the reference's Decision output types
(reference: openr/decision/RibEntry.h †, RouteUpdate.h † —
RibUnicastEntry, RibMplsEntry, DecisionRouteUpdate).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from openr_tpu.types.network import IpPrefix, MplsRoute, NextHop, UnicastRoute
from openr_tpu.types.topology import PrefixEntry


@dataclass(frozen=True)
class RibEntry:
    """A computed unicast route with provenance.

    reference: openr/decision/RibEntry.h † RibUnicastEntry: the winning
    PrefixEntry (for policy/redistribution), the set of best-advertising
    nodes, and the ECMP/UCMP nexthop set.
    """

    prefix: IpPrefix
    nexthops: tuple[NextHop, ...]
    best_node: str = ""
    best_nodes: tuple[str, ...] = ()
    best_entry: PrefixEntry | None = None
    igp_cost: int = 0
    # RFC 5286 loop-free alternates (neighbors whose shortest path to the
    # destination provably avoids this node); computed when
    # DecisionConfig.enable_lfa is set. Not programmed into the FIB —
    # surfaced for fast-reroute consumers (reference: legacy LFA support
    # in SpfSolver †).
    backup_nexthops: tuple[NextHop, ...] = ()

    def to_unicast_route(self) -> UnicastRoute:
        return UnicastRoute(dest=self.prefix, nexthops=self.nexthops)


@dataclass(frozen=True)
class RibMplsEntry:
    """reference: openr/decision/RibEntry.h † RibMplsEntry."""

    label: int
    nexthops: tuple[NextHop, ...]

    def to_mpls_route(self) -> MplsRoute:
        return MplsRoute(top_label=self.label, nexthops=self.nexthops)


@dataclass
class RouteDatabase:
    """Full RIB snapshot (reference: openr/if/Types.thrift † RouteDatabase)."""

    this_node_name: str = ""
    unicast_routes: dict[IpPrefix, RibEntry] = field(default_factory=dict)
    mpls_routes: dict[int, RibMplsEntry] = field(default_factory=dict)


class RouteUpdateType(enum.IntEnum):
    INCREMENTAL = 0
    FULL_SYNC = 1


@dataclass
class RouteUpdate:
    """Delta between successive RIBs — what Decision emits and Fib consumes.

    reference: openr/decision/RouteUpdate.h † DecisionRouteUpdate
    (unicastRoutesToUpdate / unicastRoutesToDelete / mplsRoutesToUpdate /
    mplsRoutesToDelete, type).
    """

    type: RouteUpdateType = RouteUpdateType.INCREMENTAL
    unicast_to_update: dict[IpPrefix, RibEntry] = field(default_factory=dict)
    unicast_to_delete: list[IpPrefix] = field(default_factory=list)
    mpls_to_update: dict[int, RibMplsEntry] = field(default_factory=dict)
    mpls_to_delete: list[int] = field(default_factory=list)
    # convergence traces of the publications folded into this delta
    # (reference: DecisionRouteUpdate.perfEvents †); Fib stamps
    # FIB_PROGRAMMED and completes them into Monitor's ring.
    # compare=False: a trace annotates the delta, it doesn't identify it
    perf_events: list = field(default_factory=list, compare=False)

    def empty(self) -> bool:
        return not (
            self.unicast_to_update
            or self.unicast_to_delete
            or self.mpls_to_update
            or self.mpls_to_delete
        )


def diff_route_dbs(
    old: RouteDatabase,
    new: RouteDatabase,
    prefix_scope=None,
    label_scope=None,
) -> RouteUpdate:
    """Compute the delta update turning `old` into `new`.

    reference: openr/decision/Decision.cpp † (Decision computes deltas on
    rebuildRoutes; Fib re-diffs against programmed state).

    `prefix_scope` / `label_scope` (iterables of candidate keys) restrict
    the walk: only scoped keys are compared, everything else is asserted
    unchanged BY THE CALLER. Decision's prefix-only rebuilds satisfy that
    by construction — the new RIB reuses the previous RIB's entry objects
    verbatim outside the touched-prefix set — so the diff is O(|scope|)
    instead of a full O(routes) sweep. None (the default) walks
    everything.
    """
    upd = RouteUpdate()
    if prefix_scope is None:
        for prefix, entry in new.unicast_routes.items():
            # identity first: the solver's cross-rebuild entry caches
            # hand back the same frozen object for unchanged routes,
            # making the steady-state diff a pointer compare instead of
            # a field-by-field dataclass equality over the nexthop tuples
            prev = old.unicast_routes.get(prefix)
            if prev is not entry and prev != entry:
                upd.unicast_to_update[prefix] = entry
        for prefix in old.unicast_routes:
            if prefix not in new.unicast_routes:
                upd.unicast_to_delete.append(prefix)
    else:
        for prefix in sorted(prefix_scope):  # sorted: deterministic delta
            entry = new.unicast_routes.get(prefix)
            if entry is None:
                if prefix in old.unicast_routes:
                    upd.unicast_to_delete.append(prefix)
                continue
            prev = old.unicast_routes.get(prefix)
            if prev is not entry and prev != entry:
                upd.unicast_to_update[prefix] = entry
    if label_scope is None:
        for label, mentry in new.mpls_routes.items():
            prev_m = old.mpls_routes.get(label)
            if prev_m is not mentry and prev_m != mentry:
                upd.mpls_to_update[label] = mentry
        for label in old.mpls_routes:
            if label not in new.mpls_routes:
                upd.mpls_to_delete.append(label)
    else:
        for label in sorted(label_scope):
            mentry = new.mpls_routes.get(label)
            if mentry is None:
                if label in old.mpls_routes:
                    upd.mpls_to_delete.append(label)
                continue
            prev_m = old.mpls_routes.get(label)
            if prev_m is not mentry and prev_m != mentry:
                upd.mpls_to_update[label] = mentry
    return upd
