"""KvStore wire types: versioned values and publications.

Equivalent of the reference's KvStore.thrift (reference: openr/if/
KvStore.thrift † — Value, Publication, KeyDumpParams, KvStorePeerSpec).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from openr_tpu.common.constants import DEFAULT_AREA
from openr_tpu.monitor.perf import HopSpan, PerfEvent, PerfEvents
from openr_tpu.types.serde import register_wire_types

# TTL sentinel: key never expires (reference: openr/common/Constants.h †
# kTtlInfinity == INT32_MIN in some versions; we use -1).
TTL_INFINITY = -1


def value_hash(version: int, originator_id: str, value: bytes | None) -> int:
    """Content hash used as the last conflict-resolution tiebreak and for
    cheap full-sync comparison (reference: openr/kvstore/KvStore.cpp †
    generateHash). 63-bit so it stays a non-negative int on any wire.
    """
    h = hashlib.blake2b(digest_size=8)
    h.update(version.to_bytes(8, "big", signed=False))
    oid = originator_id.encode()
    h.update(len(oid).to_bytes(4, "big"))  # length prefix: no (id, value)
    h.update(oid)                          # concatenation collisions
    h.update(b"\x01" if value is not None else b"\x00")  # None != b""
    if value is not None:
        h.update(value)
    return int.from_bytes(h.digest(), "big") >> 1


@dataclass
class Value:
    """A versioned KvStore value.

    reference: openr/if/KvStore.thrift † Value. `value=None` means
    "hash-only" (used in full-sync digests and ttl-refresh updates where the
    payload is omitted). ttl is milliseconds remaining (TTL_INFINITY = never
    expires); ttl_version increments on every originator refresh so refreshes
    propagate without version bumps.
    """

    version: int
    originator_id: str
    value: bytes | None = None
    ttl: int = TTL_INFINITY
    ttl_version: int = 0
    hash: int | None = None

    def with_hash(self) -> "Value":
        if self.hash is None:
            self.hash = value_hash(self.version, self.originator_id, self.value)
        return self


@dataclass
class Publication:
    """A batch of key updates flooded between stores / to subscribers.

    reference: openr/if/KvStore.thrift † Publication.
    """

    area: str = DEFAULT_AREA
    key_vals: dict[str, Value] = field(default_factory=dict)
    expired_keys: list[str] = field(default_factory=list)
    node_ids: list[str] = field(default_factory=list)  # flood loop guard
    # set on full-sync responses: keys the responder wants from the requester
    to_be_updated_keys: list[str] | None = None
    # convergence trace riding the update (reference: thrift Publication
    # carries no perf, but the flooded AdjacencyDatabase values do †;
    # publication-level here so Decision needn't decode to trace).
    # compare=False: a trace annotates the update, it doesn't identify it
    perf_events: PerfEvents | None = field(default=None, compare=False)
    # serialize-once flood fan-out: encoded wire frames, keyed by codec
    # ("bin" = serde blob, "rpc_bin" = complete kv.flood RPC frame).
    # Leading underscore = transient (serde never puts it on the wire);
    # compare/repr excluded — a cache annotates, it doesn't identify.
    # Safe to share across N peers because the coalescing paths
    # (messaging/policies.py, KvStore._enqueue_flood) always build NEW
    # Publications, so a cached frame can never go stale in place.
    _wire_cache: dict | None = field(
        default=None, compare=False, repr=False
    )


@dataclass
class KeyDumpParams:
    """Filter for dump/subscribe operations.

    reference: openr/if/KvStore.thrift † KeyDumpParams.
    """

    prefix: str = ""  # key-prefix match ("" = all)
    originator_ids: list[str] = field(default_factory=list)
    keys: list[str] = field(default_factory=list)
    ignore_ttl: bool = True


# wire-schema lock registration: the flood/full-sync frame payloads.
# The perf trio is registered HERE, not in monitor/perf.py: perf is
# imported by the types package, so it cannot import types.serde back
# (circular), and HopSpan is only reachable through the packed span_bin
# extension — never through a dataclass field hint the registry closure
# could walk.
register_wire_types(
    Value, Publication, KeyDumpParams, PerfEvent, HopSpan, PerfEvents
)
