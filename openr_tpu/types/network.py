"""Network-layer types: prefixes, nexthops, routes.

Equivalent of the reference's Network.thrift (reference: openr/if/
Network.thrift † — BinaryAddress, IpPrefix, NextHopThrift, UnicastRoute,
MplsRoute, MplsAction). Addresses are kept as strings (parsed lazily via
`ipaddress`) since the emulated dataplane is keyed by node/interface names;
the netlink platform layer converts to packed binary at the kernel boundary.
"""

from __future__ import annotations

import enum
import ipaddress
from dataclasses import dataclass
from functools import cached_property, total_ordering

from openr_tpu.types.serde import register_wire_types


class MplsActionType(enum.IntEnum):
    """reference: openr/if/Network.thrift † MplsActionCode."""

    PUSH = 0
    SWAP = 1
    PHP = 2  # penultimate hop pop
    POP_AND_LOOKUP = 3


@dataclass(frozen=True)
class MplsAction:
    action: MplsActionType
    swap_label: int | None = None
    push_labels: tuple[int, ...] = ()


@total_ordering
@dataclass(frozen=True)
class IpPrefix:
    """A v4/v6 prefix in canonical "net/len" form.

    reference: openr/if/Network.thrift † IpPrefix (BinaryAddress + len).
    """

    prefix: str  # canonical, e.g. "10.0.0.0/24" or "2001:db8::/64"

    @staticmethod
    def make(s: str) -> "IpPrefix":
        net = ipaddress.ip_network(s, strict=False)
        return IpPrefix(prefix=str(net))

    def __hash__(self):
        # the generated frozen-dataclass hash builds a field tuple per
        # call; at a million prefixes every RIB/FIB dict probe pays it,
        # and the diff walk alone does millions of probes per rebuild.
        # Cache the string hash on the instance (explicit __hash__ in
        # the class body: @dataclass keeps it).
        try:
            return self._hash
        except AttributeError:
            object.__setattr__(self, "_hash", hash(self.prefix))
            return self._hash

    @cached_property
    def network(self) -> ipaddress.IPv4Network | ipaddress.IPv6Network:
        # cached_property writes to __dict__ directly, so it works on a
        # frozen dataclass; parse happens once per instance, not per access.
        return ipaddress.ip_network(self.prefix)

    @property
    def prefix_len(self) -> int:
        return self.network.prefixlen

    @property
    def is_v4(self) -> bool:
        return self.network.version == 4

    def __str__(self) -> str:
        return self.prefix

    def __lt__(self, other: "IpPrefix") -> bool:
        return self.prefix < other.prefix


@total_ordering
@dataclass(frozen=True, slots=True)
class NextHop:
    """One nexthop of a route.

    reference: openr/if/Network.thrift † NextHopThrift. In the emulator the
    address is the neighbor node name; on a real dataplane it is the
    link-local address of the neighbor on `if_name`. `weight` is the UCMP
    weight (0 == ECMP, equal split). `mpls_action` carries SR-MPLS
    push/swap/php for KSP2 and label routes. `area` records which area the
    path goes through (for multi-area route redistribution).
    """

    address: str
    if_name: str = ""
    metric: int = 0
    weight: int = 0
    mpls_action: MplsAction | None = None
    area: str = ""
    neighbor_node: str = ""

    def _key(self):
        a = self.mpls_action
        return (
            self.address,
            self.if_name,
            self.metric,
            self.weight,
            # tuple, not str(...): this runs once per nexthop in every
            # route-canonicalization sort on the rebuild hot path
            (-1, 0, ()) if a is None else (
                int(a.action),
                a.swap_label if a.swap_label is not None else -1,
                a.push_labels,
            ),
            self.area,
            self.neighbor_node,
        )

    def __lt__(self, other: "NextHop") -> bool:
        return self._key() < other._key()


@dataclass(frozen=True)
class UnicastRoute:
    """reference: openr/if/Network.thrift † UnicastRoute."""

    dest: IpPrefix
    nexthops: tuple[NextHop, ...]


@dataclass(frozen=True)
class MplsRoute:
    """reference: openr/if/Network.thrift † MplsRoute."""

    top_label: int
    nexthops: tuple[NextHop, ...]


def sorted_nexthops(nhs) -> tuple[NextHop, ...]:
    """Canonical ordering so route equality is set-equality. Explicit
    sort key: `sorted(nhs)` would recompute _key twice per comparison
    through __lt__ (measured hot in 10k-route rebuilds)."""
    return tuple(sorted(nhs, key=NextHop._key))


# wire-schema lock registration (docs/Wire.md "Schema evolution"):
# everything below travels through the serde codecs — on flood frames
# and in the persist plane's fib/dataplane books
register_wire_types(MplsAction, IpPrefix, NextHop, UnicastRoute, MplsRoute)
