"""Wire codecs for schema dataclasses: canonical JSON + compact binary.

The reference uses fbthrift CompactProtocol for everything on the wire
(reference: openr/if/ †). This module carries BOTH codecs:

  * canonical JSON (`to_wire`/`from_wire`): sorted keys, no spaces —
    equal objects produce identical bytes, which KvStore hashes for
    conflict resolution. Value PAYLOADS (the bytes inside
    ``Value.value``) stay canonical JSON by contract: the content hash
    and Decision's byte-splice decode cache depend on it.
  * compact binary (`to_wire_bin`/`from_wire_bin`): tag-length-value
    with varint ints and RAW bytes (no base64/hex detour), positional
    dataclass fields, versioned by a leading (magic, version) pair.
    This is the TRANSPORT framing — what floods, full_syncs, Spark
    hellos and RPC envelopes travel as (docs/Wire.md).

Both codecs are schema-driven off dataclass type hints, support
nesting, lists, dicts, enums and Optionals, and are forward-compatible:
JSON ignores unknown field names; binary skips extra trailing fields
and defaults missing ones, so schema evolution is append-only (add new
dataclass fields AT THE END, with defaults). Fields whose name starts
with an underscore are transient (never on the wire in either codec).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import struct
import types
import typing
from typing import Any, Type, TypeVar, get_args, get_origin, get_type_hints

T = TypeVar("T")

_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def _hints(cls: type) -> dict[str, Any]:
    h = _HINTS_CACHE.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _HINTS_CACHE[cls] = h
    return h


_ENC_FIELDS: dict[type, tuple[str, ...]] = {}


def _enc_fields(cls: type) -> tuple[str, ...]:
    names = _ENC_FIELDS.get(cls)
    if names is None:
        # leading-underscore fields are transient (e.g. Publication's
        # encoded-frame cache) — never serialized by either codec
        names = tuple(
            f.name
            for f in dataclasses.fields(cls)
            if not f.name.startswith("_")
        )
        _ENC_FIELDS[cls] = names
    return names


def _wire_fields(cls: type):
    """Dataclass fields that travel on the wire, in declaration order
    (the binary codec's positional contract — append-only evolution)."""
    return [
        f for f in dataclasses.fields(cls) if not f.name.startswith("_")
    ]


def _encode(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, bytes):
        return {"__bytes__": obj.hex()}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            name: _encode(getattr(obj, name))
            for name in _enc_fields(type(obj))
        }
    if isinstance(obj, (list, tuple)):
        return [_encode(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): _encode(v) for k, v in obj.items()}
    raise TypeError(f"cannot encode {type(obj)!r}")


# Compiled decoders: all the reflective dispatch (get_origin/get_args/
# dataclass fields) runs ONCE per hint, producing a closure tree; the
# per-message work is plain dict/closure calls. Measured ~3x on the
# churn hot path (Decision re-parsing AdjacencyDatabases per flap).
_DECODERS: dict[Any, Any] = {}


def _identity(raw):
    """Marker decoder for pass-through fields: _build_decoder returns
    THIS object so dec_dc can skip the call entirely (identity fields
    dominate real messages — all-primitive dataclasses like Adjacency
    then decode with one dict-splat construction)."""
    return raw


def _decoder(hint: Any):
    try:
        d = _DECODERS.get(hint)
    except TypeError:  # unhashable hint — fall back to a fresh build
        return _build_decoder(hint)
    if d is None:
        d = _build_decoder(hint)
        _DECODERS[hint] = d
    return d


def _build_decoder(hint: Any):
    origin = get_origin(hint)
    if origin in (typing.Union, types.UnionType):  # Optional[X] and unions
        args = [a for a in get_args(hint) if a is not type(None)]
        if len(args) == 1:
            inner = _decoder(args[0])

            def dec_opt(raw):
                return None if raw is None else inner(raw)

            return dec_opt
        return _identity  # heterogeneous unions: pass through
    if hint is bytes:

        def dec_bytes(raw):
            if raw is None:
                return None
            if isinstance(raw, dict) and "__bytes__" in raw:
                return bytes.fromhex(raw["__bytes__"])
            raise TypeError(f"expected bytes payload, got {raw!r}")

        return dec_bytes
    if isinstance(hint, type) and issubclass(hint, enum.Enum):
        return lambda raw: None if raw is None else hint(raw)
    if dataclasses.is_dataclass(hint):
        hints = _hints(hint)
        field_decs = [
            (f.name, _decoder(hints[f.name])) for f in _wire_fields(hint)
        ]
        conv = [(n, fd) for n, fd in field_decs if fd is not _identity]
        if not conv:
            # every field decodes as-is: one dict-splat construction.
            # Unknown keys (a newer peer's extra field) TypeError out of
            # __init__ — fall back to the filtering path for those.
            known = frozenset(n for n, _fd in field_decs)

            def dec_dc_fast(raw):
                if raw is None:
                    return None
                try:
                    return hint(**raw)
                except TypeError:
                    return hint(
                        **{k: v for k, v in raw.items() if k in known}
                    )

            return dec_dc_fast

        ident = [n for n, fd in field_decs if fd is _identity]

        def dec_dc(raw):
            if raw is None:
                return None
            kwargs = {n: raw[n] for n in ident if n in raw}
            for name, fd in conv:
                if name in raw:
                    kwargs[name] = fd(raw[name])
            return hint(**kwargs)

        return dec_dc
    if origin in (list, tuple):
        args = [a for a in get_args(hint) if a is not Ellipsis] or [Any]
        if origin is tuple and len(args) > 1:  # heterogeneous tuple
            elem_decs = [_decoder(a) for a in args]

            def dec_htuple(raw):
                if raw is None:
                    return None
                return tuple(d(x) for x, d in zip(raw, elem_decs))

            return dec_htuple
        item = _decoder(args[0])
        if item is _identity:
            if origin is tuple:
                return lambda raw: None if raw is None else tuple(raw)
            return lambda raw: None if raw is None else list(raw)
        if origin is tuple:
            return lambda raw: (
                None if raw is None else tuple([item(x) for x in raw])
            )
        return lambda raw: (
            None if raw is None else [item(x) for x in raw]
        )
    if origin is dict:
        args = get_args(hint)
        key_hint, val_hint = args if args else (str, Any)
        val_dec = _decoder(val_hint)

        def dec_dict(raw):
            if raw is None:
                return None
            return {
                _decode_key(k, key_hint): val_dec(v)
                for k, v in raw.items()
            }

        return dec_dict
    return _identity


def _decode(raw: Any, hint: Any) -> Any:
    return _decoder(hint)(raw)


def _decode_key(k: str, hint: Any) -> Any:
    if hint is int:
        return int(k)
    # Frozen single-str-field dataclasses (e.g. IpPrefix) encode as str(obj);
    # reconstruct from that string so dataclass-keyed dicts round-trip. Use
    # the type's canonicalizing `make` when it has one, so a non-canonical
    # key from a peer can't create a second unequal key for the same object.
    if dataclasses.is_dataclass(hint):
        if hasattr(hint, "make"):
            return hint.make(k)
        flds = dataclasses.fields(hint)
        if len(flds) == 1:
            return hint(**{flds[0].name: k})
        raise TypeError(f"cannot decode dict key {k!r} as {hint!r}")
    return k


# Compiled encoders, symmetric with the decoders: hint-driven closure
# trees built once per type. Values come from our own schema dataclasses,
# so the type hints are trustworthy; anything surprising falls back to
# the generic reflective _encode.
_ENCODERS: dict[Any, Any] = {}


def _encoder(hint: Any):
    try:
        e = _ENCODERS.get(hint)
    except TypeError:
        return _encode
    if e is None:
        e = _build_encoder(hint)
        _ENCODERS[hint] = e
    return e


def _build_encoder(hint: Any):
    origin = get_origin(hint)
    if hint in (int, str, bool, float) or hint is Any:
        return lambda v: v
    if origin in (typing.Union, types.UnionType):
        args = [a for a in get_args(hint) if a is not type(None)]
        if len(args) == 1:
            inner = _encoder(args[0])
            return lambda v: None if v is None else inner(v)
        return _encode
    if hint is bytes:
        return lambda v: None if v is None else {"__bytes__": v.hex()}
    if isinstance(hint, type) and issubclass(hint, enum.Enum):
        return lambda v: None if v is None else v.value
    if dataclasses.is_dataclass(hint) and isinstance(hint, type):
        hints = _hints(hint)
        field_encs = [
            (f.name, _encoder(hints[f.name])) for f in _wire_fields(hint)
        ]

        def enc_dc(v):
            if v is None:
                return None
            return {name: fe(getattr(v, name)) for name, fe in field_encs}

        return enc_dc
    if origin in (list, tuple):
        args = [a for a in get_args(hint) if a is not Ellipsis] or [Any]
        if origin is tuple and len(args) > 1:
            elem_encs = [_encoder(a) for a in args]
            return lambda v: (
                None if v is None
                else [e(x) for x, e in zip(v, elem_encs)]
            )
        item = _encoder(args[0])
        return lambda v: None if v is None else [item(x) for x in v]
    if origin is dict:
        args = get_args(hint)
        val_enc = _encoder(args[1]) if args else _encode
        return lambda v: (
            None if v is None
            else {str(k): val_enc(x) for k, x in v.items()}
        )
    return _encode


def to_jsonable(obj: Any) -> Any:
    """Dataclass → plain JSON-ready dict/list tree (no string encoding).

    Use this when embedding a schema object inside a larger RPC message —
    the transport serializes once at the socket boundary instead of
    round-tripping every nested object through its own JSON string.
    """
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return _encoder(type(obj))(obj)
    return _encode(obj)


def from_jsonable(raw: Any, cls: Type[T]) -> T:
    """Inverse of to_jsonable."""
    return _decode(raw, cls)


def to_wire(obj: Any) -> bytes:
    """Serialize a schema dataclass to canonical JSON bytes.

    Canonical: sorted keys, compact separators — equal objects always
    produce identical bytes, which KvStore hashes for conflict resolution
    (reference: openr/kvstore/KvStore.cpp † mergeKeyValues hash tiebreak).
    """
    return json.dumps(
        to_jsonable(obj), sort_keys=True, separators=(",", ":")
    ).encode()


def from_wire(data: bytes | str, cls: Type[T]) -> T:
    """Deserialize canonical JSON bytes into a schema dataclass."""
    raw = json.loads(data)
    return _decode(raw, cls)


def decoder_for(cls: Type[T]):
    """The compiled raw→object decoder closure for `cls` (the same one
    `from_wire` dispatches through). Exposed for callers that decode
    many sibling objects from pre-parsed JSON and want to skip the
    per-call registry lookup — e.g. Decision's churn-path adjacency
    decode, which reuses unchanged sub-objects across versions."""
    return _decoder(cls)


# ====================================================================
# Compact binary codec (docs/Wire.md)
#
# Blob layout:   [0xB1 magic][0x01 version][value]
# Value grammar (one tag byte then payload):
#   0x00 None | 0x01 False | 0x02 True
#   0x03 int    zigzag uvarint (arbitrary precision)
#   0x04 float  8-byte IEEE754 big-endian
#   0x05 str    uvarint len + utf-8
#   0x06 bytes  uvarint len + RAW bytes (no hex/base64 detour)
#   0x07 list   uvarint n + n values          (tuples too)
#   0x08 dict   uvarint n + n × (key value)   (keys emitted as str)
#   0x09 dc     uvarint nfields + field values in declaration order
#
# Forward compat: a decoder reading a dataclass with MORE fields than
# it knows skips the extras (values are self-describing); with FEWER,
# the missing trailing fields take their dataclass defaults. Schema
# evolution is therefore append-only — new fields go at the END and
# must carry defaults.
# ====================================================================

WIRE_BIN_MAGIC = 0xB1  # cannot begin a JSON text (and is invalid UTF-8)
WIRE_BIN_VERSION = 0x01

_T_NONE = 0x00
_T_FALSE = 0x01
_T_TRUE = 0x02
_T_INT = 0x03
_T_FLOAT = 0x04
_T_STR = 0x05
_T_BYTES = 0x06
_T_LIST = 0x07
_T_DICT = 0x08
_T_DC = 0x09


class WireDecodeError(ValueError):
    """Malformed binary frame — controlled failure, callers treat it
    exactly like a JSON decode error (ValueError family)."""


def _w_uvarint(out: bytearray, n: int) -> None:
    while n > 0x7F:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)


def _r_uvarint(buf, pos: int) -> tuple[int, int]:
    n = 0
    shift = 0
    blen = len(buf)
    while True:
        if pos >= blen:
            raise WireDecodeError("truncated varint")
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 70:  # > 10 continuation bytes: corrupt, not just big
            raise WireDecodeError("varint too long")


# public alias for the frame layer (rpc/core.py length prefixes): one
# canonical varint writer on the wire, not two drifting copies
write_uvarint = _w_uvarint

_pack_f8 = struct.Struct(">d").pack
_unpack_f8 = struct.Struct(">d").unpack_from


# ---------------------------------------------------------- generic encode


def _bin_encode_any(v: Any, out: bytearray) -> None:
    """Runtime-typed encoder: used for Any-typed fields and whole RPC
    envelopes (dict/list/primitive trees with raw-bytes leaves)."""
    if v is None:
        out.append(_T_NONE)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, int):
        u = v << 1 if v >= 0 else (-v << 1) - 1
        if u >> 77:
            # the decoder's corrupt-stream guard rejects varints past
            # 11 bytes (77 payload bits) — fail at the SENDER with a
            # typed error instead of emitting a frame every receiver
            # silently drops. No schema int comes near this (hashes
            # are 63-bit); only a hand-built RPC envelope can
            raise TypeError(f"int exceeds binary wire range: {v!r}")
        out.append(_T_INT)
        _w_uvarint(out, u)
    elif isinstance(v, float):
        out.append(_T_FLOAT)
        out += _pack_f8(v)
    elif isinstance(v, str):
        b = v.encode()
        out.append(_T_STR)
        _w_uvarint(out, len(b))
        out += b
    elif isinstance(v, (bytes, bytearray, memoryview)):
        out.append(_T_BYTES)
        _w_uvarint(out, len(v))
        out += v
    elif isinstance(v, enum.Enum):
        _bin_encode_any(v.value, out)
    elif dataclasses.is_dataclass(v) and not isinstance(v, type):
        _bin_encoder(type(v))(v, out)
    elif isinstance(v, (list, tuple)):
        out.append(_T_LIST)
        _w_uvarint(out, len(v))
        for x in v:
            _bin_encode_any(x, out)
    elif isinstance(v, dict):
        out.append(_T_DICT)
        _w_uvarint(out, len(v))
        for k, x in v.items():
            ks = str(k).encode()
            out.append(_T_STR)
            _w_uvarint(out, len(ks))
            out += ks
            _bin_encode_any(x, out)
    else:
        raise TypeError(f"cannot binary-encode {type(v)!r}")


# ---------------------------------------------------------- generic decode


def _bin_decode_any(buf, pos: int) -> tuple[Any, int]:
    # hot path: tags ordered by frequency in real traffic (ints and
    # strings dominate Publication/Value trees), 1-byte varint lengths
    # inlined — this function runs once per value per flood delivery
    blen = len(buf)
    if pos >= blen:
        raise WireDecodeError("truncated value")
    tag = buf[pos]
    pos += 1
    if tag == _T_INT:
        if pos < blen and buf[pos] < 0x80:  # 1-byte varint fast path
            u = buf[pos]
            pos += 1
        else:
            u, pos = _r_uvarint(buf, pos)
        return (u >> 1) if not u & 1 else -((u + 1) >> 1), pos
    if tag == _T_STR:
        if pos < blen and buf[pos] < 0x80:
            n = buf[pos]
            pos += 1
        else:
            n, pos = _r_uvarint(buf, pos)
        if pos + n > blen:
            raise WireDecodeError("truncated str")
        try:
            return buf[pos : pos + n].decode(), pos + n
        except UnicodeDecodeError as e:
            raise WireDecodeError("bad utf-8 in str") from e
    if tag == _T_BYTES:
        if pos < blen and buf[pos] < 0x80:
            n = buf[pos]
            pos += 1
        else:
            n, pos = _r_uvarint(buf, pos)
        if pos + n > blen:
            raise WireDecodeError("truncated bytes")
        return bytes(buf[pos : pos + n]), pos + n
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_FLOAT:
        if pos + 8 > blen:
            raise WireDecodeError("truncated float")
        return _unpack_f8(buf, pos)[0], pos + 8
    if tag in (_T_LIST, _T_DC):
        n, pos = _r_uvarint(buf, pos)
        if n > len(buf) - pos:  # each element needs ≥ 1 byte
            raise WireDecodeError("oversized container count")
        items = []
        for _ in range(n):
            v, pos = _bin_decode_any(buf, pos)
            items.append(v)
        return items, pos
    if tag == _T_DICT:
        n, pos = _r_uvarint(buf, pos)
        if n > (len(buf) - pos) // 2:  # key + value ≥ 2 bytes each
            raise WireDecodeError("oversized dict count")
        d = {}
        for _ in range(n):
            k, pos = _bin_decode_any(buf, pos)
            v, pos = _bin_decode_any(buf, pos)
            d[k] = v
        return d, pos
    raise WireDecodeError(f"unknown tag 0x{tag:02x}")


def _bin_skip(buf, pos: int) -> int:
    """Skip one self-describing value (forward-compat extra fields)."""
    _, pos = _bin_decode_any(buf, pos)
    return pos


# ---------------------------------------------------------- typed encoders

_BIN_ENCODERS: dict[Any, Any] = {}


def _bin_encoder(hint: Any):
    try:
        e = _BIN_ENCODERS.get(hint)
    except TypeError:  # unhashable hint
        return _bin_encode_any
    if e is None:
        e = _build_bin_encoder(hint)
        _BIN_ENCODERS[hint] = e
    return e


def _build_bin_encoder(hint: Any):
    origin = get_origin(hint)
    if origin in (typing.Union, types.UnionType):
        args = [a for a in get_args(hint) if a is not type(None)]
        if len(args) == 1:
            inner = _bin_encoder(args[0])

            def enc_opt(v, out):
                if v is None:
                    out.append(_T_NONE)
                else:
                    inner(v, out)

            return enc_opt
        return _bin_encode_any
    if dataclasses.is_dataclass(hint) and isinstance(hint, type):
        hints = _hints(hint)
        field_encs = [
            (f.name, _bin_encoder(hints[f.name]))
            for f in _wire_fields(hint)
        ]
        nfields = len(field_encs)

        def enc_dc(v, out):
            if v is None:
                out.append(_T_NONE)
                return
            out.append(_T_DC)
            _w_uvarint(out, nfields)
            for name, fe in field_encs:
                fe(getattr(v, name), out)

        return enc_dc
    if origin in (list, tuple):
        args = [a for a in get_args(hint) if a is not Ellipsis] or [Any]
        if origin is tuple and len(args) > 1:
            elem_encs = [_bin_encoder(a) for a in args]
            arity = len(elem_encs)

            def enc_htuple(v, out):
                if v is None:
                    out.append(_T_NONE)
                    return
                out.append(_T_LIST)
                # the emitted count must match the emitted values: a
                # runtime tuple longer than the hint (the codec is as
                # lax as the JSON one about hint/value drift) encodes
                # its extras by runtime type — truncating the zip would
                # desync the count and corrupt every following field
                _w_uvarint(out, len(v))
                for i, x in enumerate(v):
                    if i < arity:
                        elem_encs[i](x, out)
                    else:
                        _bin_encode_any(x, out)

            return enc_htuple
        item = _bin_encoder(args[0])

        def enc_seq(v, out):
            if v is None:
                out.append(_T_NONE)
                return
            out.append(_T_LIST)
            _w_uvarint(out, len(v))
            for x in v:
                item(x, out)

        return enc_seq
    if origin is dict:
        args = get_args(hint)
        val_enc = _bin_encoder(args[1]) if args else _bin_encode_any

        def enc_dict(v, out):
            if v is None:
                out.append(_T_NONE)
                return
            out.append(_T_DICT)
            _w_uvarint(out, len(v))
            for k, x in v.items():
                ks = str(k).encode()
                out.append(_T_STR)
                _w_uvarint(out, len(ks))
                out += ks
                val_enc(x, out)

        return enc_dict
    # primitives / enums / Any: runtime dispatch (cheap, and as lax as
    # the JSON codec about hint-vs-value mismatches)
    return _bin_encode_any


# ---------------------------------------------------------- typed decoders

_BIN_DECODERS: dict[Any, Any] = {}


def _bin_decoder(hint: Any):
    try:
        d = _BIN_DECODERS.get(hint)
    except TypeError:
        return _bin_decode_any
    if d is None:
        d = _build_bin_decoder(hint)
        _BIN_DECODERS[hint] = d
    return d


def _build_bin_decoder(hint: Any):
    origin = get_origin(hint)
    if origin in (typing.Union, types.UnionType):
        args = [a for a in get_args(hint) if a is not type(None)]
        if len(args) == 1:
            inner = _bin_decoder(args[0])

            def dec_opt(buf, pos):
                if pos < len(buf) and buf[pos] == _T_NONE:
                    return None, pos + 1
                return inner(buf, pos)

            return dec_opt
        return _bin_decode_any
    if isinstance(hint, type) and issubclass(hint, enum.Enum):

        def dec_enum(buf, pos):
            v, pos = _bin_decode_any(buf, pos)
            if v is None:
                return None, pos
            try:
                return hint(v), pos
            except ValueError as e:
                raise WireDecodeError(f"bad enum value {v!r}") from e

        return dec_enum
    if dataclasses.is_dataclass(hint):
        hints = _hints(hint)
        field_decs = [
            (f.name, _bin_decoder(hints[f.name]))
            for f in _wire_fields(hint)
        ]
        dec_fns = [fd for _, fd in field_decs]
        nfields = len(dec_fns)
        # positional construction is measurably faster than kwargs, but
        # only valid when the wire fields are exactly the leading
        # __init__ parameters (no transient/init=False field interleaved)
        init_names = [
            f.name for f in dataclasses.fields(hint) if f.init
        ]
        positional = init_names[:nfields] == [n for n, _ in field_decs]

        def dec_dc(buf, pos):
            blen = len(buf)
            if pos >= blen:
                raise WireDecodeError("truncated dataclass")
            tag = buf[pos]
            pos += 1
            if tag == _T_NONE:
                return None, pos
            if tag != _T_DC:
                raise WireDecodeError(
                    f"expected dataclass tag, got 0x{tag:02x}"
                )
            if pos < blen and buf[pos] < 0x80:  # 1-byte count fast path
                n = buf[pos]
                pos += 1
            else:
                n, pos = _r_uvarint(buf, pos)
            if n > blen - pos:
                raise WireDecodeError("oversized field count")
            try:
                if positional:
                    args = []
                    for i in range(n):
                        if i < nfields:
                            v, pos = dec_fns[i](buf, pos)
                            args.append(v)
                        else:  # newer peer appended unknown fields
                            pos = _bin_skip(buf, pos)
                    return hint(*args), pos
                kwargs = {}
                for i in range(n):
                    if i < nfields:
                        name, fd = field_decs[i]
                        kwargs[name], pos = fd(buf, pos)
                    else:
                        pos = _bin_skip(buf, pos)
                return hint(**kwargs), pos
            except TypeError as e:  # older peer omitted a required field
                raise WireDecodeError(f"bad {hint.__name__}: {e}") from e

        return dec_dc
    if origin in (list, tuple):
        args = [a for a in get_args(hint) if a is not Ellipsis] or [Any]
        if origin is tuple and len(args) > 1:
            elem_decs = [_bin_decoder(a) for a in args]

            def dec_htuple(buf, pos):
                items, pos = _read_list_header(buf, pos)
                if items is None:
                    return None, pos
                n = items
                out = []
                for i in range(n):
                    if i < len(elem_decs):
                        v, pos = elem_decs[i](buf, pos)
                        out.append(v)
                    else:
                        pos = _bin_skip(buf, pos)
                return tuple(out), pos

            return dec_htuple
        item = _bin_decoder(args[0])
        wrap = tuple if origin is tuple else list

        def dec_seq(buf, pos):
            n, pos = _read_list_header(buf, pos)
            if n is None:
                return None, pos
            out = []
            for _ in range(n):
                v, pos = item(buf, pos)
                out.append(v)
            return wrap(out), pos

        return dec_seq
    if origin is dict:
        args = get_args(hint)
        key_hint, val_hint = args if args else (str, Any)
        val_dec = _bin_decoder(val_hint)

        str_keys = key_hint is str

        def dec_dict(buf, pos):
            blen = len(buf)
            if pos >= blen:
                raise WireDecodeError("truncated dict")
            tag = buf[pos]
            pos += 1
            if tag == _T_NONE:
                return None, pos
            if tag != _T_DICT:
                raise WireDecodeError(f"expected dict, got 0x{tag:02x}")
            n, pos = _r_uvarint(buf, pos)
            if n > (blen - pos) // 2:
                raise WireDecodeError("oversized dict count")
            d = {}
            for _ in range(n):
                # keys are emitted as str: inline the short-string
                # decode (the flood hot path walks one per key_val)
                if (
                    str_keys
                    and pos + 1 < blen
                    and buf[pos] == _T_STR
                    and buf[pos + 1] < 0x80
                ):
                    kn = buf[pos + 1]
                    kend = pos + 2 + kn
                    if kend > blen:
                        raise WireDecodeError("truncated str")
                    try:
                        k = buf[pos + 2 : kend].decode()
                    except UnicodeDecodeError as e:
                        raise WireDecodeError("bad utf-8 in str") from e
                    pos = kend
                else:
                    k, pos = _bin_decode_any(buf, pos)
                    k = _decode_key(k, key_hint)
                v, pos = val_dec(buf, pos)
                d[k] = v
            return d, pos

        return dec_dict
    # primitives / Any: self-describing (same laxness as the JSON codec)
    return _bin_decode_any


def _read_list_header(buf, pos):
    if pos >= len(buf):
        raise WireDecodeError("truncated list")
    tag = buf[pos]
    pos += 1
    if tag == _T_NONE:
        return None, pos
    if tag != _T_LIST:
        raise WireDecodeError(f"expected list, got 0x{tag:02x}")
    n, pos = _r_uvarint(buf, pos)
    if n > len(buf) - pos:
        raise WireDecodeError("oversized list count")
    return n, pos


# ------------------------------------------------------------ entry points

_BIN_HEADER = bytes((WIRE_BIN_MAGIC, WIRE_BIN_VERSION))


def to_wire_bin(obj: Any) -> bytes:
    """Serialize to the compact binary wire form (magic + version +
    TLV value). Schema dataclasses encode positionally; generic trees
    (RPC envelopes) encode by runtime type."""
    out = bytearray(_BIN_HEADER)
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        _bin_encoder(type(obj))(obj, out)
    else:
        _bin_encode_any(obj, out)
    return bytes(out)


def from_wire_bin(data: bytes, cls: Type[T] | None = None) -> T:
    """Inverse of :func:`to_wire_bin`. With `cls`, decodes through the
    compiled schema decoders; without, returns the generic value tree
    (dicts/lists/primitives/bytes — RPC envelopes). Every failure mode
    raises :class:`WireDecodeError` (a ValueError)."""
    if len(data) < 2:
        raise WireDecodeError("short frame")
    if data[0] != WIRE_BIN_MAGIC:
        raise WireDecodeError(f"bad magic 0x{data[0]:02x}")
    if data[1] != WIRE_BIN_VERSION:
        raise WireDecodeError(f"unsupported wire version {data[1]}")
    try:
        if cls is None:
            val, pos = _bin_decode_any(data, 2)
        else:
            val, pos = _bin_decoder(cls)(data, 2)
    except WireDecodeError:
        raise
    except (IndexError, struct.error, OverflowError, RecursionError,
            TypeError, ValueError) as e:
        raise WireDecodeError(f"corrupt frame: {e}") from e
    if pos != len(data):
        raise WireDecodeError(f"{len(data) - pos} trailing bytes")
    return val


def from_wire_auto(data: bytes, cls: Type[T]) -> T:
    """Codec-sniffing decode for seams that accept either framing
    during migration (Spark rx): binary frames lead with the magic
    byte, which can never begin a JSON text."""
    if data[:1] == _BIN_HEADER[:1]:
        return from_wire_bin(data, cls)
    return from_wire(data, cls)


# ------------------------------------------- schema-lock introspection hooks
#
# The wire-schema lock (docs/Wire.md "Schema evolution",
# tools/orlint/wireschema.py, orlint rule OR015) needs a ground-truth
# enumeration of every dataclass that travels through either codec plus
# a canonical rendering of each type's positional contract. Modules
# that define wire types register them at import time; the closure in
# :func:`registered_wire_types` pulls in every nested dataclass/enum a
# registered type references, so a type cannot silently escape the lock
# by being reachable-only.

_WIRE_TYPES: dict[str, type] = {}


def register_wire_types(*classes: type) -> None:
    """Declare dataclasses as lock-covered wire schema types."""
    for cls in classes:
        if not (isinstance(cls, type) and dataclasses.is_dataclass(cls)):
            raise TypeError(f"not a dataclass type: {cls!r}")
        prev = _WIRE_TYPES.get(cls.__name__)
        if prev is not None and prev is not cls:
            raise ValueError(
                f"wire type name collision: {cls.__name__} "
                f"({prev.__module__} vs {cls.__module__})"
            )
        _WIRE_TYPES[cls.__name__] = cls


def _reachable_schema_types(hint: Any) -> list[type]:
    """Dataclass / Enum classes inside a field hint, through Optional,
    union, list/tuple/dict nesting."""
    found: list[type] = []
    stack = [hint]
    seen: set[int] = set()
    while stack:
        h = stack.pop()
        if id(h) in seen:
            continue
        seen.add(id(h))
        if isinstance(h, type):
            if dataclasses.is_dataclass(h) or issubclass(h, enum.Enum):
                found.append(h)
            continue
        stack.extend(get_args(h))
    return found


def registered_wire_types() -> dict[str, type]:
    """Every registered wire type plus every dataclass/enum reachable
    through registered types' field hints, sorted by name. Reachability
    is what makes lock coverage structural: a nested type joins the
    lock the moment any registered type references it."""
    out: dict[str, type] = {}
    stack = list(_WIRE_TYPES.values())
    while stack:
        cls = stack.pop()
        if cls.__name__ in out:
            continue
        out[cls.__name__] = cls
        if dataclasses.is_dataclass(cls):
            hints = _hints(cls)
            for f in _wire_fields(cls):
                stack.extend(
                    t
                    for t in _reachable_schema_types(hints[f.name])
                    if t.__name__ not in out
                )
    return dict(sorted(out.items()))


def normalize_type_str(ann: Any) -> str:
    """Canonical rendering of a field annotation for the lock: the
    source annotation string (PEP 563 — every schema module uses
    ``from __future__ import annotations``) with whitespace and quote
    characters stripped, so formatting churn can never read as drift."""
    if not isinstance(ann, str):
        ann = getattr(ann, "__name__", None) or repr(ann)
    return ann.replace(" ", "").replace('"', "").replace("'", "")


def _default_token(f: dataclasses.Field) -> str | None:
    """Stable token for a field's default: None means REQUIRED (no
    default — appends without one are a breaking schema change)."""
    if f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
        return f"factory:{getattr(f.default_factory, '__name__', '?')}"
    if f.default is dataclasses.MISSING:
        return None
    v = f.default
    if isinstance(v, enum.Enum):
        return f"{type(v).__name__}.{v.name}"
    return repr(v)


def wire_schema_of(cls: type) -> dict:
    """Canonical schema dict of one registered type, as committed in
    ``wire_schema.lock.json``: positional field order, normalized type
    strings, default presence, transient-underscore exclusions; enums
    lock their member→value map (renumbering is wire drift too)."""
    if isinstance(cls, type) and issubclass(cls, enum.Enum):
        return {
            "kind": "enum",
            "module": cls.__module__,
            "members": {m.name: int(m.value) for m in cls},
        }
    return {
        "kind": "dataclass",
        "module": cls.__module__,
        "fields": [
            {
                "name": f.name,
                "type": normalize_type_str(f.type),
                "default": _default_token(f),
            }
            for f in _wire_fields(cls)
        ],
        "transient": [
            f.name for f in dataclasses.fields(cls) if f.name.startswith("_")
        ],
    }
