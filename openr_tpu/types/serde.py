"""Canonical JSON wire codec for schema dataclasses.

The reference uses fbthrift CompactProtocol for everything on the wire
(reference: openr/if/ †). We use canonical JSON (sorted keys, no spaces)
instead: the control plane is small-message gossip where codec speed is not
the bottleneck, and canonical bytes give us a stable content hash for
KvStore conflict resolution. The codec is schema-driven off dataclass type
hints, supports nesting, lists, dicts, enums and Optionals, and is
versioned by field name (unknown fields are ignored on decode — the same
forward-compat posture thrift gives the reference).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import types
import typing
from typing import Any, Type, TypeVar, get_args, get_origin, get_type_hints

T = TypeVar("T")

_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def _hints(cls: type) -> dict[str, Any]:
    h = _HINTS_CACHE.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _HINTS_CACHE[cls] = h
    return h


def _encode(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, bytes):
        return {"__bytes__": obj.hex()}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: _encode(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, (list, tuple)):
        return [_encode(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): _encode(v) for k, v in obj.items()}
    raise TypeError(f"cannot encode {type(obj)!r}")


def _decode(raw: Any, hint: Any) -> Any:
    if raw is None:
        return None
    origin = get_origin(hint)
    if origin in (typing.Union, types.UnionType):  # Optional[X] and unions
        args = [a for a in get_args(hint) if a is not type(None)]
        if len(args) == 1:
            return _decode(raw, args[0])
        return raw  # heterogeneous unions: pass through
    if hint is bytes:
        if isinstance(raw, dict) and "__bytes__" in raw:
            return bytes.fromhex(raw["__bytes__"])
        raise TypeError(f"expected bytes payload, got {raw!r}")
    if isinstance(hint, type) and issubclass(hint, enum.Enum):
        return hint(raw)
    if dataclasses.is_dataclass(hint):
        hints = _hints(hint)
        kwargs = {}
        for f in dataclasses.fields(hint):
            if f.name in raw:
                kwargs[f.name] = _decode(raw[f.name], hints[f.name])
        return hint(**kwargs)
    if origin in (list, tuple):
        args = [a for a in get_args(hint) if a is not Ellipsis] or [Any]
        if origin is tuple and len(args) > 1:  # heterogeneous tuple
            return tuple(_decode(x, a) for x, a in zip(raw, args))
        item_hint = args[0]
        seq = [_decode(x, item_hint) for x in raw]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        args = get_args(hint)
        key_hint, val_hint = args if args else (str, Any)
        return {
            _decode_key(k, key_hint): _decode(v, val_hint)
            for k, v in raw.items()
        }
    return raw


def _decode_key(k: str, hint: Any) -> Any:
    if hint is int:
        return int(k)
    # Frozen single-str-field dataclasses (e.g. IpPrefix) encode as str(obj);
    # reconstruct from that string so dataclass-keyed dicts round-trip. Use
    # the type's canonicalizing `make` when it has one, so a non-canonical
    # key from a peer can't create a second unequal key for the same object.
    if dataclasses.is_dataclass(hint):
        if hasattr(hint, "make"):
            return hint.make(k)
        flds = dataclasses.fields(hint)
        if len(flds) == 1:
            return hint(**{flds[0].name: k})
        raise TypeError(f"cannot decode dict key {k!r} as {hint!r}")
    return k


def to_jsonable(obj: Any) -> Any:
    """Dataclass → plain JSON-ready dict/list tree (no string encoding).

    Use this when embedding a schema object inside a larger RPC message —
    the transport serializes once at the socket boundary instead of
    round-tripping every nested object through its own JSON string.
    """
    return _encode(obj)


def from_jsonable(raw: Any, cls: Type[T]) -> T:
    """Inverse of to_jsonable."""
    return _decode(raw, cls)


def to_wire(obj: Any) -> bytes:
    """Serialize a schema dataclass to canonical JSON bytes.

    Canonical: sorted keys, compact separators — equal objects always
    produce identical bytes, which KvStore hashes for conflict resolution
    (reference: openr/kvstore/KvStore.cpp † mergeKeyValues hash tiebreak).
    """
    return json.dumps(
        _encode(obj), sort_keys=True, separators=(",", ":")
    ).encode()


def from_wire(data: bytes | str, cls: Type[T]) -> T:
    """Deserialize canonical JSON bytes into a schema dataclass."""
    raw = json.loads(data)
    return _decode(raw, cls)
