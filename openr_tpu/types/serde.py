"""Canonical JSON wire codec for schema dataclasses.

The reference uses fbthrift CompactProtocol for everything on the wire
(reference: openr/if/ †). We use canonical JSON (sorted keys, no spaces)
instead: the control plane is small-message gossip where codec speed is not
the bottleneck, and canonical bytes give us a stable content hash for
KvStore conflict resolution. The codec is schema-driven off dataclass type
hints, supports nesting, lists, dicts, enums and Optionals, and is
versioned by field name (unknown fields are ignored on decode — the same
forward-compat posture thrift gives the reference).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import types
import typing
from typing import Any, Type, TypeVar, get_args, get_origin, get_type_hints

T = TypeVar("T")

_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def _hints(cls: type) -> dict[str, Any]:
    h = _HINTS_CACHE.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _HINTS_CACHE[cls] = h
    return h


_ENC_FIELDS: dict[type, tuple[str, ...]] = {}


def _enc_fields(cls: type) -> tuple[str, ...]:
    names = _ENC_FIELDS.get(cls)
    if names is None:
        names = tuple(f.name for f in dataclasses.fields(cls))
        _ENC_FIELDS[cls] = names
    return names


def _encode(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, bytes):
        return {"__bytes__": obj.hex()}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            name: _encode(getattr(obj, name))
            for name in _enc_fields(type(obj))
        }
    if isinstance(obj, (list, tuple)):
        return [_encode(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): _encode(v) for k, v in obj.items()}
    raise TypeError(f"cannot encode {type(obj)!r}")


# Compiled decoders: all the reflective dispatch (get_origin/get_args/
# dataclass fields) runs ONCE per hint, producing a closure tree; the
# per-message work is plain dict/closure calls. Measured ~3x on the
# churn hot path (Decision re-parsing AdjacencyDatabases per flap).
_DECODERS: dict[Any, Any] = {}


def _identity(raw):
    """Marker decoder for pass-through fields: _build_decoder returns
    THIS object so dec_dc can skip the call entirely (identity fields
    dominate real messages — all-primitive dataclasses like Adjacency
    then decode with one dict-splat construction)."""
    return raw


def _decoder(hint: Any):
    try:
        d = _DECODERS.get(hint)
    except TypeError:  # unhashable hint — fall back to a fresh build
        return _build_decoder(hint)
    if d is None:
        d = _build_decoder(hint)
        _DECODERS[hint] = d
    return d


def _build_decoder(hint: Any):
    origin = get_origin(hint)
    if origin in (typing.Union, types.UnionType):  # Optional[X] and unions
        args = [a for a in get_args(hint) if a is not type(None)]
        if len(args) == 1:
            inner = _decoder(args[0])

            def dec_opt(raw):
                return None if raw is None else inner(raw)

            return dec_opt
        return _identity  # heterogeneous unions: pass through
    if hint is bytes:

        def dec_bytes(raw):
            if raw is None:
                return None
            if isinstance(raw, dict) and "__bytes__" in raw:
                return bytes.fromhex(raw["__bytes__"])
            raise TypeError(f"expected bytes payload, got {raw!r}")

        return dec_bytes
    if isinstance(hint, type) and issubclass(hint, enum.Enum):
        return lambda raw: None if raw is None else hint(raw)
    if dataclasses.is_dataclass(hint):
        hints = _hints(hint)
        field_decs = [
            (f.name, _decoder(hints[f.name]))
            for f in dataclasses.fields(hint)
        ]
        conv = [(n, fd) for n, fd in field_decs if fd is not _identity]
        if not conv:
            # every field decodes as-is: one dict-splat construction.
            # Unknown keys (a newer peer's extra field) TypeError out of
            # __init__ — fall back to the filtering path for those.
            known = frozenset(n for n, _fd in field_decs)

            def dec_dc_fast(raw):
                if raw is None:
                    return None
                try:
                    return hint(**raw)
                except TypeError:
                    return hint(
                        **{k: v for k, v in raw.items() if k in known}
                    )

            return dec_dc_fast

        ident = [n for n, fd in field_decs if fd is _identity]

        def dec_dc(raw):
            if raw is None:
                return None
            kwargs = {n: raw[n] for n in ident if n in raw}
            for name, fd in conv:
                if name in raw:
                    kwargs[name] = fd(raw[name])
            return hint(**kwargs)

        return dec_dc
    if origin in (list, tuple):
        args = [a for a in get_args(hint) if a is not Ellipsis] or [Any]
        if origin is tuple and len(args) > 1:  # heterogeneous tuple
            elem_decs = [_decoder(a) for a in args]

            def dec_htuple(raw):
                if raw is None:
                    return None
                return tuple(d(x) for x, d in zip(raw, elem_decs))

            return dec_htuple
        item = _decoder(args[0])
        if item is _identity:
            if origin is tuple:
                return lambda raw: None if raw is None else tuple(raw)
            return lambda raw: None if raw is None else list(raw)
        if origin is tuple:
            return lambda raw: (
                None if raw is None else tuple([item(x) for x in raw])
            )
        return lambda raw: (
            None if raw is None else [item(x) for x in raw]
        )
    if origin is dict:
        args = get_args(hint)
        key_hint, val_hint = args if args else (str, Any)
        val_dec = _decoder(val_hint)

        def dec_dict(raw):
            if raw is None:
                return None
            return {
                _decode_key(k, key_hint): val_dec(v)
                for k, v in raw.items()
            }

        return dec_dict
    return _identity


def _decode(raw: Any, hint: Any) -> Any:
    return _decoder(hint)(raw)


def _decode_key(k: str, hint: Any) -> Any:
    if hint is int:
        return int(k)
    # Frozen single-str-field dataclasses (e.g. IpPrefix) encode as str(obj);
    # reconstruct from that string so dataclass-keyed dicts round-trip. Use
    # the type's canonicalizing `make` when it has one, so a non-canonical
    # key from a peer can't create a second unequal key for the same object.
    if dataclasses.is_dataclass(hint):
        if hasattr(hint, "make"):
            return hint.make(k)
        flds = dataclasses.fields(hint)
        if len(flds) == 1:
            return hint(**{flds[0].name: k})
        raise TypeError(f"cannot decode dict key {k!r} as {hint!r}")
    return k


# Compiled encoders, symmetric with the decoders: hint-driven closure
# trees built once per type. Values come from our own schema dataclasses,
# so the type hints are trustworthy; anything surprising falls back to
# the generic reflective _encode.
_ENCODERS: dict[Any, Any] = {}


def _encoder(hint: Any):
    try:
        e = _ENCODERS.get(hint)
    except TypeError:
        return _encode
    if e is None:
        e = _build_encoder(hint)
        _ENCODERS[hint] = e
    return e


def _build_encoder(hint: Any):
    origin = get_origin(hint)
    if hint in (int, str, bool, float) or hint is Any:
        return lambda v: v
    if origin in (typing.Union, types.UnionType):
        args = [a for a in get_args(hint) if a is not type(None)]
        if len(args) == 1:
            inner = _encoder(args[0])
            return lambda v: None if v is None else inner(v)
        return _encode
    if hint is bytes:
        return lambda v: None if v is None else {"__bytes__": v.hex()}
    if isinstance(hint, type) and issubclass(hint, enum.Enum):
        return lambda v: None if v is None else v.value
    if dataclasses.is_dataclass(hint) and isinstance(hint, type):
        hints = _hints(hint)
        field_encs = [
            (f.name, _encoder(hints[f.name]))
            for f in dataclasses.fields(hint)
        ]

        def enc_dc(v):
            if v is None:
                return None
            return {name: fe(getattr(v, name)) for name, fe in field_encs}

        return enc_dc
    if origin in (list, tuple):
        args = [a for a in get_args(hint) if a is not Ellipsis] or [Any]
        if origin is tuple and len(args) > 1:
            elem_encs = [_encoder(a) for a in args]
            return lambda v: (
                None if v is None
                else [e(x) for x, e in zip(v, elem_encs)]
            )
        item = _encoder(args[0])
        return lambda v: None if v is None else [item(x) for x in v]
    if origin is dict:
        args = get_args(hint)
        val_enc = _encoder(args[1]) if args else _encode
        return lambda v: (
            None if v is None
            else {str(k): val_enc(x) for k, x in v.items()}
        )
    return _encode


def to_jsonable(obj: Any) -> Any:
    """Dataclass → plain JSON-ready dict/list tree (no string encoding).

    Use this when embedding a schema object inside a larger RPC message —
    the transport serializes once at the socket boundary instead of
    round-tripping every nested object through its own JSON string.
    """
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return _encoder(type(obj))(obj)
    return _encode(obj)


def from_jsonable(raw: Any, cls: Type[T]) -> T:
    """Inverse of to_jsonable."""
    return _decode(raw, cls)


def to_wire(obj: Any) -> bytes:
    """Serialize a schema dataclass to canonical JSON bytes.

    Canonical: sorted keys, compact separators — equal objects always
    produce identical bytes, which KvStore hashes for conflict resolution
    (reference: openr/kvstore/KvStore.cpp † mergeKeyValues hash tiebreak).
    """
    return json.dumps(
        to_jsonable(obj), sort_keys=True, separators=(",", ":")
    ).encode()


def from_wire(data: bytes | str, cls: Type[T]) -> T:
    """Deserialize canonical JSON bytes into a schema dataclass."""
    raw = json.loads(data)
    return _decode(raw, cls)


def decoder_for(cls: Type[T]):
    """The compiled raw→object decoder closure for `cls` (the same one
    `from_wire` dispatches through). Exposed for callers that decode
    many sibling objects from pre-parsed JSON and want to skip the
    per-call registry lookup — e.g. Decision's churn-path adjacency
    decode, which reuses unchanged sub-objects across versions."""
    return _decoder(cls)
