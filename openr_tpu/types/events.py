"""Inter-module event types (reference: openr/if/Types.thrift † neighbor/
interface event structs + openr/spark/Spark.h † NeighborEvent)."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from openr_tpu.monitor.perf import PerfEvents
from openr_tpu.types.serde import register_wire_types


class NeighborEventType(enum.IntEnum):
    """reference: NeighborEventType in Types.thrift †."""

    NEIGHBOR_UP = 0
    NEIGHBOR_DOWN = 1
    NEIGHBOR_RESTARTING = 2
    NEIGHBOR_RESTARTED = 3
    NEIGHBOR_RTT_CHANGE = 4


@dataclass(frozen=True)
class NeighborInfo:
    """Everything LinkMonitor needs to build an adjacency + KvStore peer.

    reference: SparkNeighbor fields surfaced in NeighborEvent †."""

    node_name: str
    local_if: str
    remote_if: str = ""
    area: str = "0"
    kvstore_port: int = 0
    ctrl_port: int = 0
    hold_time_ms: int = 0
    gr_time_ms: int = 0
    rtt_us: int = 0
    label: int = 0
    # transport endpoint for kvstore peering (host for TCP; node name for
    # in-proc transports)
    endpoint_host: str = ""


@dataclass(frozen=True)
class NeighborEvent:
    type: NeighborEventType
    info: NeighborInfo
    # convergence trace carried along the pipeline (reference: the
    # thrift event structs carry optional PerfEvents †); excluded from
    # eq/hash — a trace annotates the event, it doesn't identify it
    perf_events: PerfEvents | None = field(
        default=None, compare=False
    )


@dataclass(frozen=True)
class InterfaceInfo:
    """reference: InterfaceEntry / netlink link state †."""

    name: str
    is_up: bool = True
    ifindex: int = 0
    addrs: tuple[str, ...] = ()


@dataclass
class InterfaceEvent:
    interfaces: list[InterfaceInfo] = field(default_factory=list)


# wire-schema lock registration: neighbor/interface events cross the
# module pipeline and ride ctrl RPC payloads
register_wire_types(NeighborInfo, NeighborEvent, InterfaceInfo, InterfaceEvent)
