"""Typed schemas — the equivalent of the reference's Thrift IDL layer.

Mirrors (in spirit, not wire format) the upstream thrift files
(reference: openr/if/Types.thrift †, KvStore.thrift †, Network.thrift †,
OpenrCtrl.thrift †). All types are plain dataclasses with a canonical JSON
wire codec (`to_wire` / `from_wire`) used by KvStore values, RPC, and the
persistent store. Integer metrics end-to-end (never float) so that RIB
equivalence with the oracle solver is exact.
"""

from openr_tpu.types.network import (  # noqa: F401
    IpPrefix,
    MplsAction,
    MplsActionType,
    MplsRoute,
    NextHop,
    UnicastRoute,
)
from openr_tpu.types.topology import (  # noqa: F401
    Adjacency,
    AdjacencyDatabase,
    ForwardingAlgorithm,
    ForwardingType,
    PrefixDatabase,
    PrefixEntry,
    PrefixMetrics,
)
from openr_tpu.types.kvstore import (  # noqa: F401
    KeyDumpParams,
    Publication,
    Value,
)
from openr_tpu.types.routes import (  # noqa: F401
    RibEntry,
    RibMplsEntry,
    RouteDatabase,
    RouteUpdate,
)
from openr_tpu.types.serde import (  # noqa: F401
    from_wire,
    to_wire,
)
