# orlint: disable-file=OR011 (lock JSON is a dev artifact, not wire)
"""Wire-schema lock: extraction, drift classification, golden frames.

The TLV codec's evolution contract (serde module docstring: append-only,
trailing defaults, transient underscores) is load-bearing for live
mixed-version interop AND for crash recovery — journals and snapshots
persist the same frames. This module is the runtime half of the lock
that makes the contract enforceable:

  * :func:`extract_schema` renders the CURRENT source tree's schema —
    every serde-registered dataclass/enum (``serde.register_wire_types``
    closure) plus the RPC method/notification/stream name surface
    scraped from ``rpc/``, ``ctrl/`` and ``kvstore/``.
  * ``wire_schema.lock.json`` (next to this file) is the COMMITTED
    schema. :func:`diff_schemas` classifies extracted-vs-lock drift as
    breaking (reorder / removal / rename / retype / default change /
    un-defaulted append / enum renumber / RPC removal) or benign
    (defaulted trailing append, new type, new RPC name) — the legal /
    illegal table in docs/Wire.md "Schema evolution".
  * :func:`build_sample` / :func:`golden_frame` mint the deterministic
    per-type fixture frames under ``tests/fixtures/wire/golden/`` that
    turn the lock into an executable decode-forever contract, and the
    raw-frame helpers below it power the schema-driven fuzzer
    (tests/test_wire_schema.py) — mutations are derived from the lock's
    own type strings, so a newly locked type is fuzzed for free.

Consumers: ``tools/orlint/wireschema.py`` (CLI: check / write /
goldens), orlint rule OR015 (lint-time breaking-drift findings),
``breeze wire schema`` (operator dump+diff), ctrl ``get_wire_schema``.
"""

from __future__ import annotations

import ast
import dataclasses
import enum
import hashlib
import importlib
import json
import pathlib
import re
from typing import Any, get_args, get_origin

from openr_tpu.types import serde

LOCK_FILENAME = "wire_schema.lock.json"
LOCK_PATH = pathlib.Path(__file__).resolve().parent / LOCK_FILENAME

#: every module that registers wire types — imported before extraction
#: so the registry is complete no matter who asks first
WIRE_MODULES = (
    "openr_tpu.types.network",
    "openr_tpu.types.topology",
    "openr_tpu.types.kvstore",  # also registers the monitor.perf trio
    "openr_tpu.types.routes",
    "openr_tpu.types.events",
    "openr_tpu.spark.spark",
    "openr_tpu.persist.journal",
    "openr_tpu.prefixmgr.ranges",
)

#: files whose ``.register`` / ``.notify`` / ``.call`` literals define
#: the RPC name surface (server registrations + peer-facing sends)
RPC_SCAN_FILES = (
    "rpc/core.py",
    "ctrl/server.py",
    "kvstore/kvstore.py",
    "kvstore/transport.py",
)

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_.]*$")


# ------------------------------------------------------------- extraction


def extract_schema() -> dict:
    """Schema of the source tree as currently importable: the lock's
    ``types`` + ``rpc`` sections, freshly rendered."""
    for mod in WIRE_MODULES:
        importlib.import_module(mod)
    return {
        "types": {
            name: serde.wire_schema_of(cls)
            for name, cls in serde.registered_wire_types().items()
        },
        "rpc": extract_rpc_surface(),
    }


def extract_rpc_surface() -> dict:
    """AST-scrape the RPC name surface: method names from ``register``
    / ``call`` literals and the ctrl ``_register_all`` tuple, stream
    names from ``register_stream``, notification names from ``notify``.
    Renaming or dropping any of these strands a version-skewed peer the
    same way a field reorder does, so they are locked alongside types."""
    import openr_tpu

    pkg = pathlib.Path(openr_tpu.__file__).resolve().parent
    methods: set[str] = set()
    notifications: set[str] = set()
    streams: set[str] = set()

    def lit(call: ast.Call) -> str | None:
        if (
            call.args
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)
        ):
            return call.args[0].value
        return None

    for rel in RPC_SCAN_FILES:
        tree = ast.parse((pkg / rel).read_text())
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                name = lit(node)
                if name is None:
                    continue
                if node.func.attr in ("register", "call"):
                    methods.add(name)
                elif node.func.attr == "notify":
                    notifications.add(name)
                elif node.func.attr == "register_stream":
                    streams.add(name)
            elif (
                isinstance(node, ast.FunctionDef)
                and node.name == "_register_all"
            ):
                # ctrl registers through a name tuple + getattr; scoop
                # every identifier-shaped string constant in the body
                # (docstrings contain spaces and drop out)
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.Constant)
                        and isinstance(sub.value, str)
                        and _NAME_RE.match(sub.value)
                    ):
                        methods.add(sub.value)
    methods -= streams
    return {
        "methods": sorted(methods),
        "notifications": sorted(notifications),
        "streams": sorted(streams),
    }


# ---------------------------------------------------------------- lock IO


def load_lock(path: pathlib.Path | None = None) -> dict | None:
    p = path or LOCK_PATH
    try:
        return json.loads(p.read_text())
    except FileNotFoundError:
        return None


_VERSION_CACHE: list = []


def locked_version() -> int | None:
    """lock_version of the committed lock, read once per process —
    cheap enough to stamp as a gauge on every Node construction and
    print from ``breeze version``. None only when the lock is missing
    (a source checkout mid-surgery)."""
    if not _VERSION_CACHE:
        lock = load_lock()
        _VERSION_CACHE.append(
            None if lock is None else lock["lock_version"]
        )
    return _VERSION_CACHE[0]


def render_lock(extracted: dict, lock_version: int, changelog: list) -> str:
    """Canonical lock text: sorted keys, 2-space indent, trailing
    newline — byte-stable so ci.sh can literally ``diff`` it."""
    doc = {
        "lock_version": lock_version,
        "changelog": changelog,
        "types": extracted["types"],
        "rpc": extracted["rpc"],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


# ---------------------------------------------------- drift classification


@dataclasses.dataclass(frozen=True)
class Drift:
    """One extracted-vs-lock divergence. ``breaking`` is the OR015 /
    bump-required verdict; benign drift only means the lock text is
    stale (regenerate, no version bump)."""

    kind: str
    breaking: bool
    subject: str  # "Value", "Value.ttl", "rpc:get_counters"
    detail: str

    def __str__(self) -> str:
        sev = "BREAKING" if self.breaking else "benign"
        return f"[{sev}] {self.kind}: {self.subject} — {self.detail}"


def _diff_dataclass(name: str, lock_t: dict, ext_t: dict) -> list[Drift]:
    out: list[Drift] = []
    lf = lock_t.get("fields", [])
    ef = ext_t.get("fields", [])
    lnames = [f["name"] for f in lf]
    enames = [f["name"] for f in ef]
    if enames[: len(lnames)] == lnames:
        # positional prefix intact: only type/default/append questions
        for a, b in zip(lf, ef):
            if a.get("type") != b.get("type"):
                out.append(Drift(
                    "field-retyped", True, f"{name}.{a['name']}",
                    f"locked type {a.get('type')!r} is now "
                    f"{b.get('type')!r}",
                ))
            if a.get("default") != b.get("default"):
                out.append(Drift(
                    "default-changed", True, f"{name}.{a['name']}",
                    f"locked default {a.get('default')!r} is now "
                    f"{b.get('default')!r} (old frames omitting the "
                    f"field decode to a different value)",
                ))
        for b in ef[len(lnames):]:
            if b.get("default") is None:
                out.append(Drift(
                    "append-no-default", True, f"{name}.{b['name']}",
                    "appended field has no default — frames from "
                    "locked-schema peers cannot decode",
                ))
            else:
                out.append(Drift(
                    "field-appended", False, f"{name}.{b['name']}",
                    "legal defaulted trailing append — regenerate the "
                    "lock (no version bump needed)",
                ))
    else:
        eset = set(enames)
        removed = [n for n in lnames if n not in eset]
        for n in removed:
            out.append(Drift(
                "field-removed", True, f"{name}.{n}",
                "locked wire field removed or renamed — every peer and "
                "journal frame shifts positionally",
            ))
        if not removed:
            out.append(Drift(
                "field-reordered", True, name,
                f"locked order {lnames} vs extracted "
                f"{enames[: len(lnames)]} (positional codec: reorders "
                f"and mid-inserts silently mis-decode old frames)",
            ))
    lt = lock_t.get("transient", [])
    et = ext_t.get("transient", [])
    if sorted(lt) != sorted(et):
        out.append(Drift(
            "transient-changed", False, name,
            f"transient exclusions {lt} -> {et} (never on the wire; "
            f"regenerate the lock)",
        ))
    return out


def _diff_enum(name: str, lock_t: dict, ext_t: dict) -> list[Drift]:
    out: list[Drift] = []
    lm = lock_t.get("members", {})
    em = ext_t.get("members", {})
    for m, v in lm.items():
        if m not in em:
            out.append(Drift(
                "enum-member-removed", True, f"{name}.{m}",
                "locked enum member removed — its wire value decodes as "
                "WireDecodeError on new nodes",
            ))
        elif em[m] != v:
            out.append(Drift(
                "enum-member-renumbered", True, f"{name}.{m}",
                f"locked value {v} is now {em[m]} — old frames decode "
                f"to the WRONG member",
            ))
    for m in em:
        if m not in lm:
            out.append(Drift(
                "enum-member-added", False, f"{name}.{m}",
                "new enum member (old peers reject its value as "
                "WireDecodeError — legal; regenerate the lock)",
            ))
    return out


def diff_schemas(lock_doc: dict, extracted: dict) -> list[Drift]:
    """All divergences between a committed lock and a fresh extraction,
    breaking and benign. An empty list means lock and source agree."""
    out: list[Drift] = []
    lock_types = lock_doc.get("types", {})
    ext_types = extracted.get("types", {})
    for name, lock_t in sorted(lock_types.items()):
        ext_t = ext_types.get(name)
        if ext_t is None:
            out.append(Drift(
                "type-removed", True, name,
                "locked wire type no longer registered/reachable",
            ))
            continue
        if lock_t.get("kind") != ext_t.get("kind"):
            out.append(Drift(
                "kind-changed", True, name,
                f"{lock_t.get('kind')} became {ext_t.get('kind')}",
            ))
        elif lock_t.get("kind") == "enum":
            out.extend(_diff_enum(name, lock_t, ext_t))
        else:
            out.extend(_diff_dataclass(name, lock_t, ext_t))
        if lock_t.get("module") != ext_t.get("module"):
            out.append(Drift(
                "type-moved", False, name,
                f"{lock_t.get('module')} -> {ext_t.get('module')} "
                f"(modules never travel on the wire; regenerate)",
            ))
    for name in sorted(set(ext_types) - set(lock_types)):
        out.append(Drift(
            "type-added", False, name,
            "serde-registered type missing from the lock — regenerate "
            "(completeness: 100% of registered types must be locked)",
        ))
    lock_rpc = lock_doc.get("rpc", {})
    ext_rpc = extracted.get("rpc", {})
    for sect in ("methods", "notifications", "streams"):
        ls, es = set(lock_rpc.get(sect, [])), set(ext_rpc.get(sect, []))
        for n in sorted(ls - es):
            out.append(Drift(
                f"rpc-{sect[:-1]}-removed", True, f"rpc:{n}",
                "locked RPC name no longer served/sent — version-skewed "
                "peers calling it get method-not-found",
            ))
        for n in sorted(es - ls):
            out.append(Drift(
                f"rpc-{sect[:-1]}-added", False, f"rpc:{n}",
                "new RPC name (legal — regenerate the lock)",
            ))
    return out


def classify(drifts: list[Drift]) -> tuple[list[Drift], list[Drift]]:
    """Split into (breaking, benign)."""
    return (
        [d for d in drifts if d.breaking],
        [d for d in drifts if not d.breaking],
    )


# ------------------------------------------------- deterministic samples


def _stable_int(path: str) -> int:
    """Seedless determinism: content-addressed small ints (sha256, not
    hash() — PYTHONHASHSEED must not leak into committed fixtures)."""
    return int.from_bytes(
        hashlib.sha256(path.encode()).digest()[:2], "big"
    ) % 97 + 3


def _sample_value(hint: Any, path: str) -> Any:
    origin = get_origin(hint)
    if origin is not None and origin not in (list, tuple, dict):
        # Optional[X] / unions: exercise the first concrete arm
        args = [a for a in get_args(hint) if a is not type(None)]
        if args:
            return _sample_value(args[0], path)
        return None
    if hint is bool:
        return True
    if hint is int:
        return _stable_int(path)
    if hint is float:
        return _stable_int(path) / 8.0  # /8: exact in binary, f8be-stable
    if hint is str:
        leaf = path.rsplit(".", 1)[-1]
        return f"{leaf}-{_stable_int(path) % 10}"
    if hint is bytes:
        leaf = path.rsplit(".", 1)[-1]
        return leaf.encode() + bytes([_stable_int(path) % 256])
    if isinstance(hint, type) and issubclass(hint, enum.Enum):
        return next(iter(hint))
    if isinstance(hint, type) and dataclasses.is_dataclass(hint):
        return build_sample(hint)
    if origin in (list, tuple):
        args = [a for a in get_args(hint) if a is not Ellipsis]
        if origin is tuple and len(args) > 1:  # heterogeneous tuple
            return tuple(
                _sample_value(a, f"{path}.{i}") for i, a in enumerate(args)
            )
        inner = args[0] if args else None
        if inner is None:
            return () if origin is tuple else []
        v = [_sample_value(inner, f"{path}.item")]
        return tuple(v) if origin is tuple else v
    if origin is dict:
        args = get_args(hint)
        if not args:
            return {}
        k, vt = args
        return {
            _sample_value(k, f"{path}.key"): _sample_value(vt, f"{path}.val")
        }
    if hint is list:
        return []  # untyped list (e.g. RouteUpdate.perf_events): decodes
        # generically, so goldens keep it empty for byte-stable roundtrips
    if hint is dict:
        return {}
    return _stable_int(path)


def build_sample(cls: type) -> Any:
    """Deterministic, byte-stable-encoding instance of a locked type.
    Optional fields are populated (exercise the payload, not the None
    arm); types with construction invariants get canonical overrides."""
    if cls.__name__ == "IpPrefix":
        return cls(prefix="10.32.0.0/24")  # canonical: dict-key roundtrip
    if cls.__name__ == "PrefixRange":
        return cls(base="10.64.0.0", plen=24, count=2)  # aligned base
    hints = serde._hints(cls)
    kwargs = {
        f.name: _sample_value(hints[f.name], f"{cls.__name__}.{f.name}")
        for f in serde._wire_fields(cls)
    }
    return cls(**kwargs)


def golden_frame(cls: type) -> bytes:
    """The committed fixture frame for one locked dataclass type."""
    return serde.to_wire_bin(build_sample(cls))


# ----------------------------------------------- schema-driven mutations
#
# Raw-frame helpers for the fuzzer: operate on the lock's own field
# counts / type strings, never on the dataclasses, so coverage follows
# the lock automatically.

_DC_TAG = 0x09  # serde._T_DC: positional dataclass frame


def build_raw_frame(values: list) -> bytes:
    """Hand-rolled top-level dataclass frame: header + DC tag + count +
    generically-encoded field values (what a peer with a DIFFERENT
    schema would send)."""
    out = bytearray(serde._BIN_HEADER)
    out.append(_DC_TAG)
    serde._w_uvarint(out, len(values))
    for v in values:
        serde._bin_encode_any(v, out)
    return bytes(out)


def field_spans(frame: bytes) -> list[tuple[int, int]]:
    """(start, end) byte span of each top-level field of a DC frame."""
    if len(frame) < 3 or frame[2] != _DC_TAG:
        raise ValueError("not a top-level dataclass frame")
    n, pos = serde._r_uvarint(frame, 3)
    spans = []
    for _ in range(n):
        end = serde._bin_skip(frame, pos)
        spans.append((pos, end))
        pos = end
    return spans


def append_unknown_field(frame: bytes, extra: Any) -> bytes:
    """A newer peer's frame: same fields plus one appended unknown —
    MUST decode (the forward-compat half of the contract)."""
    if len(frame) < 3 or frame[2] != _DC_TAG:
        raise ValueError("not a top-level dataclass frame")
    n, pos = serde._r_uvarint(frame, 3)
    out = bytearray(frame[:3])
    serde._w_uvarint(out, n + 1)
    out += frame[pos:]
    serde._bin_encode_any(extra, out)
    return bytes(out)


def swap_fields(frame: bytes, i: int, j: int) -> bytes:
    """Reordered-TLV mutation: exchange two field payloads in place."""
    spans = field_spans(frame)
    (a0, a1), (b0, b1) = sorted([spans[i], spans[j]])
    return (
        frame[:a0] + frame[b0:b1] + frame[a1:b0] + frame[a0:a1] + frame[b1:]
    )


def _split_top(s: str, sep: str) -> list[str]:
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        if ch == sep and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def sample_for_type_str(ts: str, registry: dict[str, type]) -> Any:
    """A well-typed generic value for one lock type string — the fuzzer
    builds whole frames from these without touching the dataclasses."""
    arms = [a for a in _split_top(ts, "|") if a and a != "None"]
    if not arms:
        return None
    ts = arms[0]
    if ts.endswith("]"):
        head, inner = ts.split("[", 1)
        args = _split_top(inner[:-1], ",")
        if head in ("list", "set", "frozenset"):
            return [sample_for_type_str(args[0], registry)]
        if head == "tuple":
            args = [a for a in args if a != "..."]
            return tuple(sample_for_type_str(a, registry) for a in args)
        if head == "dict":
            return {
                sample_for_type_str(args[0], registry):
                    sample_for_type_str(args[1], registry)
            }
        return [1]
    prim = {
        "int": 5, "str": "s", "bytes": b"s", "bool": True,
        "float": 1.5, "list": [], "dict": {}, "Any": 1,
    }
    if ts in prim:
        return prim[ts]
    cls = registry.get(ts)
    if cls is not None:
        if issubclass(cls, enum.Enum):
            return int(next(iter(cls)).value)
        return build_sample(cls)
    return 1


def wrong_value_for_type_str(ts: str) -> Any:
    """A value from a DIFFERENT TLV family than the locked type — the
    field-type-swap mutation (a mis-evolved peer)."""
    arms = [a for a in _split_top(ts, "|") if a and a != "None"]
    head = (arms[0].split("[", 1)[0]) if arms else "None"
    if head in ("int", "bool", "float"):
        return "type-swapped"
    return 20071  # strs/bytes/lists/dicts/dataclasses/enums get an int
