"""Link-state topology types: adjacencies and prefix advertisements.

Equivalent of the reference's Types.thrift core structs
(reference: openr/if/Types.thrift † — Adjacency, AdjacencyDatabase,
PrefixEntry, PrefixMetrics, PrefixDatabase). These are the payloads of the
`adj:<node>` and `prefix:<node>:<area>:[<prefix>]` KvStore keys (see
constants.prefix_key) and the sole inputs to Decision's LSDB.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

from openr_tpu.common.constants import DEFAULT_AREA
from openr_tpu.types.network import IpPrefix
from openr_tpu.types.serde import register_wire_types


class ForwardingType(enum.IntEnum):
    """How packets to this prefix are forwarded.

    reference: openr/if/Types.thrift † PrefixForwardingType.
    """

    IP = 0
    SR_MPLS = 1


class ForwardingAlgorithm(enum.IntEnum):
    """Which path algorithm Decision uses for this prefix.

    reference: openr/if/Types.thrift † PrefixForwardingAlgorithm.
    """

    SP_ECMP = 0
    KSP2_ED_ECMP = 1  # 2 edge-disjoint shortest paths (SR-MPLS)


@dataclass(frozen=True)
class Adjacency:
    """One directed adjacency (this node → other node over if_name).

    reference: openr/if/Types.thrift † Adjacency. Integer metric (hop count
    or RTT-derived) — never float, so path costs are exact. `weight` feeds
    UCMP; `adj_label` is the SR adjacency segment.
    """

    other_node_name: str
    if_name: str
    metric: int = 1
    adj_label: int = 0
    is_overloaded: bool = False  # drain: don't transit this link
    rtt_us: int = 0
    weight: int = 1
    other_if_name: str = ""


@dataclass(frozen=True)
class AdjacencyDatabase:
    """All adjacencies of one node in one area — the `adj:<node>` value.

    reference: openr/if/Types.thrift † AdjacencyDatabase.
    """

    this_node_name: str
    adjacencies: tuple[Adjacency, ...] = ()
    is_overloaded: bool = False  # node drain: never transit this node
    node_label: int = 0  # SR node segment label
    area: str = DEFAULT_AREA


# Default metric values mirror the reference's best-route preference space
# (reference: openr/if/Types.thrift † PrefixMetrics defaults: pp=1000,
# sp=100, distance additive per redistribution hop).
DEFAULT_PATH_PREFERENCE = 1000
DEFAULT_SOURCE_PREFERENCE = 100


@dataclass(frozen=True)
class PrefixMetrics:
    """Best-route selection metrics, compared lexicographically:
    higher path_preference wins, then higher source_preference, then lower
    distance (reference: openr/decision/ † BestRouteSelection comment in
    Types.thrift † PrefixMetrics).
    """

    path_preference: int = DEFAULT_PATH_PREFERENCE
    source_preference: int = DEFAULT_SOURCE_PREFERENCE
    distance: int = 0


@dataclass(frozen=True, slots=True)
class PrefixEntry:
    """One advertised prefix — element of the `prefix:` key value.

    reference: openr/if/Types.thrift † PrefixEntry. `weight` is the node's
    advertised UCMP bandwidth/weight for this prefix; `min_nexthop` drops
    the route if fewer nexthops survive; `tags` feed policy.
    """

    prefix: IpPrefix
    metrics: PrefixMetrics = PrefixMetrics()
    forwarding_type: ForwardingType = ForwardingType.IP
    forwarding_algorithm: ForwardingAlgorithm = ForwardingAlgorithm.SP_ECMP
    tags: tuple[str, ...] = ()
    area_stack: tuple[str, ...] = ()
    weight: int = 0
    min_nexthop: int = 0


@dataclass(frozen=True)
class PrefixDatabase:
    """Prefixes advertised by one node in one area.

    reference: openr/if/Types.thrift † PrefixDatabase. The reference moved
    from one monolithic per-node prefix db to per-prefix keys
    (`prefix:<node>:<area>:<prefix>`); we support both via this type holding
    one-or-many entries.
    """

    this_node_name: str
    prefix_entries: tuple[PrefixEntry, ...] = ()
    area: str = DEFAULT_AREA
    delete_prefix: bool = False  # per-prefix-key withdrawal marker


# wire-schema lock registration: the adj:/prefix: KvStore key payloads
register_wire_types(
    Adjacency, AdjacencyDatabase, PrefixMetrics, PrefixEntry, PrefixDatabase
)
