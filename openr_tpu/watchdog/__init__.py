"""Watchdog (reference: openr/watchdog/ †)."""

from openr_tpu.watchdog.watchdog import Watchdog

__all__ = ["Watchdog"]
