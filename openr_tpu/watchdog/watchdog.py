"""Liveness supervisor: abort on stuck event loops or memory breach.

reference: openr/watchdog/Watchdog.{h,cpp} † — every OpenrEventBase
periodically stamps a progress timestamp; the Watchdog thread scans all
registered eventbases each interval and aborts the process (SIGABRT, so
a supervisor restarts it and the LSDB re-floods from peers) when one has
not progressed within thread_timeout_s, or when RSS exceeds the
configured ceiling. Here every OpenrModule already stamps
`last_heartbeat` from its heartbeat fiber; a module whose fiber is
starved (event loop blocked, fiber crashed) goes stale and trips the
scan.
"""

from __future__ import annotations

import logging
import os
import resource
import signal
import time

from openr_tpu.common.eventbase import OpenrModule

log = logging.getLogger(__name__)


def _current_rss_mb() -> float | None:
    """Current (not peak) resident set size. /proc/self/statm field 2 is
    resident pages; ru_maxrss would be the lifetime high-water mark and
    would keep firing long after a transient spike was freed."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGESIZE") / (1024 * 1024)
    except (OSError, ValueError, IndexError):
        # non-Linux fallback: peak RSS (ru_maxrss is KiB on Linux but
        # BYTES on Darwin; it is also the lifetime high-water mark, so
        # this path re-admits the transient-spike false positive — it is
        # a degraded fallback, not the design)
        try:
            import sys

            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            div = 1024 * 1024 if sys.platform == "darwin" else 1024
            return rss / div
        except Exception:  # noqa: BLE001
            return None


def _default_abort(reason: str) -> None:
    """reference: Watchdog fires LOG(FATAL)/abort † — SIGABRT leaves a
    core for the supervisor; never returns."""
    log.critical("watchdog aborting process: %s", reason)
    os.kill(os.getpid(), signal.SIGABRT)


class Watchdog(OpenrModule):
    """Supervises a set of OpenrModules' heartbeats + process memory."""

    def __init__(
        self,
        config,
        modules: list[OpenrModule],
        abort_fn=None,  # injectable for tests (reference tests stub abort †)
        max_memory_mb: int | None = None,
        counters=None,
    ):
        super().__init__(f"{config.node_name}.watchdog", counters=counters)
        self.config = config
        self.modules = list(modules)
        self.abort_fn = abort_fn or _default_abort
        self.max_memory_mb = max_memory_mb
        self.timeout_s = config.node.watchdog.thread_timeout_s
        self.interval_s = config.node.watchdog.interval_s
        self.fired: str | None = None  # reason, once tripped

    async def main(self) -> None:
        self.run_every(self.interval_s, self.check, name=f"{self.name}.scan")

    def watch(self, module: OpenrModule) -> None:
        self.modules.append(module)

    # ------------------------------------------------------------------ scan

    def check(self) -> None:
        """One scan pass (public so tests can drive it synchronously)."""
        now = time.monotonic()
        for m in self.modules:
            if m.stopped:
                continue
            age = now - m.last_heartbeat
            if age > self.timeout_s:
                if self.counters:
                    # stall-specific ledger (aborts also counts memory
                    # breaches; a soak watches this one for stuck loops)
                    self.counters.increment("watchdog.stalls")
                self._fire(
                    f"module {m.name} stuck: no heartbeat for {age:.1f}s "
                    f"(limit {self.timeout_s}s)"
                )
                return
        if self.max_memory_mb is not None:
            rss_mb = _current_rss_mb()
            if rss_mb is not None and rss_mb > self.max_memory_mb:
                self._fire(
                    f"memory {rss_mb:.0f}MB exceeds limit {self.max_memory_mb}MB"
                )
                return
        if self.counters:
            self.counters.increment("watchdog.scans")

    def _fire(self, reason: str) -> None:
        self.fired = reason
        if self.counters:
            self.counters.increment("watchdog.aborts")
        self.abort_fn(reason)
