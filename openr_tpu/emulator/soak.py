"""Seeded long-horizon soak runner: back-to-back chaos storms over
sustained background prefix churn, with per-round invariant gates.

The short storms in tests/test_chaos.py prove the recovery machinery
converges once; production outages look different — *minutes* of
overlapping flaps while the control plane keeps originating and
withdrawing prefixes, which is exactly the regime where unbounded queues
grow and slow leaks hide. This runner composes PR 3's ``ChaosPlan``
storms for N rounds over a continuous churn generator and, after every
round's quiescence, enforces:

  * all five cluster invariant classes (``emulator/invariants.py``),
    including the bounded-queue-depth watermark check, and
  * a **monotone-memory watermark**: RSS and live-object count after
    round r must stay within tolerance of the post-round-1 baseline
    (round 1 absorbs warmup: JAX compilation caches, interned wire
    bytes) — the leak class a single short storm can never surface.

Every failure message embeds ``seed=<s> round=<r>`` plus the plan's
schedule hash, so a failing soak replays from its printout:
``python -m openr_tpu.emulator --soak --seed <s> --rounds <r+1>``.
"""

from __future__ import annotations

import asyncio
import gc
import logging
from dataclasses import dataclass, field

from openr_tpu.common.tasks import guard_task, reap
from openr_tpu.emulator.chaos import (
    ChaosPlan,
    FibFaults,
    KvFaults,
    LinkFaults,
    run_schedule,
)
from openr_tpu.emulator.cluster import Cluster
from openr_tpu.emulator.invariants import wait_quiescent
from openr_tpu.monitor import work_ledger
from openr_tpu.watchdog.watchdog import _current_rss_mb

log = logging.getLogger(__name__)


class SoakError(AssertionError):
    """An invariant or watermark breach; the message carries the seed and
    round needed to replay the failing run."""


@dataclass
class SoakConfig:
    seed: int = 7
    rounds: int = 3
    edges: list = field(default_factory=list)  # [(a, b)] — required
    solver: str = "cpu"
    # per-round storm shape (fed to Cluster.make_storm)
    storm_duration_s: float = 1.6
    n_flaps: int = 3
    n_crashes: int = 1
    n_partitions: int = 0
    #: disk-fault crash archetypes per round (multi-process soaks only:
    #: the in-process cluster has no persist plane to damage)
    n_disk_faults: int = 0
    heal_after_s: float = 0.6
    # rate faults active during each storm
    link_faults: LinkFaults = field(
        default_factory=lambda: LinkFaults(drop=0.05, reorder=0.05, jitter_ms=20.0)
    )
    kv_faults: KvFaults = field(
        default_factory=lambda: KvFaults(fail_flood=0.05)
    )
    fib_faults: FibFaults = field(default_factory=FibFaults)
    # background churn: advertise/withdraw cadence per churn step
    churn_interval_s: float = 0.03
    churn_prefixes: int = 12  # fixed pool size (fixed pool ⇒ bounded keys)
    # must cover a saturated peer-sync backoff (30 s envelope): a peer
    # whose connects failed throughout a crash window may legitimately
    # sleep most of that before the reconnect that drains its backlog
    quiesce_timeout_s: float = 90.0
    # memory watermark tolerances vs the post-round-1 baseline
    mem_rss_slack_mb: float = 96.0
    mem_obj_rel_tol: float = 0.10
    mem_obj_abs_tol: int = 50_000
    # warm-start solve-state watermark: the summed
    # Decision.warm_cache_bytes() across nodes (reverse adjacency /
    # pred-DAG aux / host distance mirrors held by cached
    # SolveArtifacts) must stay within this slack of the post-round-1
    # baseline — the enlarged artifact state the topology-delta path
    # retains is exactly the leak class a storm-heavy soak would grow
    # if the idle-trim eviction policy regressed
    warm_cache_slack_mb: float = 32.0
    # prefix-table + nexthop-group-intern watermark: the summed
    # Decision.prefix_table_bytes() across nodes must stay within this
    # slack of the post-round-1 baseline — a churn horizon that leaks
    # withdrawn prefixes into PrefixState, or grows the intern tables
    # without bound, trips here instead of hiding inside total RSS
    # (the million-prefix data plane's leak class; docs/Decision.md)
    prefix_table_slack_mb: float = 24.0
    # device-HBM watermark (monitor/device.py sample_hbm): summed live
    # bytes_in_use across local devices must stay within this slack of
    # the post-round-1 baseline — the leak class where device-resident
    # LSDB table sets, warm distance matrices, or election matrices
    # accumulate in HBM across churn rounds. Skipped (None samples) on
    # backends without memory_stats (CPU), where the RSS watermark
    # already covers the same arrays in host RAM.
    hbm_slack_mb: float = 64.0
    # control knob: build the cluster with messaging bounds DISABLED
    # (caps stay configured, queues unbounded) to prove the watermark
    # checks catch unbounded growth
    enforce_queue_bounds: bool = True


@dataclass
class RoundSample:
    round: int
    rss_mb: float | None
    objects: int
    churn_events: int
    schedule_hash: str
    warm_mb: float = 0.0  # summed Decision warm-start cache footprint
    prefix_mb: float = 0.0  # summed prefix-table + intern-table footprint
    hbm_mb: float | None = None  # summed device bytes_in_use (None on cpu)


@dataclass
class SoakReport:
    seed: int
    rounds: list[RoundSample] = field(default_factory=list)

    def summary(self) -> str:
        lines = [f"soak seed={self.seed}: {len(self.rounds)} round(s) clean"]
        for s in self.rounds:
            rss = f"{s.rss_mb:.0f}MB" if s.rss_mb is not None else "n/a"
            hbm = f"{s.hbm_mb:.0f}MB" if s.hbm_mb is not None else "n/a"
            lines.append(
                f"  round {s.round}: rss={rss} objects={s.objects} "
                f"churn={s.churn_events} warm={s.warm_mb}MB "
                f"prefix={s.prefix_mb}MB hbm={hbm} "
                f"schedule={s.schedule_hash[:12]}"
            )
        return "\n".join(lines)


class PrefixChurner:
    """Sustained background prefix churn through the PrefixManager API
    seam: each step advertises or withdraws one prefix from a fixed
    per-node pool on a seeded-random live node. The pool is fixed so the
    steady-state key count is bounded — what must NOT grow round over
    round is memory, and a drifting advertisement set would mask that.
    """

    def __init__(self, cluster: Cluster, rng, interval_s: float, pool: int):
        self.cluster = cluster
        self.rng = rng
        self.interval_s = interval_s
        self.pool = pool
        self.events = 0
        self._advertised: set[tuple[str, int]] = set()  # (node, idx)
        self._task: asyncio.Task | None = None
        # stable node ids for prefix derivation: crash/restart must not
        # shift another node's churn prefixes onto it
        self._ids = {
            name: i
            for i, name in enumerate(
                sorted(set(cluster.nodes) | set(cluster.crashed))
            )
        }

    def _push(self, node_name: str, idx: int, add: bool) -> None:
        from openr_tpu.prefixmgr.prefix_manager import (
            PrefixEvent,
            PrefixEventType,
            PrefixSource,
        )
        from openr_tpu.types.network import IpPrefix
        from openr_tpu.types.topology import PrefixEntry

        node = self.cluster.nodes.get(node_name)
        if node is None:
            return  # crashed mid-storm: skip this step
        nid = self._ids[node_name] & 0xFF
        entry = PrefixEntry(
            prefix=IpPrefix.make(f"10.200.{nid}.{idx}/32")
        )
        node.prefix_events.push(
            PrefixEvent(
                type=(
                    PrefixEventType.ADD_PREFIXES
                    if add
                    else PrefixEventType.WITHDRAW_PREFIXES
                ),
                source=PrefixSource.API,
                entries=(entry,),
            )
        )
        self.events += 1
        key = (node_name, idx)
        (self._advertised.add if add else self._advertised.discard)(key)

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            names = sorted(self.cluster.nodes)
            if not names:
                continue
            node_name = names[self.rng.randrange(len(names))]
            idx = self.rng.randrange(self.pool)
            add = (node_name, idx) not in self._advertised
            self._push(node_name, idx, add)

    def start(self) -> None:
        assert self._task is None
        # guard: a crash mid-churn must surface (log + counter) the
        # moment it happens, not sit parked on the Task until stop()
        self._task = guard_task(
            asyncio.get_event_loop().create_task(
                self._run(), name="soak.churner"
            ),
            owner="soak.churner",
        )

    async def stop(self, withdraw: bool = True) -> None:
        if self._task is not None:
            # reap swallows only the churner's own cancellation; a
            # cancellation aimed at stop() itself still propagates
            await reap(self._task)
            self._task = None
        if withdraw:
            # return to the base advertisement set so every round
            # quiesces into the same steady state
            for node_name, idx in sorted(self._advertised):
                self._push(node_name, idx, add=False)
            self._advertised.clear()


def _memory_sample() -> tuple[float | None, int]:
    gc.collect()
    return _current_rss_mb(), len(gc.get_objects())


async def run_soak(cfg: SoakConfig) -> SoakReport:
    """Run the multi-round soak; raises :class:`SoakError` (with the
    seed+round replay hint embedded) on any invariant or watermark
    breach."""
    assert cfg.edges, "SoakConfig.edges is required"
    plan = ChaosPlan(
        cfg.seed,
        link_faults=cfg.link_faults,
        kv_faults=cfg.kv_faults,
        fib_faults=cfg.fib_faults,
    )
    transform = None
    if not cfg.enforce_queue_bounds:
        # control case: every node built with bounds OFF while the caps
        # stay configured, so check_queue_bounds still knows the limits
        from dataclasses import replace

        def transform(ncfg):  # noqa: F811
            return replace(
                ncfg,
                messaging=replace(ncfg.messaging, enforce_bounds=False),
            )

    cluster = Cluster.from_edges(
        cfg.edges, solver=cfg.solver, chaos=plan,
        node_config_transform=transform,
    )
    # rate faults gate on the per-round storms — initial bring-up is
    # clean so round boundaries always start from a converged baseline
    plan.active = False
    # the work ledger is process-global: clear anything a previous soak
    # or bench left behind so round attribution starts from zero
    work_ledger.reset()
    await cluster.start()
    try:
        await cluster.wait_converged(timeout=cfg.quiesce_timeout_s)
        report = SoakReport(seed=cfg.seed)
        churn_rng = plan.rng("soak/churn")
        baseline: (
            tuple[float | None, int, float, float, float | None] | None
        ) = None
        for rnd in range(cfg.rounds):
            plan.active = True
            cluster.make_storm(
                plan,
                duration_s=cfg.storm_duration_s,
                n_flaps=cfg.n_flaps,
                n_crashes=cfg.n_crashes,
                n_partitions=cfg.n_partitions,
                heal_after_s=cfg.heal_after_s,
                n_disk_faults=cfg.n_disk_faults,
            )
            context = (
                f"soak seed={cfg.seed} round={rnd} "
                f"(--soak --seed {cfg.seed} --rounds {rnd + 1}; "
                f"{plan.replay_hint()})"
            )
            churner = PrefixChurner(
                cluster, churn_rng, cfg.churn_interval_s, cfg.churn_prefixes
            )
            churner.start()
            try:
                await run_schedule(cluster, plan)
            finally:
                await churner.stop(withdraw=True)
            try:
                await wait_quiescent(
                    cluster,
                    timeout_s=cfg.quiesce_timeout_s,
                    context=context,
                )
            except AssertionError as e:
                raise SoakError(str(e)) from e
            # HBM first: on a cpu-oracle soak this is the process's
            # FIRST jax touch, and the import's ~60k live objects must
            # land inside round 0's object-watermark baseline, not be
            # charged to round 1 as a phantom leak
            from openr_tpu.monitor import device as device_telemetry

            hbm_mb = device_telemetry.hbm_in_use_mb()
            rss_mb, objects = _memory_sample()
            warm_mb = (
                sum(
                    n.decision.warm_cache_bytes()
                    for n in cluster.nodes.values()
                )
                / 1e6
            )
            prefix_mb = (
                sum(
                    n.decision.prefix_table_bytes()
                    for n in cluster.nodes.values()
                )
                / 1e6
            )
            report.rounds.append(
                RoundSample(
                    round=rnd,
                    rss_mb=rss_mb,
                    objects=objects,
                    churn_events=churner.events,
                    schedule_hash=plan.schedule_hash(),
                    warm_mb=round(warm_mb, 2),
                    prefix_mb=round(prefix_mb, 2),
                    hbm_mb=None if hbm_mb is None else round(hbm_mb, 2),
                )
            )
            log.info(
                "soak round %d clean: rss=%s objects=%d churn=%d "
                "warm=%.1fMB prefix=%.1fMB hbm=%s",
                rnd, rss_mb, objects, churner.events, warm_mb, prefix_mb,
                hbm_mb,
            )
            if rnd == 0:
                # round 1 is the warmup baseline (JIT caches, interned
                # bytes); monotone growth is judged from here on —
                # and the same boundary arms the work-proportionality
                # invariant (invariants.check_work_ratios): from here
                # every storm round's per-stage touched-entity counts
                # are judged against their deltas
                baseline = (rss_mb, objects, warm_mb, prefix_mb, hbm_mb)
                work_ledger.mark_warm()
                continue
            base_rss, base_obj, base_warm, base_prefix, base_hbm = baseline
            if (
                hbm_mb is not None
                and base_hbm is not None
                and hbm_mb > base_hbm + cfg.hbm_slack_mb
            ):
                raise SoakError(
                    f"device-HBM watermark breach ({context}): "
                    f"{hbm_mb:.1f}MB live device memory > baseline "
                    f"{base_hbm:.1f}MB + {cfg.hbm_slack_mb:.0f}MB slack "
                    "(device-resident tables or warm matrices leaking?)"
                )
            if warm_mb > base_warm + cfg.warm_cache_slack_mb:
                raise SoakError(
                    f"warm-cache watermark breach ({context}): "
                    f"{warm_mb:.1f}MB of warm-start solve state > "
                    f"baseline {base_warm:.1f}MB + "
                    f"{cfg.warm_cache_slack_mb:.0f}MB slack "
                    "(SolveArtifact eviction policy regressed?)"
                )
            if prefix_mb > base_prefix + cfg.prefix_table_slack_mb:
                raise SoakError(
                    f"prefix-table watermark breach ({context}): "
                    f"{prefix_mb:.1f}MB of prefix-table + intern-table "
                    f"state > baseline {base_prefix:.1f}MB + "
                    f"{cfg.prefix_table_slack_mb:.0f}MB slack "
                    "(withdrawn prefixes or nexthop groups leaking?)"
                )
            if (
                rss_mb is not None
                and base_rss is not None
                and rss_mb > base_rss + cfg.mem_rss_slack_mb
            ):
                raise SoakError(
                    f"memory watermark breach ({context}): RSS "
                    f"{rss_mb:.0f}MB > baseline {base_rss:.0f}MB + "
                    f"{cfg.mem_rss_slack_mb:.0f}MB slack"
                )
            obj_cap = base_obj * (1 + cfg.mem_obj_rel_tol) + cfg.mem_obj_abs_tol
            if objects > obj_cap:
                raise SoakError(
                    f"object watermark breach ({context}): "
                    f"{objects} live objects > cap {obj_cap:.0f} "
                    f"(baseline {base_obj})"
                )
        return report
    finally:
        # disarm the process-global proportionality gate so later
        # single-shot assert_invariants calls in the same process
        # (tests) don't inherit this soak's warm window
        work_ledger.reset_warm()
        await cluster.stop()
