"""Run an in-process emulated cluster and report convergence.

    python -m openr_tpu.emulator --nodes 9 --topo grid
    python -m openr_tpu.emulator --topo ring --nodes 6 --churn 3

Analogue of running N openr binaries in network namespaces against the
reference; used for demos and manual convergence measurement.
"""

from __future__ import annotations

import argparse
import asyncio
import time


def topo_edges(topo: str, n: int) -> list[tuple[str, str]]:
    names = [f"node-{i}" for i in range(n)]
    edges: list[tuple[str, str]] = []
    if topo == "line":
        edges = [(names[i], names[i + 1]) for i in range(n - 1)]
    elif topo == "ring":
        edges = [(names[i], names[(i + 1) % n]) for i in range(n)]
    elif topo == "grid":
        side = int(n**0.5)
        assert side * side == n, f"--nodes must be a square for grid (got {n})"
        for r in range(side):
            for c_ in range(side):
                i = r * side + c_
                if c_ + 1 < side:
                    edges.append((names[i], names[i + 1]))
                if r + 1 < side:
                    edges.append((names[i], names[i + side]))
    elif topo == "mesh":
        edges = [
            (names[i], names[j]) for i in range(n) for j in range(i + 1, n)
        ]
    else:
        raise SystemExit(f"unknown topo {topo!r}")
    return edges


async def main() -> None:
    ap = argparse.ArgumentParser(prog="openr_tpu.emulator")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument(
        "--topo", choices=["line", "ring", "grid", "mesh"], default="ring"
    )
    ap.add_argument(
        "--solver", choices=["cpu", "tpu"], default="cpu",
        help="route computation backend (tpu = JAX batched SSSP)",
    )
    ap.add_argument(
        "--churn", type=int, default=0,
        help="after convergence, fail/heal this many links and re-measure",
    )
    ap.add_argument(
        "--ctrl", action="store_true",
        help="start a ctrl server per node and print its port "
        "(drive with `python -m openr_tpu.cli --port <port> ...`)",
    )
    ap.add_argument(
        "--hold", type=float, default=0.0,
        help="keep the cluster running this many seconds after convergence",
    )
    ap.add_argument(
        "--soak", action="store_true",
        help="run the seeded multi-round soak (storms + background "
        "prefix churn + per-round invariant and memory-watermark gates) "
        "instead of the one-shot convergence run",
    )
    ap.add_argument("--seed", type=int, default=7, help="soak chaos seed")
    ap.add_argument("--rounds", type=int, default=3, help="soak rounds")
    ap.add_argument("--flaps", type=int, default=3)
    ap.add_argument("--crashes", type=int, default=1)
    ap.add_argument("--partitions", type=int, default=0)
    ap.add_argument(
        "--unbounded-control", action="store_true",
        help="soak control case: disable the messaging queue bounds "
        "(caps stay configured) to demonstrate the watermark check fails",
    )
    args = ap.parse_args()

    if args.soak:
        from openr_tpu.emulator.soak import SoakConfig, run_soak

        report = await run_soak(
            SoakConfig(
                seed=args.seed,
                rounds=args.rounds,
                edges=topo_edges(args.topo, args.nodes),
                solver=args.solver,
                n_flaps=args.flaps,
                n_crashes=args.crashes,
                n_partitions=args.partitions,
                enforce_queue_bounds=not args.unbounded_control,
            )
        )
        print(report.summary())
        return

    from openr_tpu.emulator import Cluster

    edges = topo_edges(args.topo, args.nodes)
    cluster = Cluster.from_edges(edges, solver=args.solver, enable_ctrl=args.ctrl)
    print(f"starting {args.nodes} nodes, {len(edges)} links ({args.topo})")
    t0 = time.perf_counter()
    await cluster.start()
    # convergence wall derives from the SAME oversubscription scaling
    # as the Spark timers (one source of truth — review finding): a
    # 196-node grid converges in ~12 hold periods on one core; 36
    # gives 3x headroom
    from openr_tpu.emulator.cluster import scaled_spark

    conv_timeout = max(
        60.0, 36 * scaled_spark(args.nodes).hold_time_ms / 1000.0
    )
    await cluster.wait_converged(timeout=conv_timeout)
    t_conv = time.perf_counter() - t0
    total_routes = sum(
        len(n.fib.programmed_unicast) for n in cluster.nodes.values()
    )
    print(
        f"converged in {t_conv * 1e3:.1f} ms: "
        f"{total_routes} unicast routes programmed across the cluster"
    )

    if args.ctrl:
        for name, node in cluster.nodes.items():
            print(f"ctrl {name} 127.0.0.1:{node.ctrl.port}", flush=True)

    for k in range(args.churn):
        a, b = edges[k % len(edges)]
        t0 = time.perf_counter()
        cluster.fail_link(a, b)
        # wait for any FIB change, then heal
        await asyncio.sleep(1.0)
        cluster.heal_link(a, b)
        await cluster.wait_converged(timeout=conv_timeout)
        print(
            f"churn {k}: fail/heal {a}—{b}, reconverged in "
            f"{(time.perf_counter() - t0) * 1e3:.1f} ms (incl. 1s hold)"
        )

    if args.hold:
        print(f"holding for {args.hold}s", flush=True)
        await asyncio.sleep(args.hold)

    await cluster.stop()


if __name__ == "__main__":
    asyncio.run(main())
