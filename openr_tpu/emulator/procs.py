"""Multi-process cluster harness: real processes, real sockets, real
signals.

The in-process emulator (emulator/cluster.py) co-schedules N OpenrNodes
on one asyncio loop — crash_node is a cancelled task, partitions are
dict flips, and one loop serializes every flood fan-out. This module is
the other half of the robustness story: a supervisor that spawns each
node as ``python -m openr_tpu`` (its own interpreter, its own loop),
wired over the seams that already abstract the process boundary —

  * Spark neighbor discovery over **real UDP sockets**
    (``spark/io.py`` ``UdpIoProvider``; one ephemeral localhost port
    per interface),
  * KvStore flooding/full-sync over **real TCP** (``kvstore/
    transport.py`` ``TcpKvTransport`` + the negotiated binary codec),
  * all observation and chaos control over **ctrl RPC**
    (``ctrl/server.py`` — including the harness endpoints:
    get_convergence_state / get_kvstore_digest / check_fib_oracle /
    chaos_set_drop / set_udp_peer / work_ledger_control).

Faults are REAL: ``crash_node`` is SIGKILL (or a graceful-restart
announcement + SIGTERM), ``hang_node`` is SIGSTOP, partitions are
socket-level drop rules installed in the target processes' io
providers, and ``restart_node`` is a genuine re-exec that re-syncs the
LSDB from peers. The method surface mirrors ``Cluster`` closely enough
that ``chaos.run_schedule`` drives either (link/partition methods are
coroutines here; the dispatcher awaits whatever it gets back).

Port allocation is collision-free by construction: every listener and
UDP socket in a generated config binds port 0, the node process reports
its bound ports through the ``--ready-file`` readiness handshake
(openr_tpu/__main__.py), and the supervisor wires each link's two
endpoints together afterwards via ctrl ``set_udp_peer`` —
``UdpIoProvider.send`` no-ops until its peer is set, and Spark hellos
are periodic, so discovery starts by itself once both ends are wired.

See docs/Emulator.md "Multi-process clusters" for the lifecycle and
fault matrix.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field, replace

from openr_tpu.config import Config, NodeConfig, OriginatedPrefix
from openr_tpu.config.config import UdpInterfaceConfig
from openr_tpu.emulator.cluster import LinkSpec, loopback_of, scaled_spark
from openr_tpu.rpc import RpcClient, RpcError

log = logging.getLogger(__name__)

#: readiness-handshake patience: N interpreters starting on (possibly)
#: one core serialize their imports; scaled by fleet size at wait time
READY_BASE_TIMEOUT_S = 30.0

_LOG_TAIL = 30  # lines of a dead node's log quoted in errors


def _read_json(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


@dataclass
class ProcNode:
    """Supervisor-side handle for one spawned node process."""

    name: str
    config_path: str
    log_path: str
    ready_path: str
    proc: subprocess.Popen | None = None
    ready: dict = field(default_factory=dict)  # the handshake payload
    ctrl: RpcClient | None = None
    interfaces: dict[str, str] = field(default_factory=dict)  # if -> peer
    #: journal directory (docs/Persist.md); survives crash/restart so a
    #: re-exec is a WARM boot — originated keys, redistribution books
    #: and the programmed FIB come back from disk, not from peers
    persist_dir: str | None = None

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    @property
    def ctrl_port(self) -> int | None:
        return self.ready.get("ctrl_port")

    def log_tail(self, n: int = _LOG_TAIL) -> str:
        try:
            with open(self.log_path, errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return "<no log>"


class ProcCluster:
    """N real node processes + the chaos/observation control plane.

    Mirrors emulator.Cluster's surface (nodes / crashed / links /
    fail_link / heal_link / crash_node / restart_node / partition /
    heal_partition / converged / wait_converged / make_storm /
    fleet_counters) so the PR 3/4/16 chaos + soak machinery runs
    unchanged — with the difference that every fault crosses a real
    process boundary.
    """

    def __init__(
        self,
        links: list[LinkSpec],
        workdir: str,
        python: str | None = None,
        prefixes_per_node: int = 0,
        host: str = "127.0.0.1",
        spark_scale_cap: float = 20.0,
        persist: bool = True,
        spark_overrides: dict | None = None,
    ):
        self.links = links
        self.workdir = workdir
        self.python = python or sys.executable
        self.host = host
        self.nodes: dict[str, ProcNode] = {}
        self.crashed: dict[str, ProcNode] = {}
        self.hung: dict[str, ProcNode] = {}
        self._partitioned: list[LinkSpec] = []
        names = sorted({ls.a for ls in links} | {ls.b for ls in links})
        self.names = names
        os.makedirs(workdir, exist_ok=True)
        n = len(names)
        # Host-oversubscription scaling. The in-proc emulator's
        # scaled_spark covers coroutine crowding on ONE loop; here every
        # node is an interpreter PROCESS contending for the host's
        # cores, and each process stalls its own event loop for the
        # duration of its solver + FIB work (O(prefixes)). A hold timer
        # must survive the worst such stall times the scheduling
        # multiplier, or CPU contention masquerades as neighbor loss
        # and the fleet churns itself forever (observed: 8 procs on 1
        # core, 100 prefixes each — 573 ms full rebuilds vs a 400 ms
        # hold). Real routers run multi-second holds for the same
        # reason.
        cpu = os.cpu_count() or 1
        factor = max(
            1.0,
            (n / cpu) / 4.0,  # >4 interpreters per core: stretch
            n * (1 + prefixes_per_node) / 4000.0,  # solver stall term
        )
        factor = min(factor, spark_scale_cap)
        base = scaled_spark(n)
        spark_cfg = replace(
            base,
            hello_time_ms=int(base.hello_time_ms * factor),
            fastinit_hello_time_ms=int(
                base.fastinit_hello_time_ms * factor
            ),
            handshake_time_ms=int(base.handshake_time_ms * factor),
            keepalive_time_ms=int(base.keepalive_time_ms * factor),
            hold_time_ms=int(base.hold_time_ms * factor),
            graceful_restart_time_ms=int(
                base.graceful_restart_time_ms * factor
            ),
        )
        if spark_overrides:
            # crash-recovery tests pin hold/GR above the worst re-exec
            # time: a warm boot is only "hitless" if the survivors'
            # hold timers outlive the victim's restart window
            spark_cfg = replace(spark_cfg, **spark_overrides)
        self.spark_factor = round(factor, 2)
        debounce = (10, max(60, int(60 * factor)))
        for i, name in enumerate(names):
            ifaces = {}
            for ls in links:
                if ls.a == name:
                    ifaces[ls.a_if] = ls.b
                elif ls.b == name:
                    ifaces[ls.b_if] = ls.a
            originated = [OriginatedPrefix(prefix=loopback_of(i))]
            for p in range(prefixes_per_node):
                # deterministic per-node prefix block out of 100.64/10
                originated.append(OriginatedPrefix(
                    prefix=f"100.{64 + (i >> 8)}.{i & 0xFF}.{p % 256}/32"
                    if p < 256 else
                    f"100.{96 + (p >> 8)}.{i & 0xFF}.{p & 0xFF}/32"
                ))
            ncfg = NodeConfig(
                node_name=name,
                spark=spark_cfg,
                originated_prefixes=tuple(originated),
                # everything ephemeral: the readiness handshake is the
                # only source of truth for where this node listens
                ctrl_port=0,
                kvstore_port=0,
                endpoint_host=host,
                udp_interfaces=tuple(
                    # local_port=0 (bind ephemeral), peer_port=0 (defer
                    # wiring to the supervisor's set_udp_peer pass)
                    UdpInterfaceConfig(
                        if_name=ifn, local_port=0,
                        peer_host=host, peer_port=0,
                    )
                    for ifn in sorted(ifaces)
                ),
            )
            ncfg = replace(
                ncfg,
                decision=replace(
                    ncfg.decision,
                    # real fleets of single-node interpreters must not
                    # each warm a jax jit cache: the CPU oracle is the
                    # right per-process solver at emulation scale
                    use_tpu_solver=False,
                    debounce_min_ms=debounce[0],
                    debounce_max_ms=debounce[1],
                ),
            )
            cfg_path = os.path.join(workdir, f"{name}.json")
            with open(cfg_path, "w") as f:
                f.write(Config(ncfg).to_json())
            self.nodes[name] = ProcNode(
                name=name,
                config_path=cfg_path,
                log_path=os.path.join(workdir, f"{name}.log"),
                ready_path=os.path.join(workdir, f"{name}.ready.json"),
                interfaces=ifaces,
                # persistence on by default: a ProcCluster restart is a
                # warm boot, which is what the crash-recovery invariants
                # (proc_invariants.persist_parity) exercise
                persist_dir=(
                    os.path.join(workdir, f"{name}.persist")
                    if persist else None
                ),
            )

    @staticmethod
    def from_edges(
        edges, workdir: str, prefixes_per_node: int = 0, **kw
    ) -> "ProcCluster":
        links = [
            e if isinstance(e, LinkSpec) else LinkSpec(a=e[0], b=e[1])
            for e in edges
        ]
        return ProcCluster(
            links, workdir, prefixes_per_node=prefixes_per_node, **kw
        )

    # ------------------------------------------------------------ lifecycle

    def _spawn(self, pn: ProcNode) -> None:
        try:
            os.unlink(pn.ready_path)
        except OSError:
            pass
        logf = open(pn.log_path, "a")
        env = dict(os.environ)
        # the child runs with cwd=workdir (its logs/stores land there),
        # so when the package is imported from a source tree rather
        # than installed, hand the tree to the child explicitly
        import openr_tpu

        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(openr_tpu.__file__))
        )
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (pkg_root, env.get("PYTHONPATH")) if p
        )
        # the node process must never touch a TPU plugin — and with
        # use_tpu_solver=False it never imports jax at all (the import
        # is lazy); the env pin is belt-and-braces for the odd path
        # (compile ledger) that does
        env["JAX_PLATFORMS"] = "cpu"
        pn.proc = subprocess.Popen(
            [
                self.python, "-m", "openr_tpu",
                "--config", pn.config_path,
                "--ready-file", pn.ready_path,
                "--log-level", "WARNING",
                *(
                    ["--persist-dir", pn.persist_dir]
                    if pn.persist_dir else []
                ),
            ],
            stdout=logf,
            stderr=subprocess.STDOUT,
            env=env,
            cwd=self.workdir,
        )
        logf.close()  # the child owns the fd now

    async def _wait_ready(self, pns: list[ProcNode]) -> None:
        """Poll the ready files; fail FAST on a dead process or an
        {'error': ...} handshake instead of hanging on convergence."""
        timeout = READY_BASE_TIMEOUT_S + 1.5 * len(self.names)
        deadline = time.monotonic() + timeout
        pending = list(pns)
        while pending:
            still = []
            for pn in pending:
                if os.path.exists(pn.ready_path):
                    ready = await asyncio.to_thread(_read_json, pn.ready_path)
                    if "error" in ready:
                        raise RuntimeError(
                            f"node {pn.name} failed to bind: "
                            f"{ready['error']}\n--- {pn.name} log tail "
                            f"---\n{pn.log_tail()}"
                        )
                    pn.ready = ready
                    continue
                if not pn.alive:
                    raise RuntimeError(
                        f"node {pn.name} exited rc={pn.proc.returncode} "
                        f"before reporting ready\n--- {pn.name} log tail"
                        f" ---\n{pn.log_tail()}"
                    )
                still.append(pn)
            pending = still
            if pending and time.monotonic() > deadline:
                raise RuntimeError(
                    f"{len(pending)} node(s) not ready after "
                    f"{timeout:.0f}s: "
                    f"{sorted(pn.name for pn in pending)[:8]}"
                )
            if pending:
                await asyncio.sleep(0.1)

    async def _ctrl(self, pn: ProcNode) -> RpcClient:
        """Pooled ctrl client; (re)connects lazily — a node that was
        killed and restarted comes back on a new ctrl port, so the
        stale client is dropped whenever the connection is gone."""
        if pn.ctrl is not None and pn.ctrl.connected:
            return pn.ctrl
        if pn.ctrl is not None:
            await pn.ctrl.close()
        pn.ctrl = RpcClient(self.host, pn.ready["ctrl_port"])
        await pn.ctrl.connect()
        return pn.ctrl

    async def call(
        self, name: str, method: str, params: dict | None = None,
        timeout: float = 30.0,
    ):
        pn = self.nodes.get(name) or self.crashed.get(name)
        if pn is None:
            raise KeyError(name)
        c = await self._ctrl(pn)
        return await c.call(method, params or {}, timeout=timeout)

    async def _wire_links(self, names: set[str] | None = None) -> None:
        """Point each link endpoint's UDP socket at its neighbor's
        bound port. With `names`, only links touching those nodes are
        (re)wired — the restart path, where the restarted node AND each
        neighbor's facing interface both need the fresh ports."""
        for ls in self.links:
            if names is not None and not ({ls.a, ls.b} & names):
                continue
            a, b = self.nodes.get(ls.a), self.nodes.get(ls.b)
            if a is None or b is None:
                continue  # endpoint crashed; restart re-wires it
            await self.call(ls.a, "set_udp_peer", {
                "if_name": ls.a_if, "host": self.host,
                "port": b.ready["udp_ports"][ls.b_if],
            })
            await self.call(ls.b, "set_udp_peer", {
                "if_name": ls.b_if, "host": self.host,
                "port": a.ready["udp_ports"][ls.a_if],
            })

    async def start(self) -> None:
        for pn in self.nodes.values():
            self._spawn(pn)
        await self._wait_ready(list(self.nodes.values()))
        await self._wire_links()

    async def stop(self) -> None:
        for pn in list(self.nodes.values()) + list(self.crashed.values()):
            if pn.ctrl is not None:
                try:
                    await pn.ctrl.close()
                except RpcError:
                    pass
                pn.ctrl = None
            if pn.alive:
                pn.proc.send_signal(signal.SIGCONT)  # un-hang first
                pn.proc.terminate()
        deadline = time.monotonic() + 10.0
        for pn in list(self.nodes.values()) + list(self.crashed.values()):
            if pn.proc is None:
                continue
            while pn.alive and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            if pn.alive:
                pn.proc.kill()
            pn.proc.wait()

    def endpoints(self) -> list[str]:
        """Live ctrl endpoints, `breeze --endpoints` format."""
        return [
            f"{self.host}:{pn.ready['ctrl_port']}"
            for pn in self.nodes.values()
            if pn.ready.get("ctrl_port")
        ]

    # ----------------------------------------------------------- assertions

    async def converged(self) -> bool:
        """Every live process initialized with a route to every other
        live node's loopback (same definition as Cluster.converged,
        answered over ctrl)."""
        n_remote = len(self.nodes) - 1
        for pn in self.nodes.values():
            try:
                st = await self.call(
                    pn.name, "get_convergence_state", timeout=10.0
                )
            except (RpcError, OSError):
                return False
            if not st["initialized"]:
                return False
            if st["fib"]["programmed_unicast"] < n_remote:
                return False
        return True

    async def wait_converged(self, timeout: float = 120.0) -> None:
        t0 = time.monotonic()
        while not await self.converged():
            if time.monotonic() - t0 > timeout:
                detail = {}
                for pn in self.nodes.values():
                    try:
                        st = await self.call(
                            pn.name, "get_convergence_state", timeout=5.0
                        )
                        detail[pn.name] = (
                            st["initialized"],
                            st["fib"]["programmed_unicast"],
                        )
                    except (RpcError, OSError):
                        detail[pn.name] = (
                            "alive" if pn.alive else "dead", None
                        )
                raise TimeoutError(
                    f"proc cluster did not converge: {detail}"
                )
            await asyncio.sleep(0.25)

    async def fleet_counters(self, prefix: str = "") -> dict:
        from openr_tpu.monitor.fleet import aggregate_counters

        snaps = {}
        for pn in self.nodes.values():
            try:
                snaps[pn.name] = await self.call(
                    pn.name, "get_counters", {"prefix": prefix}
                )
            except (RpcError, OSError):
                continue
        return aggregate_counters(snaps, prefix=prefix)

    # ------------------------------------------------------------- persist

    async def get_persist_status(self, name: str) -> dict:
        """Journal health + per-book digests over ctrl — the byte-parity
        token proc_invariants.persist_parity snapshots BEFORE a crash
        and compares against the restarted incarnation's recovery."""
        return await self.call(name, "get_persist_status")

    async def inject_disk_fault(self, name: str, kind: str, **params):
        """Arm a one-shot disk fault (torn / corrupt / enospc /
        crash_between_rename / slow_fsync) in the target PROCESS's
        persist plane — the chaos machinery's durable-storage seam.
        The fault fires at the next matching journal edge."""
        return await self.call(
            name, "persist_control",
            {"op": "inject", "kind": kind, "params": params},
        )

    # -------------------------------------------------------------- control

    def _links_between(self, a: str, b: str) -> list[LinkSpec]:
        found = [ls for ls in self.links if {ls.a, ls.b} == {a, b}]
        if not found:
            raise ValueError(f"no link between {a!r} and {b!r}")
        return found

    async def _set_drop(self, node: str, if_names: list[str], op: str):
        pn = self.nodes.get(node)
        if pn is None or not pn.alive:
            return  # crashed/hung endpoint: nothing to install
        try:
            await self.call(node, "chaos_set_drop", {
                "if_names": if_names, "op": op,
            })
        except (RpcError, OSError):
            # a process dying mid-partition is chaos working as
            # intended; the drop rule dies with the process
            log.debug("chaos_set_drop on %s failed (process gone?)", node)

    async def fail_link(self, a: str, b: str) -> None:
        """Socket-level silent loss: both endpoints' UDP interfaces for
        the (a, b) link drop tx AND rx, so the adjacency dies by Spark
        hold expiry — and the KvStore TCP session follows when
        LinkMonitor withdraws the peer. No process is told anything."""
        for ls in self._links_between(a, b):
            await self._set_drop(ls.a, [ls.a_if], "add")
            await self._set_drop(ls.b, [ls.b_if], "add")

    async def heal_link(self, a: str, b: str) -> None:
        """Remove the drop rules; periodic hellos resume on their own
        (the interfaces never went down, only their packets did)."""
        for ls in self._links_between(a, b):
            await self._set_drop(ls.a, [ls.a_if], "remove")
            await self._set_drop(ls.b, [ls.b_if], "remove")

    # ------------------------------------------------------- crash archetypes

    async def crash_node(self, name: str, graceful: bool = False) -> None:
        """Hard crash = SIGKILL (nothing flushed, sockets RST on next
        use — peers' in-flight syncs surface transport errors and land
        in backoff). Graceful = announce Spark GR over ctrl, then
        SIGTERM for the orderly shutdown path."""
        pn = self.nodes.pop(name)  # KeyError: unknown or already crashed
        # register under crashed FIRST: call() resolves through both
        # maps, and the graceful path still needs one ctrl round trip
        self.crashed[name] = pn
        if graceful and pn.alive:
            try:
                await self.call(name, "spark_announce_restart", timeout=5.0)
            except (RpcError, OSError):
                pass  # already dying — a hard crash then
        if pn.ctrl is not None:
            try:
                await pn.ctrl.close()
            except RpcError:
                pass
            pn.ctrl = None
        if pn.alive:
            pn.proc.send_signal(
                signal.SIGTERM if graceful else signal.SIGKILL
            )
            await asyncio.to_thread(pn.proc.wait)

    async def restart_node(self, name: str) -> None:
        """Real re-exec from the same config: fresh interpreter, fresh
        ephemeral ports. The readiness handshake reports the new ports
        and the re-wire pass updates BOTH the restarted node's
        interfaces and every neighbor's facing interface; neighbors
        re-learn the new kvstore port from the Spark handshake
        (KvStore re-peers when a known neighbor's endpoint moves)."""
        pn = self.crashed.pop(name)
        pn.ready = {}
        self._spawn(pn)
        self.nodes[name] = pn
        await self._wait_ready([pn])
        await self._wire_links(names={name})

    async def hang_node(self, name: str) -> None:
        """SIGSTOP: the process exists but schedules nothing — TCP
        stays ESTABLISHED while hellos stop, the fault mode an asyncio
        cancel can't fake. Neighbors must detect via hold expiry."""
        pn = self.nodes.pop(name)
        pn.proc.send_signal(signal.SIGSTOP)
        self.hung[name] = pn

    async def resume_node(self, name: str) -> None:
        """SIGCONT a hung process; its timers fire late, its neighbors
        have long since withdrawn it, and it must re-converge."""
        pn = self.hung.pop(name)
        pn.proc.send_signal(signal.SIGCONT)
        self.nodes[name] = pn

    # ------------------------------------------------------------ partition

    async def partition(self, groups) -> None:
        """Cross-group links go down at the socket layer on both ends
        (same membership semantics as Cluster.partition; composes)."""
        all_names = set(self.nodes) | set(self.crashed) | set(self.hung)
        membership: dict[str, int] = {}
        for gi, group in enumerate(groups):
            for n in group:
                if n not in all_names:
                    raise ValueError(
                        f"partition group names unknown node {n!r}"
                    )
                membership[n] = gi
        for ls in self.links:
            ga, gb = membership.get(ls.a), membership.get(ls.b)
            if ga == gb and ga is not None:
                continue
            if ga is None and gb is None:
                continue
            await self._set_drop(ls.a, [ls.a_if], "add")
            await self._set_drop(ls.b, [ls.b_if], "add")
            self._partitioned.append(ls)

    async def heal_partition(self) -> None:
        healed, self._partitioned = self._partitioned, []
        for ls in healed:
            await self._set_drop(ls.a, [ls.a_if], "remove")
            await self._set_drop(ls.b, [ls.b_if], "remove")

    # ----------------------------------------------------- chaos: flap storm

    def make_storm(
        self,
        plan,
        *,
        duration_s: float = 2.0,
        n_flaps: int = 0,
        n_crashes: int = 0,
        n_partitions: int = 0,
        heal_after_s: float = 0.6,
        n_disk_faults: int = 0,
    ):
        """Deterministic fault schedule over this cluster's real link/
        node sets — same generator as the in-process emulator, so a
        seed replays identically on either harness. Disk-fault crashes
        (`n_disk_faults`) only bite here: the armed journal fault lands
        in a real process whose restart warm-boots through the damage."""
        return plan.build_storm(
            [(ls.a, ls.b) for ls in self.links],
            sorted(set(self.nodes) | set(self.crashed)),
            duration_s=duration_s,
            n_flaps=n_flaps,
            n_crashes=n_crashes,
            n_partitions=n_partitions,
            heal_after_s=heal_after_s,
            n_disk_faults=n_disk_faults,
        )
