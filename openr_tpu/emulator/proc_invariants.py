"""Cross-process port of the cluster invariant checker.

The in-process checker (emulator/invariants.py) reaches into live
OpenrNode objects; a ProcCluster's nodes are separate interpreters, so
every probe here crosses the ctrl RPC boundary instead — the same six
invariant classes, answered by the harness observation endpoints, plus
a seventh only a real process crash can exercise:

  1. **KvStore consistency** — ``get_kvstore_digest`` from every live
     process; per-area key/(version, originator, hash) sets must be
     identical fleet-wide.
  2. **FIB/oracle parity** — ``check_fib_oracle``: the from-scratch
     CPU-oracle solve runs *inside* each node process (where its LSDB
     lives) and only the verdict crosses the wire — at 100k prefixes
     shipping LSDBs to a central checker would dwarf the routing
     traffic under test.
  3. **No stuck state** — ``get_convergence_state``: init gates,
     Decision backlog, FIB desired-vs-programmed delta and retry
     backoff, per-peer sync/session/backlog/backoff.
  4. **Counter sanity** — ``get_counters``: rebuild-path counters sum
     to spf_runs, the peer add/remove ledger matches the live peer set,
     no residual FIB failure streak.
  5. **Bounded seam depth** — policied queue watermarks (riding the
     convergence-state payload) never exceeded cap + counted overflow.
  6. **Work proportionality** — ``work_ledger_control``: the ledger is
     per-PROCESS here (not one shared registry as in-proc), so each
     node is warmed and audited individually; a breach names the node
     it happened in.
  7. **Crash-consistent recovery** — ``get_persist_status``: a
     SIGKILLed-and-restarted node's boot-time recovery digests must be
     byte-identical to the pre-crash snapshot (snapshot_persist /
     check_persist_recovery), and no survivor may observe a
     withdrawal window across the cycle. Opt-in per crash (the other
     six are fleet sweeps; this one needs a before/after pair).

On failure the checker gathers flight-recorder rings from every
*surviving* process over ctrl (``get_flight_recorder`` — a SIGKILLed
node's ring dies with it; its absence is recorded in the manifest) and
writes one JSON per node under a fresh dump dir, with the chaos replay
seed embedded in the raised AssertionError.
"""

from __future__ import annotations

import asyncio
import json
import os
import tempfile
import time

from openr_tpu.emulator.invariants import (
    _DETAIL_CAP,
    WORK_EXEMPT_STAGES,
    Violation,
)
from openr_tpu.rpc import RpcError

_PROBE_TIMEOUT_S = 60.0  # per-node ctrl call budget (oracle solves included)


async def _probe(cluster, name: str, method: str, params=None):
    """One ctrl probe; an unreachable node is itself a violation (the
    process should be alive — crash_node moves it out of .nodes), so
    failures surface as (None, Violation) rather than raising."""
    try:
        res = await cluster.call(
            name, method, params or {}, timeout=_PROBE_TIMEOUT_S
        )
        return res, None
    except (RpcError, OSError, KeyError) as e:
        return None, Violation(
            "ctrl.unreachable", name, f"{method} failed: {e}"
        )


# ------------------------------------------------------- 1. kvstore identical


async def check_kvstore_consistency(cluster) -> list[Violation]:
    out: list[Violation] = []
    digests: dict[str, dict[str, dict]] = {}  # name -> area -> {k: triple}
    for name in sorted(cluster.nodes):
        res, bad = await _probe(cluster, name, "get_kvstore_digest")
        if bad:
            out.append(bad)
            continue
        digests[name] = {
            area: {k: tuple(v) for k, v in kv.items()}
            for area, kv in res["areas"].items()
        }
    areas = sorted({a for d in digests.values() for a in d})
    for area in areas:
        per_node = {
            n: d[area] for n, d in digests.items() if area in d
        }
        if not per_node:
            continue
        ref_name = min(per_node)
        ref = per_node[ref_name]
        for name, d in per_node.items():
            if d == ref:
                continue
            diff_keys = sorted(
                k for k in set(d) | set(ref) if d.get(k) != ref.get(k)
            )
            out.append(
                Violation(
                    "kvstore.divergence",
                    name,
                    f"area {area}: {len(diff_keys)} keys differ from "
                    f"{ref_name}'s store, e.g. {diff_keys[:_DETAIL_CAP]}",
                )
            )
    return out


# ------------------------------------------------------ 2. fib == oracle rib


async def check_fib_oracle_parity(cluster) -> list[Violation]:
    out: list[Violation] = []
    for name in sorted(cluster.nodes):
        res, bad = await _probe(cluster, name, "check_fib_oracle")
        if bad:
            out.append(bad)
            continue
        if res["pass"]:
            continue
        out.append(
            Violation(
                "fib.oracle_mismatch",
                name,
                f"{res['unicast_mismatches']} unicast / "
                f"{res['mpls_mismatches']} mpls routes differ from the "
                f"CPU-oracle rebuild, e.g. {res['sample'][:_DETAIL_CAP]}",
            )
        )
    return out


# ----------------------------------------------------------- 3. nothing stuck


def _stuck_from_state(name: str, st: dict) -> list[Violation]:
    out: list[Violation] = []
    if not st["initialized"]:
        out.append(
            Violation("node.uninitialized", name, "init gates not passed")
        )
    if st["decision_pending_kvs"] or st["decision_debounce_pending"]:
        out.append(
            Violation(
                "decision.pending",
                name,
                f"{st['decision_pending_kvs']} buffered kvs, debounce "
                f"pending={st['decision_debounce_pending']}",
            )
        )
    fib = st["fib"]
    if not fib["converged"]:
        out.append(
            Violation(
                "fib.unconverged",
                name,
                f"{fib['pending']} desired-vs-programmed deltas, "
                f"e.g. {fib['stale'][:_DETAIL_CAP]}",
            )
        )
    if fib["backoff_saturated"]:
        out.append(
            Violation(
                "fib.backoff_saturated",
                name,
                f"program backoff pinned at {fib['backoff_ms']} ms",
            )
        )
    elif fib["backoff_error"]:
        out.append(
            Violation(
                "fib.backoff_pending",
                name,
                f"retry backoff at {fib['backoff_ms']} ms",
            )
        )
    for p in st["peers"]:
        who = f"peer {p['peer']} (area {p['area']})"
        if not p["synced"]:
            out.append(
                Violation("kvstore.peer_unsynced", name, f"{who} not synced")
            )
        if not p["session"]:
            out.append(
                Violation(
                    "kvstore.peer_sessionless", name, f"{who} has no session"
                )
            )
        if p["pending_keys"] or p["pending_expired"]:
            out.append(
                Violation(
                    "kvstore.peer_flood_backlog",
                    name,
                    f"{who}: {p['pending_keys']} keys / "
                    f"{p['pending_expired']} expiries queued",
                )
            )
        if p["backoff_error"]:
            out.append(
                Violation(
                    "kvstore.peer_backoff",
                    name,
                    f"{who} sync backoff at {p['backoff_ms']} ms",
                )
            )
    return out


def _queue_bounds_from_state(name: str, st: dict) -> list[Violation]:
    """Class 5 over the watermarks riding the convergence payload —
    same COALESCE carve-out as the in-process checker (unmergeable
    admissions past the bound are counted, not breached)."""
    out: list[Violation] = []
    cap = st.get("queue_cap") or 0
    if cap <= 0:
        return out
    for q in st.get("queues", ()):
        if q["highwater"] > cap + q["overflow"]:
            out.append(
                Violation(
                    "queue.depth_breach",
                    name,
                    f"{q['key']} reader {q['reader']}: watermark "
                    f"{q['highwater']} > cap {cap} "
                    f"(+{q['overflow']} counted overflow)",
                )
            )
    return out


async def check_no_stuck_state(cluster) -> list[Violation]:
    out: list[Violation] = []
    for name in sorted(cluster.nodes):
        st, bad = await _probe(cluster, name, "get_convergence_state")
        if bad:
            out.append(bad)
            continue
        out += _stuck_from_state(name, st)
        out += _queue_bounds_from_state(name, st)
    return out


# ---------------------------------------------------------- 4. counter sanity


async def check_counter_sanity(cluster) -> list[Violation]:
    out: list[Violation] = []
    for name in sorted(cluster.nodes):
        c, bad = await _probe(cluster, name, "get_counters")
        if bad:
            out.append(bad)
            continue
        st, bad = await _probe(cluster, name, "get_convergence_state")
        if bad:
            out.append(bad)
            continue
        full = c.get("decision.rebuild.full", 0)
        pfx = c.get("decision.rebuild.prefix_only", 0)
        delta = c.get("decision.rebuild.topo_delta", 0)
        runs = c.get("decision.spf_runs", 0)
        if full + pfx + delta != runs:
            out.append(
                Violation(
                    "counters.rebuild_sum",
                    name,
                    f"rebuild.full({full}) + rebuild.prefix_only({pfx}) "
                    f"+ rebuild.topo_delta({delta}) != spf_runs({runs})",
                )
            )
        live_peers = len(st["peers"])
        added = c.get("kvstore.peers_added", 0)
        removed = c.get("kvstore.peers_removed", 0)
        if added - removed != live_peers:
            out.append(
                Violation(
                    "counters.peer_ledger",
                    name,
                    f"peers_added({added}) - peers_removed({removed}) "
                    f"!= live peers({live_peers})",
                )
            )
        streak = c.get("fib.program_fail_streak", 0)
        if streak:
            out.append(
                Violation(
                    "counters.fib_fail_streak",
                    name,
                    f"fib.program_fail_streak={streak} after quiescence",
                )
            )
    return out


# ------------------------------------------------- 6. work proportionality


async def mark_fleet_warm(cluster) -> None:
    """Arm the work-proportionality gate: each PROCESS has its own
    ledger, so every live node is marked individually (the in-process
    emulator marks one shared registry). Call after the first converged
    round so warmup work (full syncs, first solves) is baseline, not
    breach."""
    for name in sorted(cluster.nodes):
        await _probe(
            cluster, name, "work_ledger_control", {"op": "mark_warm"}
        )


async def check_work_ratios(cluster) -> list[Violation]:
    out: list[Violation] = []
    for name in sorted(cluster.nodes):
        res, bad = await _probe(
            cluster, name, "work_ledger_control",
            {"op": "violations", "exempt": list(WORK_EXEMPT_STAGES)},
        )
        if bad:
            out.append(bad)
            continue
        if not res["warm_marked"]:
            continue
        for v in res["violations"]:
            out.append(
                Violation(
                    "work.ratio_breach",
                    name,
                    f"stage {v['stage']}: worst steady round touched "
                    f"{v['touched']} entities for delta {v['delta']} "
                    f"(ratio {v['ratio']:.1f}, bound {v['bound']:.0f}) — "
                    "a full-table walk crept into a delta-proportional "
                    "stage",
                )
            )
    return out


# ------------------------------------------- 7. crash-consistent recovery


#: survivor counters that tick iff a peer's keys expired / an adjacency
#: dropped — the observables of a "withdrawal window" during a crash
_WITHDRAWAL_COUNTERS = ("kvstore.expired_keys", "linkmonitor.neighbor_down")


async def snapshot_persist(cluster, victim: str) -> dict:
    """Pre-crash snapshot for the persistence invariant. Call at
    quiescence, BEFORE arming any disk fault: captures the victim's
    durable book digests (the byte-parity token) and every survivor's
    withdrawal-window counters. The contract with
    :func:`check_persist_recovery` is that mutations between this
    snapshot and the SIGKILL are the doomed, fault-eaten ones — so the
    restarted incarnation must recover *exactly* this state."""
    status = await cluster.get_persist_status(victim)
    if not status.get("enabled"):
        raise RuntimeError(f"persistence disabled on {victim}")
    books = {
        name: b["digest"]
        for name, b in (status.get("books") or {}).items()
        if b["records"]
    }
    watch: dict[str, dict[str, float]] = {}
    for name in sorted(cluster.nodes):
        if name == victim:
            continue
        c, _bad = await _probe(cluster, name, "get_counters")
        if c is not None:
            watch[name] = {k: c.get(k, 0) for k in _WITHDRAWAL_COUNTERS}
    return {"victim": victim, "books": books, "watch": watch}


async def check_persist_recovery(cluster, pre: dict) -> list[Violation]:
    """Post-restart half of the crash-recovery invariant:

    * **byte parity** — the restarted process's boot-time recovery
      digests (what actually came off disk, per book) equal the
      pre-crash snapshot's, even with torn/corrupt/ENOSPC faults armed
      in between (the doomed records must be discarded, never
      half-applied);
    * **zero withdrawal window** — no survivor saw the victim's keys
      expire or the adjacency drop across the whole crash+restart cycle
      (graceful-restart hold + warm boot keep the fleet's view intact).
    """
    out: list[Violation] = []
    victim = pre["victim"]
    status, bad = await _probe(cluster, victim, "get_persist_status")
    if bad:
        return [bad]
    rec_books: dict[str, str] = (status.get("recovery") or {}).get(
        "books"
    ) or {}
    for name, digest in sorted(pre["books"].items()):
        got = rec_books.get(name)
        if got is None:
            out.append(
                Violation(
                    "persist.book_lost",
                    victim,
                    f"book {name!r} ({digest[:12]}…) not recovered from "
                    "disk",
                )
            )
        elif got != digest:
            out.append(
                Violation(
                    "persist.parity",
                    victim,
                    f"book {name!r} recovered {got[:12]}… != pre-crash "
                    f"{digest[:12]}… — the journal replayed different "
                    "bytes than the crashed incarnation held durable",
                )
            )
    for name, base in sorted(pre["watch"].items()):
        c, bad = await _probe(cluster, name, "get_counters")
        if bad:
            out.append(bad)
            continue
        for key, was in base.items():
            now = c.get(key, 0)
            if now > was:
                out.append(
                    Violation(
                        "persist.withdrawal_window",
                        name,
                        f"{key} rose {was:g} → {now:g} across the "
                        f"crash/restart of {victim} — a survivor "
                        "observed a withdrawal window",
                    )
                )
    return out


# ------------------------------------------------- flight-recorder dumps


async def dump_flight_recorders(
    cluster, violations=None, label: str = "invariant-failure"
) -> str | None:
    """Gather every SURVIVING process's flight-recorder ring + counter
    snapshot over ctrl into one JSON per node under a fresh dump dir.
    A hard-killed process's ring died with it; the dump manifest lists
    those holes explicitly so a post-mortem reader knows the silence
    is the fault, not a gap in the tooling."""
    names = sorted({v.node for v in (violations or []) if v.node})
    if not names or any(v.node is None for v in (violations or [])):
        names = sorted(cluster.nodes)
    dump_dir = tempfile.mkdtemp(prefix="openr-flight-")
    wrote, missing = [], []
    for name in names:
        fr, bad = await _probe(cluster, name, "get_flight_recorder")
        if bad:
            missing.append({"node": name, "why": bad.detail})
            continue
        counters, _ = await _probe(cluster, name, "get_counters")
        payload = {
            "node": name,
            "label": label,
            "wrote_at": time.time(),  # orlint: disable=OR006 — post-mortem artifact metadata, not a seeded decision
            "violations": [
                str(v) for v in (violations or []) if v.node in (name, None)
            ],
            "events": fr["events"],
            "counters": counters or {},
        }
        path = os.path.join(dump_dir, f"{name}.json")
        await asyncio.to_thread(
            _write_json, path, payload
        )
        wrote.append(name)
    manifest = {
        "label": label,
        "gathered": wrote,
        "unreachable": missing,
        "crashed_at_dump": sorted(cluster.crashed),
    }
    await asyncio.to_thread(
        _write_json, os.path.join(dump_dir, "MANIFEST.json"), manifest
    )
    return dump_dir if wrote or missing else None


def _write_json(path: str, payload: dict) -> None:
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, default=str)


async def _flight_hint(cluster, violations, label: str) -> str:
    try:
        d = await dump_flight_recorders(cluster, violations, label=label)
    except asyncio.CancelledError:
        raise
    except Exception:  # noqa: BLE001 — the dump must never mask the failure
        return ""
    return f"\nflight-recorder dumps: {d}" if d else ""


# -------------------------------------------------------------- entry points


async def check_cluster(cluster) -> list[Violation]:
    """All six invariant classes over ctrl; cheap single-payload checks
    first so a settling cluster fails fast, the per-node oracle solves
    last."""
    out = await check_no_stuck_state(cluster)  # includes queue bounds
    out += await check_work_ratios(cluster)
    out += await check_kvstore_consistency(cluster)
    out += await check_counter_sanity(cluster)
    out += await check_fib_oracle_parity(cluster)
    return out


async def assert_invariants(cluster, context: str = "") -> None:
    violations = await check_cluster(cluster)
    if violations:
        hint = f" (replay: {context})" if context else ""
        lines = "\n  ".join(str(v) for v in violations)
        flight = await _flight_hint(
            cluster, violations, label=context or "assert"
        )
        raise AssertionError(
            f"{len(violations)} cluster invariant violation(s){hint}:\n"
            f"  {lines}{flight}"
        )


async def wait_quiescent(
    cluster,
    timeout_s: float = 60.0,
    poll_s: float = 0.5,
    context: str = "",
) -> None:
    """Converged AND two consecutive clean invariant sweeps, or raise
    with the replay seed and a flight-recorder gather — the gate every
    multi-process chaos round ends with. The oracle-parity probe runs
    a real solve per node per sweep, hence the longer default poll."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout_s
    clean = 0
    last: list[Violation] = []
    while True:
        if not await cluster.converged():
            last = [
                Violation(
                    "cluster.unconverged",
                    None,
                    "cluster.converged() is False",
                )
            ]
            clean = 0
        else:
            last = await check_cluster(cluster)
            clean = 0 if last else clean + 1
            if clean >= 2:
                return
        if loop.time() >= deadline:
            hint = f" (replay: {context})" if context else ""
            lines = "\n  ".join(str(v) for v in last[:8])
            flight = await _flight_hint(
                cluster, last, label=context or "quiesce-timeout"
            )
            raise AssertionError(
                f"proc cluster failed to quiesce within {timeout_s:.0f}s"
                f"{hint}; last violations:\n  {lines}{flight}"
            )
        await asyncio.sleep(poll_s)
