"""Trace-derived convergence measurement over the in-process emulator.

Spins a small full-stack cluster (real Spark/LinkMonitor/KvStore/
Decision/Fib modules over mock I/O), forces link-down events, and reads
the resulting PerfEvents traces out of each node's Monitor ring — so the
reported convergence latency is the per-stage instrumented pipeline
time (NEIGHBOR_EVENT → FIB_PROGRAMMED), not a wall-clock guess around
the whole cluster. bench.py embeds this as its `convergence_p50_ms`
field; it runs on the CPU oracle backend and never touches jax.
"""

from __future__ import annotations

import asyncio
from dataclasses import replace

from openr_tpu.emulator.cluster import Cluster
from openr_tpu.monitor import flood_trace, perf
from openr_tpu.monitor.fleet import percentile as _percentile


def _trace_every_1(ncfg):
    """Sample EVERY origination: the bench cluster is 4 nodes, so full
    tracing is cheap and every link-down's adjacency re-advertisement
    carries a hop span — the attribution source."""
    return replace(
        ncfg, kvstore=replace(ncfg.kvstore, trace_sample_every=1)
    )


async def collect_convergence_traces(
    trials: int = 3, timeout_s: float = 20.0
) -> tuple[list, list[dict]]:
    """Run `trials` link-down events on a 4-node cluster; return every
    completed PerfEvents trace (ending FIB_PROGRAMMED) they produced,
    plus the completed cross-node flood spans (jsonable dicts) for the
    per-stage attribution."""
    # triangle + stub: failing a-b leaves both endpoints reachable, so
    # every link-down yields route CHANGES (reroute via c) on live nodes
    c = Cluster.from_edges(
        [("a", "b"), ("b", "c"), ("a", "c"), ("c", "d")],
        solver="cpu",
        node_config_transform=_trace_every_1,
    )
    await c.start()
    traces: list = []
    try:
        await c.wait_converged(timeout=timeout_s)
        for _ in range(trials):
            # baseline on the monotonic completed-trace COUNTER, not the
            # ring length — the ring is a bounded deque whose length
            # stops growing once full, which would blind later trials
            seen_before = {
                name: _trace_count(node)
                for name, node in c.nodes.items()
            }
            c.fail_link("a", "b")
            got = await _wait_new_traces(c, seen_before, timeout_s)
            traces.extend(got)
            c.heal_link("a", "b")
            await c.wait_converged(timeout=timeout_s)
            # let the heal's own traces land before the next baseline
            await asyncio.sleep(0.3)
        from openr_tpu.emulator.tracing import collect_flood_traces

        flood = collect_flood_traces(c)
    finally:
        await c.stop()
    return (
        [
            t
            for t in traces
            if t.last_event() == perf.FIB_PROGRAMMED and len(t.events) >= 5
        ],
        flood,
    )


def _trace_count(node) -> int:
    return int(node.counters.get("monitor.perf_traces", 0))


async def _wait_new_traces(
    c: Cluster, seen_before: dict[str, int], timeout_s: float
) -> list:
    """Wait until at least one node's Monitor completed a new link-down
    trace, then give stragglers a short grace window."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout_s

    def new_traces() -> list:
        out = []
        for name, node in c.nodes.items():
            n_new = _trace_count(node) - seen_before[name]
            if n_new > 0:
                ring = list(node.monitor.perf_traces)
                out.extend(ring[-n_new:])
        return out

    while loop.time() < deadline:
        if new_traces():
            break
        await asyncio.sleep(0.05)
    await asyncio.sleep(0.5)  # grace: the other nodes' fibs finish too
    return new_traces()


def measure_convergence(trials: int = 3, timeout_s: float = 20.0) -> dict:
    """Synchronous wrapper for bench harnesses: p50/p99 of trace-derived
    link-down convergence plus sample counts, and the hop-span-derived
    `convergence_attribution` (per-stage p50 across the sampled flood
    spans — docs/Monitor.md "Flood tracing"). Returns convergence_p50_ms
    None only when no trace completed (reported, never raised)."""
    try:
        traces, flood = asyncio.run(
            collect_convergence_traces(trials=trials, timeout_s=timeout_s)
        )
    except Exception as e:  # noqa: BLE001 — a bench must not die on this
        return {"convergence_p50_ms": None, "error": f"{type(e).__name__}: {e}"}
    if not traces:
        return {"convergence_p50_ms": None, "traces": 0}
    totals = [t.total_ms() for t in traces]
    attr = flood_trace.attribution(flood)
    return {
        "convergence_p50_ms": round(_percentile(totals, 0.5), 3),
        "convergence_p99_ms": round(_percentile(totals, 0.99), 3),
        "traces": len(traces),
        "trials": trials,
        "stages_p50": {
            ev: round(v, 3)
            for ev, v in _stage_p50(traces).items()
        },
        # named-stage breakdown from the hop spans: where along the
        # flooding mesh + pipeline the end-to-end time actually went
        "convergence_attribution": attr.get("stages_p50_ms"),
        "attribution_coverage_p50": attr.get("coverage_p50"),
        "flood_traces": attr.get("traces", 0),
    }


def _stage_p50(traces: list) -> dict[str, float]:
    """Median per-stage delta across traces, keyed by stage marker."""
    per_stage: dict[str, list[float]] = {}
    for t in traces:
        for ev, d in t.deltas()[1:]:
            per_stage.setdefault(ev, []).append(d)
    return {ev: _percentile(v, 0.5) for ev, v in sorted(per_stage.items())}
