"""Post-quiescence cluster invariant checker for the emulator.

Six invariant classes over a quiesced Cluster (storm over, rate faults
off, structural faults healed):

  1. **KvStore consistency** — every node's KvStoreDb in an area is
     key/version/originator/hash-identical (the flood + full-sync repair
     machinery converged to one winner everywhere).
  2. **FIB/oracle parity** — every node's programmed FIB equals a fresh
     from-scratch CPU-oracle solve over that node's *own* LinkState —
     the check that catches stale dirty-scoped cache reuse (PR-2's
     per-area RIB/SolveArtifact caches) after fault-driven invalidation.
  3. **No stuck state** — no pending publication backlogs, flood queues
     or desired-vs-programmed FIB deltas; no lingering (let alone
     saturated) retry backoffs; all peers synced with live sessions.
  4. **Counter sanity** — cross-counter identities hold (rebuild-path
     counters sum to the rebuild count, peer add/remove deltas match the
     live peer set, no residual failure streaks).
  5. **Bounded seam depth** — no policied messaging queue's depth
     watermark ever exceeded its configured cap (the overload policies
     absorbed every burst at the bound); the long-horizon memory
     watermark lives in the soak runner (emulator/soak.py), which needs
     cross-round state this single-shot checker doesn't have.
  6. **Work proportionality** — once the soak marks the work ledger
     warm (after its round-0 baseline), every delta-proportional
     dataflow stage (dirt / election / assembly / fib) must keep each
     steady round's touched-entity count within k*delta + floor
     (docs/Monitor.md "Work ledger"); a breach means a full-table walk
     crept back into a scoped path, and lands a ``work.ratio_breach``
     flight-recorder event on every node for the post-mortem dump.

`wait_quiescent` polls until all of these hold (twice consecutively, so a
mid-flight sample can't pass by luck) or raises with the chaos replay
hint — a failing soak always prints the seed needed to reproduce it.
"""

from __future__ import annotations

import asyncio
import json
import os
import tempfile
import time
from dataclasses import dataclass

from openr_tpu.decision.decision import merge_area_ribs
from openr_tpu.decision.oracle import compute_routes as oracle_compute_routes

_DETAIL_CAP = 3  # sample size for mismatch listings


@dataclass(frozen=True)
class Violation:
    kind: str  # e.g. "kvstore.divergence", "fib.oracle_mismatch"
    node: str | None
    detail: str

    def __str__(self) -> str:
        where = f"[{self.node}] " if self.node else ""
        return f"{self.kind}: {where}{self.detail}"


# ------------------------------------------------------- 1. kvstore identical


def check_kvstore_consistency(cluster) -> list[Violation]:
    """All live nodes in an area hold the identical key/version/hash set
    (TTL countdowns are per-store clocks and excluded by design)."""
    out: list[Violation] = []
    areas: set[str] = set()
    for node in cluster.nodes.values():
        areas.update(node.kvstore.dbs)
    for area in sorted(areas):
        digests: dict[str, dict] = {}
        for name, node in cluster.nodes.items():
            db = node.kvstore.dbs.get(area)
            if db is None:
                continue
            digests[name] = {
                k: (v.version, v.originator_id, v.with_hash().hash)
                for k, v in db.kv.items()
            }
        if not digests:
            continue
        ref_name = min(digests)
        ref = digests[ref_name]
        for name, d in digests.items():
            if d == ref:
                continue
            diff_keys = sorted(
                k
                for k in set(d) | set(ref)
                if d.get(k) != ref.get(k)
            )
            out.append(
                Violation(
                    "kvstore.divergence",
                    name,
                    f"area {area}: {len(diff_keys)} keys differ from "
                    f"{ref_name}'s store, e.g. {diff_keys[:_DETAIL_CAP]}",
                )
            )
    return out


# ------------------------------------------------------ 2. fib == oracle rib


def check_fib_oracle_parity(cluster) -> list[Violation]:
    """Each node's programmed FIB must be byte-equal to a from-scratch
    CPU-oracle rebuild over that node's own LSDB — independent of the
    node's own solver backend (tpu or cpu) and of every incremental /
    dirty-scoped cache the live pipeline used. Nodes with an installed
    RibPolicy are skipped (the policy mutates routes after the solve)."""
    out: list[Violation] = []
    for name, node in cluster.nodes.items():
        dec = node.decision
        if dec.rib_policy is not None:
            continue
        dcfg = node.config.node.decision
        link_states = dec.link_states  # property: drains pending pubs
        prefix_states = dec.prefix_states
        per_area = {
            a: oracle_compute_routes(
                link_states[a].snapshot(),
                prefix_states[a].snapshot(),
                name,
                enable_lfa=dcfg.enable_lfa,
                ksp_k=dcfg.ksp_paths,
            )
            for a in link_states
        }
        want = merge_area_ribs(per_area, name)
        want_u = {
            p: e.to_unicast_route() for p, e in want.unicast_routes.items()
        }
        want_m = {
            l: e.to_mpls_route() for l, e in want.mpls_routes.items()
        }
        got_u = node.fib.programmed_unicast
        got_m = node.fib.programmed_mpls
        if got_u != want_u:
            diff = sorted(
                str(p)
                for p in set(got_u) | set(want_u)
                if got_u.get(p) != want_u.get(p)
            )
            out.append(
                Violation(
                    "fib.oracle_mismatch",
                    name,
                    f"{len(diff)} unicast routes differ from the "
                    f"CPU-oracle rebuild, e.g. {diff[:_DETAIL_CAP]}",
                )
            )
        if got_m != want_m:
            diff_l = sorted(
                l
                for l in set(got_m) | set(want_m)
                if got_m.get(l) != want_m.get(l)
            )
            out.append(
                Violation(
                    "fib.oracle_mismatch_mpls",
                    name,
                    f"{len(diff_l)} mpls routes differ from the "
                    f"CPU-oracle rebuild, e.g. {diff_l[:_DETAIL_CAP]}",
                )
            )
    return out


# ----------------------------------------------------------- 3. nothing stuck


def check_no_stuck_state(cluster) -> list[Violation]:
    out: list[Violation] = []
    for name, node in cluster.nodes.items():
        if not node.initialized:
            out.append(
                Violation("node.uninitialized", name, "init gates not passed")
            )
        dec = node.decision
        if dec._pending_kvs or dec.debounce.pending:
            out.append(
                Violation(
                    "decision.pending",
                    name,
                    f"{len(dec._pending_kvs)} buffered kvs, "
                    f"debounce pending={dec.debounce.pending}",
                )
            )
        pc = node.fib.pending_changes()
        if not pc["converged"]:
            out.append(
                Violation(
                    "fib.unconverged",
                    name,
                    f"{pc['pending']} desired-vs-programmed deltas, "
                    f"e.g. {pc['stale'][:_DETAIL_CAP]}",
                )
            )
        fib_cfg = node.config.node.fib
        if node.fib.backoff.current_ms >= fib_cfg.max_retry_ms:
            out.append(
                Violation(
                    "fib.backoff_saturated",
                    name,
                    f"program backoff pinned at {fib_cfg.max_retry_ms} ms",
                )
            )
        elif node.fib.backoff.has_error:
            out.append(
                Violation(
                    "fib.backoff_pending",
                    name,
                    f"retry backoff at {node.fib.backoff.current_ms} ms",
                )
            )
        for (area, pname), peer in node.kvstore.peers.items():
            if not peer.synced:
                out.append(
                    Violation(
                        "kvstore.peer_unsynced",
                        name,
                        f"peer {pname} (area {area}) not synced",
                    )
                )
            if peer.session is None:
                out.append(
                    Violation(
                        "kvstore.peer_sessionless",
                        name,
                        f"peer {pname} (area {area}) has no session",
                    )
                )
            if peer.pending_keys or peer.pending_expired:
                out.append(
                    Violation(
                        "kvstore.peer_flood_backlog",
                        name,
                        f"peer {pname}: {len(peer.pending_keys)} keys / "
                        f"{len(peer.pending_expired)} expiries queued",
                    )
                )
            if peer.backoff.has_error:
                out.append(
                    Violation(
                        "kvstore.peer_backoff",
                        name,
                        f"peer {pname} sync backoff at "
                        f"{peer.backoff.current_ms} ms",
                    )
                )
    return out


# ---------------------------------------------------------- 4. counter sanity


def check_counter_sanity(cluster) -> list[Violation]:
    out: list[Violation] = []
    for name, node in cluster.nodes.items():
        c = node.counters
        full = c.get("decision.rebuild.full")
        pfx = c.get("decision.rebuild.prefix_only")
        delta = c.get("decision.rebuild.topo_delta")
        runs = c.get("decision.spf_runs")
        if full + pfx + delta != runs:
            out.append(
                Violation(
                    "counters.rebuild_sum",
                    name,
                    f"rebuild.full({full}) + rebuild.prefix_only({pfx}) "
                    f"+ rebuild.topo_delta({delta}) != spf_runs({runs})",
                )
            )
        live_peers = len(node.kvstore.peers)
        added = c.get("kvstore.peers_added")
        removed = c.get("kvstore.peers_removed")
        if added - removed != live_peers:
            out.append(
                Violation(
                    "counters.peer_ledger",
                    name,
                    f"peers_added({added}) - peers_removed({removed}) "
                    f"!= live peers({live_peers})",
                )
            )
        streak = c.get("fib.program_fail_streak")
        if streak:
            out.append(
                Violation(
                    "counters.fib_fail_streak",
                    name,
                    f"fib.program_fail_streak={streak} after quiescence",
                )
            )
    return out


# ------------------------------------------------------ 5. bounded seam depth


def check_queue_bounds(cluster) -> list[Violation]:
    """Overload-control invariant: no policied inter-module queue's depth
    WATERMARK may have exceeded the node's configured cap — the overflow
    policies (coalesce / shed-oldest / block, openr_tpu/messaging) must
    have absorbed every burst at the bound. A node built with
    `messaging.enforce_bounds=False` keeps its cap configured but its
    queues unbounded, so this check failing on it is the *control case*
    proving the watermark detector works (tests/test_soak.py)."""
    out: list[Violation] = []
    for name, node in cluster.nodes.items():
        cap = node.config.node.messaging.queue_maxsize
        if cap <= 0:
            continue
        for key, q in getattr(node, "queues", {}).items():
            if q.policy is None:
                continue  # control-event seams are unbounded by design
            for r in q.readers:
                # COALESCE deliberately admits unmergeable items past
                # the bound, one per counted overflow — those admissions
                # are designed behavior, not a breach
                if r.highwater > cap + r.overflow:
                    out.append(
                        Violation(
                            "queue.depth_breach",
                            name,
                            f"{key} reader {r.name}: watermark "
                            f"{r.highwater} > cap {cap} "
                            f"(+{r.overflow} counted overflow)",
                        )
                    )
    return out


# ------------------------------------------------- 6. work proportionality


#: stages that are honestly super-delta by design and therefore exempt
#: from the soak proportionality gate (docs/Monitor.md "Work ledger"):
#: spf_full is O(area), spf_warm is O(region), merge_full is the
#: counter-asserted fallback fold (first build / policy / revision
#: mismatch — honest O(routes), like spf_full), full_sync is O(store)
#: — and under storm-driven topology dirt the full-table route diff is
#: honestly O(tables) too (a metric change can move any route), so diff
#: is only gated in prefix-only regimes the soak never is. `merge` and
#: `redistribute` are delta-native since ISSUE 17 (merge book + entry
#: books) and are deliberately NOT exempt: a full-table walk creeping
#: back into either trips this gate.
WORK_EXEMPT_STAGES = (
    "spf_full",
    "spf_warm",
    "merge_full",
    "full_sync",
    "fib_resync",  # periodic / post-failure full-table reprogram (O(table), delta 0 by design)
    "diff",
)


def check_work_ratios(cluster) -> list[Violation]:
    """Delta-proportionality gate over the process-global work ledger
    (openr_tpu/monitor/work_ledger.py): once a soak round has marked the
    ledger warm, no delta-proportional stage (dirt / election / assembly
    / merge / fib / redistribute) may have a steady round whose
    touched-entity count exceeds
    k*delta + floor. Inactive until ``mark_warm()`` — a single-shot
    ``assert_invariants`` on a fresh cluster never trips on warmup work.
    The ledger is per-process, so in the emulator a breach is a
    cluster-wide fact (node=None); the flight-recorder event lands on
    every node so any post-mortem dump carries it."""
    from openr_tpu.monitor import work_ledger

    if not work_ledger.ledger().warm_marked:
        return []
    out: list[Violation] = []
    for v in work_ledger.steady_violations(exempt=WORK_EXEMPT_STAGES):
        out.append(
            Violation(
                "work.ratio_breach",
                None,
                f"stage {v['stage']}: worst steady round touched "
                f"{v['touched']} entities for delta {v['delta']} "
                f"(ratio {v['ratio']:.1f}, bound {v['bound']:.0f}) — "
                "a full-table walk crept into a delta-proportional stage",
            )
        )
        for node in cluster.nodes.values():
            fr = getattr(node.counters, "flight_record", None)
            if fr is not None:
                fr(
                    "work.ratio_breach",
                    stage=v["stage"],
                    touched=v["touched"],
                    delta=v["delta"],
                    ratio=round(v["ratio"], 2),
                    bound=v["bound"],
                )
    return out


# ------------------------------------------------- flight-recorder dumps


def dump_flight_recorders(
    cluster, violations=None, label: str = "invariant-failure"
) -> str | None:
    """Write every involved node's flight-recorder ring (plus its raw
    counter snapshot) as one JSON file per node under a fresh dump
    directory, and return that directory — the post-mortem artifact a
    failing soak attaches next to its replay seed (docs/Emulator.md).

    "Involved" = the nodes the violations name; violations that name no
    node (cluster-wide checks) widen the dump to every live node. Nodes
    without a recorder (bare clusters built outside OpenrNode) are
    skipped; returns None when nothing was dumpable."""
    names = sorted({v.node for v in (violations or []) if v.node})
    if not names or any(v.node is None for v in (violations or [])):
        names = sorted(cluster.nodes)
    targets = [
        (n, cluster.nodes[n])
        for n in names
        if n in cluster.nodes
        and getattr(cluster.nodes[n], "flight", None) is not None
    ]
    if not targets:
        return None
    dump_dir = tempfile.mkdtemp(prefix="openr-flight-")
    for name, node in targets:
        payload = {
            "node": name,
            "label": label,
            "wrote_at": time.time(),  # orlint: disable=OR006 — post-mortem artifact metadata, not a seeded decision
            "violations": [
                str(v) for v in (violations or []) if v.node in (name, None)
            ],
            "events": node.flight.dump(),
            # raw counters only (no expanded stat percentiles — they
            # triple the file for no post-mortem value)
            "counters": dict(node.counters.counters),
        }
        path = os.path.join(dump_dir, f"{name}.json")
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=str)
    return dump_dir


def _flight_hint(cluster, violations, label: str) -> str:
    try:
        d = dump_flight_recorders(cluster, violations, label=label)
    except Exception:  # noqa: BLE001 — the dump must never mask the failure
        return ""
    return f"\nflight-recorder dumps: {d}" if d else ""


# -------------------------------------------------------------- entry points


def check_cluster(cluster) -> list[Violation]:
    """All six invariant classes; cheap checks first so the poll loop
    fails fast while the cluster is still settling."""
    out = check_no_stuck_state(cluster)
    out += check_queue_bounds(cluster)
    out += check_work_ratios(cluster)
    out += check_kvstore_consistency(cluster)
    out += check_counter_sanity(cluster)
    out += check_fib_oracle_parity(cluster)
    return out


def assert_invariants(cluster, context: str = "") -> None:
    """Single-shot assertion; `context` (e.g. the ChaosPlan replay hint)
    is embedded in the failure message so any failing run is replayable
    from its seed."""
    violations = check_cluster(cluster)
    if violations:
        hint = f" (replay: {context})" if context else ""
        lines = "\n  ".join(str(v) for v in violations)
        flight = _flight_hint(cluster, violations, label=context or "assert")
        raise AssertionError(
            f"{len(violations)} cluster invariant violation(s){hint}:\n"
            f"  {lines}{flight}"
        )


async def wait_quiescent(
    cluster,
    timeout_s: float = 30.0,
    poll_s: float = 0.25,
    context: str = "",
) -> None:
    """Poll until the cluster converges AND all invariants hold on two
    consecutive checks; on timeout raise with the last violations and
    the replay context. This is the post-storm gate every chaos soak
    ends with."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout_s
    clean = 0
    last: list[Violation] = []
    while True:
        if not cluster.converged():
            last = [
                Violation(
                    "cluster.unconverged",
                    None,
                    "cluster.converged() is False",
                )
            ]
            clean = 0
        else:
            last = check_cluster(cluster)
            clean = 0 if last else clean + 1
            if clean >= 2:
                return
        if loop.time() >= deadline:
            hint = f" (replay: {context})" if context else ""
            lines = "\n  ".join(str(v) for v in last[:8])
            flight = _flight_hint(
                cluster, last, label=context or "quiesce-timeout"
            )
            raise AssertionError(
                f"cluster failed to quiesce within {timeout_s:.0f}s"
                f"{hint}; last violations:\n  {lines}{flight}"
            )
        await asyncio.sleep(poll_s)
