"""Cluster-side flood-trace collector for the in-process emulator.

Walks every node's Monitor perf ring for completed *sampled* flood
traces (``PerfEvents.trace_id`` set, span ends at FIB_PROGRAMMED) and
feeds them to the pure assembly math in
``openr_tpu/monitor/flood_trace.py`` — waterfalls, propagation trees,
and the per-stage ``convergence_attribution`` the benchmarks report.

The emulator shares one process (one monotonic clock), so cross-node
stage deltas here are exact — this is the regime the waterfall's
attribution acceptance (≥95% of end-to-end time named) is defined in.
"""

from __future__ import annotations

from openr_tpu.monitor import flood_trace, perf


def collect_flood_traces(cluster) -> list[dict]:
    """Every completed sampled flood span across the cluster, as the
    jsonable trace dicts the assembly math consumes (one entry per
    completing node per trace — a 9-node flood yields up to 9 spans of
    one trace_id)."""
    out: list[dict] = []
    for node in cluster.nodes.values():
        for tr in node.monitor.perf_traces:
            if (
                getattr(tr, "trace_id", 0)
                and tr.last_event() == perf.FIB_PROGRAMMED
            ):
                out.append(tr.to_jsonable())
    return out


def trace_report(cluster) -> dict:
    """One-call summary for benches and CI gates: completions, deepest
    path, per-stage p50 attribution, and waterfall-vs-total agreement.

    ``waterfall_ok`` counts spans whose named stages sum to within 5%
    of the span's end-to-end total — the "no silent gap" check the
    flood-trace smoke lane asserts on."""
    traces = collect_flood_traces(cluster)
    attr = flood_trace.attribution(traces)
    falls = [
        w for w in (flood_trace.waterfall(t) for t in traces)
        if w is not None
    ]
    ok = sum(1 for w in falls if abs(1.0 - w["coverage"]) <= 0.05)
    multi_hop = sum(1 for w in falls if w["hops"] >= 1)
    return {
        "completions": len(falls),
        "multi_hop_completions": multi_hop,
        "max_hops": max((w["hops"] for w in falls), default=0),
        "waterfall_ok": ok,
        "waterfall_ok_frac": round(ok / len(falls), 4) if falls else None,
        "trees": len(flood_trace.propagation_tree(traces)),
        "attribution": attr,
    }
