"""Deterministic chaos injection over the emulator's three I/O seams.

reference: the reference platform validates its recovery machinery with
fault-driven integration tests (OpenrTest churn scenarios †, KvStore
flooding under peer churn †, MockNetlinkFibHandler failure injection †);
DeltaPath (PAPERS.md) argues incremental routing engines are exactly
where fault-driven state divergence hides. This module makes those
storms *seeded and replayable*:

  * ``ChaosPlan`` — one seeded RNG namespace + a deterministic fault
    schedule. The same seed (and builder arguments) always produces the
    identical schedule (`schedule_hash`), and every failure message from
    the invariant checker carries the seed needed to replay the run.
  * ``ChaosIoHub`` — MockIoHub whose per-delivery seam drops, delays
    (reorders), or duplicates Spark packets per link.
  * ``ChaosKvTransport`` / ``_ChaosKvSession`` — per-node wrapper around
    InProcKvTransport: failed ``full_sync``/``flood`` calls (the session
    is torn down by KvStore's own recovery path), delivery delay, and
    hard partition blocks.
  * ``ChaosFibHandler`` — MockFibHandler driven by the plan's seeded
    rate-based failure injection, gated by ``plan.active`` so the
    cluster can quiesce for the invariant check.
  * ``ChaosPlan.disk_injector`` / the ``disk_fault`` event kind — the
    durable-storage seam (docs/Persist.md): seeded one-shot journal
    faults (torn write, corrupt record, ENOSPC) armed in a victim's
    persist plane right before a hard kill, so the storm also proves
    warm-boot recovery through damaged journals.

The *schedule* (which link flaps when, who crashes, how the cluster
partitions) is derived purely from the seed, so it is deterministic.
Per-packet fault decisions consume seeded substreams too, but their
interleaving follows runtime packet order — replaying a seed reproduces
the same storm shape and fault rates, not a byte-identical packet log.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import random
from dataclasses import dataclass, field

from openr_tpu.fib.fib import MockFibHandler
from openr_tpu.spark.io import MockIoHub

log = logging.getLogger(__name__)


# --------------------------------------------------------------- fault knobs


@dataclass(frozen=True)
class LinkFaults:
    """Per-delivery Spark packet faults (probabilities in [0, 1])."""

    drop: float = 0.0  # P(packet silently dropped)
    dup: float = 0.0  # P(packet delivered twice)
    reorder: float = 0.0  # P(packet held back by up to jitter_ms)
    jitter_ms: float = 0.0  # max hold-back for reordered packets


@dataclass(frozen=True)
class KvFaults:
    """KvStore peer-session faults (probabilities in [0, 1])."""

    fail_full_sync: float = 0.0  # P(full_sync raises ConnectionError)
    fail_flood: float = 0.0  # P(flood raises ConnectionError)
    delay_ms: float = 0.0  # max uniform delivery delay per call


@dataclass(frozen=True)
class FibFaults:
    fail_rate: float = 0.0  # P(one FibService op raises FibProgramError)


@dataclass(frozen=True)
class ChaosEvent:
    """One scheduled structural fault, relative to storm start."""

    at_s: float
    kind: str  # fail_link | heal_link | crash | restart | partition | heal_partition | disk_fault
    target: tuple = ()


#: storm-safe injected disk faults (persist/faults.py KINDS minus
#: crash_between_rename — compaction rarely runs inside a short storm
#: window, so arming it would usually be a silent no-op)
DISK_FAULT_KINDS = ("torn", "corrupt", "enospc")


class ChaosPlan:
    """Seeded fault configuration + deterministic fault schedule.

    One plan drives all three seams. Rate-based faults (packet drops,
    kv call failures, fib failures) are gated by ``active`` — the storm
    runner clears it after the last scheduled event so the cluster can
    quiesce; structural faults (downed links, partitions, crashed
    nodes) are only undone by their own heal/restart events.
    """

    def __init__(
        self,
        seed: int,
        link_faults: LinkFaults | None = None,
        kv_faults: KvFaults | None = None,
        fib_faults: FibFaults | None = None,
        link_overrides: dict | None = None,
    ):
        self.seed = int(seed)
        self.link_faults = link_faults or LinkFaults()
        self.kv_faults = kv_faults or KvFaults()
        self.fib_faults = fib_faults or FibFaults()
        # frozenset({a, b}) -> LinkFaults, overriding the default per link
        self.link_overrides: dict[frozenset, LinkFaults] = dict(
            link_overrides or {}
        )
        self.active = True
        self.events: tuple[ChaosEvent, ...] = ()
        self.stats: dict[str, int] = {}
        self._streams: dict[str, random.Random] = {}
        self._kv_blocked: set[frozenset] = set()

    # ------------------------------------------------------------ randomness

    def rng(self, stream: str) -> random.Random:
        """Named deterministic substream: seeded from (seed, stream), so
        one seam's consumption never perturbs another's."""
        r = self._streams.get(stream)
        if r is None:
            digest = hashlib.sha256(
                f"{self.seed}/{stream}".encode()
            ).digest()
            r = self._streams[stream] = random.Random(
                int.from_bytes(digest[:8], "big")
            )
        return r

    def note(self, what: str, n: int = 1) -> None:
        self.stats[what] = self.stats.get(what, 0) + n

    # ------------------------------------------------------------- partition

    def block_kv(self, a: str, b: str) -> None:
        self._kv_blocked.add(frozenset((a, b)))

    def unblock_kv_all(self) -> None:
        self._kv_blocked.clear()

    def kv_blocked(self, a: str, b: str) -> bool:
        return frozenset((a, b)) in self._kv_blocked

    # -------------------------------------------------------------- schedule

    def build_storm(
        self,
        links,
        nodes=(),
        *,
        duration_s: float = 2.0,
        n_flaps: int = 0,
        n_crashes: int = 0,
        n_partitions: int = 0,
        heal_after_s: float = 0.6,
        graceful_crashes: bool | None = True,
        n_disk_faults: int = 0,
    ) -> tuple[ChaosEvent, ...]:
        """Deterministic storm schedule from the plan's seed: same seed +
        same arguments → the identical event list (see `schedule_hash`).

        `links` is an iterable of (a, b) node-name pairs; `nodes` the
        crash/partition candidate set. Crash targets are sampled without
        replacement, so no node is crashed while already down; each
        structural fault heals `heal_after_s` after it fires.
        `graceful_crashes`: True → every crash announces Spark GR,
        False → every crash is hard (hold-timer detection), None →
        seeded 50/50 mix.
        `n_disk_faults`: crash archetypes with a one-shot disk fault
        (DISK_FAULT_KINDS, seeded) armed in the victim's persist plane
        just before a HARD kill — the restart must warm-boot through
        the damaged journal (docs/Persist.md fault matrix). Targets
        come from the same without-replacement pool as plain crashes.
        """
        rng = self.rng("schedule")
        links = sorted(tuple(sorted(l)) for l in links)
        # dedupe: callers often pass node lists derived from edge lists;
        # sampling positions of a multiset would break the
        # no-node-crashed-twice guarantee below
        nodes = sorted(set(nodes))
        ev: list[ChaosEvent] = []
        horizon = max(duration_s - heal_after_s, 0.01)
        for _ in range(n_flaps):
            a, b = links[rng.randrange(len(links))]
            t = round(rng.uniform(0, horizon), 4)
            ev.append(ChaosEvent(t, "fail_link", (a, b)))
            ev.append(
                ChaosEvent(round(t + heal_after_s, 4), "heal_link", (a, b))
            )
        victims = rng.sample(
            nodes, min(n_crashes + n_disk_faults, len(nodes))
        )
        for i, name in enumerate(victims):
            t = round(rng.uniform(0, horizon), 4)
            if i < n_crashes:
                graceful = (
                    rng.random() < 0.5
                    if graceful_crashes is None
                    else graceful_crashes
                )
            else:
                # disk-fault crash: arm the fault, then kill HARD — a
                # graceful shutdown would fsync/close around the damage
                kind = DISK_FAULT_KINDS[rng.randrange(len(DISK_FAULT_KINDS))]
                ev.append(ChaosEvent(t, "disk_fault", (name, kind)))
                t = round(t + 0.05, 4)
                graceful = False
            ev.append(ChaosEvent(t, "crash", (name, graceful)))
            ev.append(
                ChaosEvent(round(t + heal_after_s, 4), "restart", (name,))
            )
        for _ in range(n_partitions):
            t = round(rng.uniform(0, horizon), 4)
            shuffled = list(nodes)
            rng.shuffle(shuffled)
            cut = rng.randrange(1, max(len(shuffled), 2))
            g1 = tuple(sorted(shuffled[:cut]))
            g2 = tuple(sorted(shuffled[cut:]))
            ev.append(ChaosEvent(t, "partition", (g1, g2)))
            ev.append(
                ChaosEvent(round(t + heal_after_s, 4), "heal_partition", ())
            )
        ev.sort(key=lambda e: (e.at_s, e.kind, e.target))
        self.events = tuple(ev)
        return self.events

    def schedule_hash(self) -> str:
        """Stable digest of everything that shapes the storm: seed,
        fault rates, per-link overrides, and the built schedule."""
        overrides = sorted(
            (tuple(sorted(k)), v) for k, v in self.link_overrides.items()
        )
        payload = repr(
            (
                self.seed,
                self.link_faults,
                self.kv_faults,
                self.fib_faults,
                overrides,
                self.events,
            )
        )
        return hashlib.sha256(payload.encode()).hexdigest()

    def replay_hint(self) -> str:
        """What a failure message must carry to reproduce the run."""
        return (
            f"ChaosPlan(seed={self.seed}) "
            f"schedule_sha256={self.schedule_hash()[:16]}"
        )

    # ---------------------------------------------------------- seam lookups

    def faults_for_link(self, a_node: str, b_node: str) -> LinkFaults:
        return self.link_overrides.get(
            frozenset((a_node, b_node)), self.link_faults
        )

    def disk_injector(self, node_name: str):
        """Seeded per-node DiskFaultInjector (persist/faults.py) wired
        into this plan's stats — the durable-storage seam's equivalent
        of ChaosFibHandler: fault offsets/bit positions come from the
        ``disk/<node>`` substream and every fired fault lands in
        ``plan.stats`` as ``disk.<kind>``."""
        from openr_tpu.persist.faults import DiskFaultInjector

        return DiskFaultInjector(
            rng=self.rng(f"disk/{node_name}"), note=self.note
        )


# ------------------------------------------------------------ Spark packets


class ChaosIoHub(MockIoHub):
    """MockIoHub whose delivery seam injects seeded per-packet faults.

    Reordering is modelled as a random hold-back (up to jitter_ms):
    later packets on the link overtake the held one, which is exactly
    the UDP reordering Spark has to tolerate.
    """

    def __init__(self, plan: ChaosPlan):
        super().__init__()
        self.plan = plan

    def _enqueue(self, lk, dst_node, dst_if, payload, inbox) -> None:
        plan = self.plan
        if plan.active:
            f = plan.faults_for_link(lk.a[0], lk.b[0])
            if f.drop or f.dup or f.reorder:
                rng = plan.rng("io")
                if f.drop and rng.random() < f.drop:
                    plan.note("io.dropped")
                    return
                if f.dup and rng.random() < f.dup:
                    plan.note("io.duplicated")
                    super()._enqueue(lk, dst_node, dst_if, payload, inbox)
                if (
                    f.reorder
                    and f.jitter_ms > 0
                    and rng.random() < f.reorder
                ):
                    plan.note("io.reordered")
                    asyncio.get_event_loop().call_later(
                        rng.uniform(0.2, 1.0) * f.jitter_ms / 1e3,
                        self._late_deliver,
                        dst_node,
                        dst_if,
                        payload,
                    )
                    return
        super()._enqueue(lk, dst_node, dst_if, payload, inbox)

    def _late_deliver(self, dst_node: str, dst_if: str, payload: bytes) -> None:
        # _inbox_put re-resolves the inbox at fire time (the destination
        # may have crashed while the packet was held back) and enforces
        # the inbox bound
        self._inbox_put(dst_node, dst_if, payload)


# ---------------------------------------------------------- KvStore sessions


class ChaosKvTransport:
    """Per-node wrapper around a shared InProcKvTransport registry.

    Each node gets its own wrapper (the `owner`), so sessions know both
    endpoints — that is what lets a partition block exactly the
    cross-group pairs while intra-group peering keeps working.
    """

    def __init__(self, inner, plan: ChaosPlan, owner: str):
        self._inner = inner
        self.plan = plan
        self.owner = owner

    def register(self, node_name: str, store) -> None:
        self._inner.register(node_name, store)

    def unregister(self, node_name: str) -> None:
        self._inner.unregister(node_name)

    async def connect(self, peer_id: str, endpoint, counters=None):
        if self.plan.kv_blocked(self.owner, peer_id):
            self.plan.note("kv.connect_blocked")
            raise ConnectionError(
                f"chaos: kv partition {self.owner} | {peer_id}"
            )
        session = await self._inner.connect(
            peer_id, endpoint, counters=counters
        )
        return _ChaosKvSession(session, self.plan, self.owner, peer_id)

    @property
    def codec(self) -> str | None:
        """Expose the wrapped transport's wire codec so KvStore's
        serialize-once fan-out stays active under chaos."""
        return getattr(self._inner, "codec", None)


class _ChaosKvSession:
    @property
    def codec(self):
        """Delegate the per-session wire codec so KvStore's serialize-
        once drain check sees through the chaos wrapper."""
        return getattr(self._inner, "codec", None)

    def __init__(self, inner, plan: ChaosPlan, owner: str, peer_id: str):
        self._inner = inner
        self.plan = plan
        self.owner = owner
        self.peer_id = peer_id

    async def _gate(self, op: str, fail_p: float) -> None:
        """Partition check + seeded delay/failure for one session call.
        A raised ConnectionError feeds KvStore's own recovery: the
        caller drops the session and schedules a FULL_SYNC repair."""
        plan = self.plan
        if plan.kv_blocked(self.owner, self.peer_id):
            plan.note(f"kv.{op}_blocked")
            raise ConnectionError(
                f"chaos: kv partition {self.owner} | {self.peer_id}"
            )
        if not plan.active:
            return
        f = plan.kv_faults
        rng = plan.rng("kv")
        if f.delay_ms > 0:
            await asyncio.sleep(rng.uniform(0, f.delay_ms) / 1e3)
        if fail_p and rng.random() < fail_p:
            plan.note(f"kv.{op}_failed")
            raise ConnectionError(
                f"chaos: injected {op} failure "
                f"{self.owner} -> {self.peer_id}"
            )

    async def full_sync(self, area, sender_id, digest, store_hash=None):
        await self._gate("full_sync", self.plan.kv_faults.fail_full_sync)
        return await self._inner.full_sync(
            area, sender_id, digest, store_hash=store_hash
        )

    async def flood(self, pub):
        await self._gate("flood", self.plan.kv_faults.fail_flood)
        return await self._inner.flood(pub)

    async def dual_messages(self, area, sender, msgs):
        await self._gate("dual", 0.0)
        await self._inner.dual_messages(area, sender, msgs)

    async def flood_topo_set(self, area, root, child, set_flag):
        await self._gate("flood_topo_set", 0.0)
        await self._inner.flood_topo_set(area, root, child, set_flag)

    async def close(self) -> None:
        await self._inner.close()


# ------------------------------------------------------------- Fib handler


class ChaosFibHandler(MockFibHandler):
    """MockFibHandler with plan-gated seeded failure rate: failures stop
    the moment the storm runner deactivates the plan, so Fib's backoff
    can drain and the invariant check sees a quiescent dataplane."""

    def __init__(self, plan: ChaosPlan, node_name: str):
        super().__init__(
            fail_rate=plan.fib_faults.fail_rate,
            rng=plan.rng(f"fib/{node_name}"),
        )
        self.plan = plan

    def _fail_maybe(self):
        if not self.plan.active:
            # storm over: suppress only the RATE faults — the inherited
            # count-based fail_next_n contract stays honored so
            # post-storm tests can still inject deterministic failures
            saved, self.fail_rate = self.fail_rate, 0.0
            try:
                super()._fail_maybe()
            finally:
                self.fail_rate = saved
            return
        try:
            super()._fail_maybe()
        except Exception:
            self.plan.note("fib.op_failed")
            raise


# ------------------------------------------------------------ storm runner


async def run_schedule(cluster, plan: ChaosPlan, events=None) -> None:
    """Execute a fault schedule against a Cluster in real time.

    Events fire at their `at_s` offsets from call time; when the last
    one has run, `plan.active` is cleared so rate-based faults stop and
    the cluster can quiesce for the invariant check. Crash/restart
    events are skipped when their target is already in the requested
    state (overlapping storms compose instead of crashing the runner).
    """
    events = plan.events if events is None else tuple(events)
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    try:
        for ev in sorted(events, key=lambda e: e.at_s):
            delay = t0 + ev.at_s - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            await _dispatch(cluster, ev)
    finally:
        plan.active = False


async def _dispatch(cluster, ev: ChaosEvent) -> None:
    log.debug("chaos: t=%.3fs %s %r", ev.at_s, ev.kind, ev.target)
    if ev.kind == "fail_link":
        await _maybe_await(cluster.fail_link(*ev.target))
    elif ev.kind == "heal_link":
        await _maybe_await(cluster.heal_link(*ev.target))
    elif ev.kind == "crash":
        name, graceful = ev.target
        if name in cluster.nodes:
            await cluster.crash_node(name, graceful=graceful)
    elif ev.kind == "restart":
        (name,) = ev.target
        if name in cluster.crashed:
            await cluster.restart_node(name)
    elif ev.kind == "partition":
        await _maybe_await(cluster.partition(ev.target))
    elif ev.kind == "heal_partition":
        await _maybe_await(cluster.heal_partition())
    elif ev.kind == "disk_fault":
        name, kind = ev.target
        inject = getattr(cluster, "inject_disk_fault", None)
        # only the multi-process harness has a persist plane to damage;
        # the in-process emulator skips the arming (the paired hard
        # crash still fires)
        if inject is not None and name in cluster.nodes:
            await _maybe_await(inject(name, kind))
    else:
        raise ValueError(f"unknown chaos event kind {ev.kind!r}")


async def _maybe_await(result) -> None:
    """Link/partition faults are sync dict flips on the in-process
    Cluster but ctrl round trips on the multi-process ProcCluster —
    one dispatcher serves both method surfaces."""
    if asyncio.iscoroutine(result):
        await result
