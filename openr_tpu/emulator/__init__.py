"""Multi-node in-process emulator (reference: openr/tests/OpenrWrapper †).

`Cluster` spins N complete OpenrNodes in one process: Spark packets run
over `MockIoHub` links, KvStore peering over `InProcKvTransport`, and
route programming into per-node `MockFibHandler`s — the reference's
multi-node-without-a-cluster testing pattern, also used by the
`python -m openr_tpu.emulator` CLI for interactive convergence demos.
"""

from openr_tpu.emulator.chaos import (  # noqa: F401
    ChaosEvent,
    ChaosFibHandler,
    ChaosIoHub,
    ChaosKvTransport,
    ChaosPlan,
    FibFaults,
    KvFaults,
    LinkFaults,
    run_schedule,
)
from openr_tpu.emulator.cluster import Cluster, ClusterNodeSpec, LinkSpec  # noqa: F401
from openr_tpu.emulator.convergence import measure_convergence  # noqa: F401
from openr_tpu.emulator.invariants import (  # noqa: F401
    Violation,
    assert_invariants,
    check_cluster,
    dump_flight_recorders,
    wait_quiescent,
)
from openr_tpu.emulator.tracing import (  # noqa: F401
    collect_flood_traces,
    trace_report,
)
