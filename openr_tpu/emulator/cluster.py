"""In-process cluster of full OpenrNodes over mock I/O.

reference: openr/tests/OpenrWrapper.{h,cpp} † + OpenrTest — the entire
module graph per simulated node, N nodes in one process, connected via
MockIoProvider + in-process peering; asserts end-to-end convergence
(neighbor up → routes appear everywhere) and churn scenarios.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field

from openr_tpu.config import Config, NodeConfig, OriginatedPrefix, SparkConfig
from openr_tpu.kvstore import InProcKvTransport
from openr_tpu.node import OpenrNode
from openr_tpu.spark import MockIoHub

log = logging.getLogger(__name__)


# fast timers so integration tests converge in fractions of a second
FAST_SPARK = SparkConfig(
    hello_time_ms=60,
    fastinit_hello_time_ms=20,
    handshake_time_ms=20,
    keepalive_time_ms=40,
    hold_time_ms=400,
    graceful_restart_time_ms=1200,
)


def scaled_spark(n_nodes: int) -> SparkConfig:
    """Spark timers scaled to the emulation's CPU oversubscription.

    N routers share one host core, so hello/keepalive SERVICE latency
    grows with N: during a convergence wave every node rebuilds
    (~10-20 ms each, serialized), and with FAST_SPARK's 400 ms hold a
    ~100-node cluster's holds expire mid-wave → neighbors withdrawn →
    re-flood → more rebuilds → a self-sustaining flap storm (observed:
    route counts oscillating 98→56→99 forever at n=100 while n=81
    converged in 6 s — congestion collapse, not a protocol bug; real
    deployments tune hold timers to platform service latency for the
    same reason †). Scale hold with N, keeping the small-cluster
    defaults untouched below 64 nodes."""
    if n_nodes <= 64:
        return FAST_SPARK
    f = FAST_SPARK  # single source of truth for the small-cluster base
    factor = n_nodes / 64
    return SparkConfig(
        hello_time_ms=int(f.hello_time_ms * factor),
        fastinit_hello_time_ms=int(f.fastinit_hello_time_ms * factor),
        handshake_time_ms=int(f.handshake_time_ms * factor),
        keepalive_time_ms=int(f.keepalive_time_ms * factor),
        hold_time_ms=int(f.hold_time_ms * factor * 2),
        graceful_restart_time_ms=int(
            f.graceful_restart_time_ms * factor * 2
        ),
    )


@dataclass
class ClusterNodeSpec:
    name: str
    loopback: str | None = None  # originated prefix, e.g. "10.0.0.1/32"
    config: NodeConfig | None = None  # full override


@dataclass
class LinkSpec:
    a: str
    b: str
    metric: int = 1  # applied symmetrically via LinkMonitor metric override
    latency_ms: float = 0.0
    a_if: str = ""
    b_if: str = ""

    def __post_init__(self):
        self.a_if = self.a_if or f"if-{self.a}-{self.b}"
        self.b_if = self.b_if or f"if-{self.b}-{self.a}"


def loopback_of(i: int) -> str:
    return f"10.{(i >> 8) & 0xFF}.{i & 0xFF}.1/32"


@dataclass
class Cluster:
    """N full nodes + links, one asyncio loop."""

    nodes: dict[str, OpenrNode] = field(default_factory=dict)
    hub: MockIoHub = field(default_factory=MockIoHub)
    transport: InProcKvTransport = field(default_factory=InProcKvTransport)
    links: list[LinkSpec] = field(default_factory=list)
    solver: str = "cpu"  # integration tests default to the oracle backend
    enable_ctrl: bool = False
    # chaos wiring (emulator/chaos.py): when set, the hub is a
    # ChaosIoHub, each node's kv transport is a per-node ChaosKvTransport
    # and its fib handler a plan-gated ChaosFibHandler
    chaos: object | None = None
    # crashed-but-restartable nodes: name -> (Config, fib_handler) — the
    # handler IS the emulated dataplane, surviving the control-plane
    # crash so restart_node exercises Fib warm boot
    crashed: dict[str, tuple] = field(default_factory=dict)
    _partitioned: list[LinkSpec] = field(default_factory=list)

    @staticmethod
    def build(
        node_specs: list[ClusterNodeSpec],
        link_specs: list[LinkSpec],
        solver: str = "cpu",
        debounce_ms: tuple[int, int] | None = None,
        enable_ctrl: bool = False,
        chaos=None,
        node_config_transform=None,
        wire_codec: str = "bin",
    ) -> "Cluster":
        c = Cluster(solver=solver, enable_ctrl=enable_ctrl, chaos=chaos)
        # wire codec for the whole emulated cluster (docs/Wire.md):
        # "bin" = serialize-once compact binary floods + binary Spark
        # packets (the production path chaos/soak validate); "json" =
        # the legacy per-peer text framing (bench_churn --flood-bench's
        # measured baseline)
        c.transport = InProcKvTransport(codec=wire_codec)
        if chaos is not None:
            from openr_tpu.emulator.chaos import ChaosIoHub

            c.hub = ChaosIoHub(chaos)
        spark_cfg = scaled_spark(len(node_specs))
        if debounce_ms is None:
            # Decision debounce scales with CPU oversubscription for
            # the same reason the Spark timers do (scaled_spark): in a
            # convergence wave every node receives ~N publications, and
            # a 60 ms coalescing cap on one shared core means hundreds
            # of redundant full rebuilds competing with the hello
            # service — rebuild starvation is the 256-node collapse
            # mode. Small clusters keep the responsive default.
            n = len(node_specs)
            debounce_ms = (
                (10, 60) if n <= 64 else (10, int(60 * (n / 64) * 2))
            )
        for spec in node_specs:
            ncfg = spec.config
            if (
                ncfg is not None
                and ncfg.spark.hold_time_ms < spark_cfg.hold_time_ms
            ):
                # explicit configs are honored verbatim, but a hold
                # below the oversubscription-scaled value silently
                # reintroduces the flap storm scaled_spark exists to
                # prevent — say so
                log.warning(
                    "%s: explicit spark hold %d ms is below the %d ms "
                    "scaled for a %d-node emulation; hello starvation "
                    "may flap this node's adjacencies",
                    spec.name, ncfg.spark.hold_time_ms,
                    spark_cfg.hold_time_ms, len(node_specs),
                )
            if ncfg is None:
                originated = ()
                if spec.loopback:
                    originated = (OriginatedPrefix(prefix=spec.loopback),)
                ncfg = NodeConfig(
                    node_name=spec.name,
                    spark=spark_cfg,
                    originated_prefixes=originated,
                )
            # copy-on-write: never mutate a caller-supplied NodeConfig
            from dataclasses import replace

            ncfg = replace(
                ncfg,
                decision=replace(
                    ncfg.decision,
                    debounce_min_ms=debounce_ms[0],
                    debounce_max_ms=debounce_ms[1],
                ),
                spark=replace(ncfg.spark, wire_codec=wire_codec),
            )
            if node_config_transform is not None:
                # last word on every node's config (e.g. the soak's
                # unbounded-control case flips messaging.enforce_bounds)
                # — keeps callers out of the per-node wiring below
                ncfg = node_config_transform(ncfg)
            cfg = Config(ncfg)
            node = OpenrNode(
                cfg,
                c.hub.io_for(spec.name),
                c._transport_for(spec.name),
                fib_handler=c._fib_handler_for(spec.name),
                solver=solver,
                enable_ctrl=enable_ctrl,
            )
            c.transport.register(spec.name, node.kvstore)
            c.nodes[spec.name] = node
        for ls in link_specs:
            c.links.append(ls)
        return c

    @staticmethod
    def from_edges(
        edges: list[tuple[str, str]] | list[LinkSpec],
        solver: str = "cpu",
        enable_ctrl: bool = False,
        chaos=None,
        node_config_transform=None,
        wire_codec: str = "bin",
    ) -> "Cluster":
        links = [
            e if isinstance(e, LinkSpec) else LinkSpec(a=e[0], b=e[1])
            for e in edges
        ]
        names = sorted({l.a for l in links} | {l.b for l in links})
        specs = [
            ClusterNodeSpec(name=n, loopback=loopback_of(i))
            for i, n in enumerate(names)
        ]
        return Cluster.build(
            specs, links, solver=solver, enable_ctrl=enable_ctrl, chaos=chaos,
            node_config_transform=node_config_transform,
            wire_codec=wire_codec,
        )

    def _transport_for(self, name: str):
        """Per-node kv transport view: the chaos wrapper needs to know
        which node OWNS the outgoing sessions (partition blocking is a
        pair property); without chaos the shared registry is used as-is."""
        if self.chaos is None:
            return self.transport
        from openr_tpu.emulator.chaos import ChaosKvTransport

        return ChaosKvTransport(self.transport, self.chaos, name)

    def _fib_handler_for(self, name: str):
        """Plan-gated fault-injecting FibService per node, or None to
        let OpenrNode build its default MockFibHandler."""
        if self.chaos is None or self.chaos.fib_faults.fail_rate <= 0:
            return None
        from openr_tpu.emulator.chaos import ChaosFibHandler

        return ChaosFibHandler(self.chaos, name)

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> None:
        for node in self.nodes.values():
            await node.start()
        for ls in self.links:
            self.hub.link(ls.a, ls.a_if, ls.b, ls.b_if, latency_ms=ls.latency_ms)
            if ls.metric != 1:
                self.nodes[ls.a].linkmonitor.set_link_metric(ls.a_if, ls.metric)
                self.nodes[ls.b].linkmonitor.set_link_metric(ls.b_if, ls.metric)
            self.nodes[ls.a].set_interface(ls.a_if, up=True)
            self.nodes[ls.b].set_interface(ls.b_if, up=True)

    async def stop(self) -> None:
        for node in self.nodes.values():
            await node.stop()

    # ----------------------------------------------------------- assertions

    def converged(self) -> bool:
        """Every node initialized and programs a route to every other
        node's loopback."""
        n_remote = len(self.nodes) - 1
        for node in self.nodes.values():
            if not node.initialized:
                return False
            if len(node.fib.programmed_unicast) < n_remote:
                return False
        return True

    async def wait_converged(self, timeout: float = 30.0) -> None:
        t0 = asyncio.get_event_loop().time()
        while not self.converged():
            if asyncio.get_event_loop().time() - t0 > timeout:
                detail = {
                    name: (
                        node.initialized,
                        len(node.fib.programmed_unicast),
                    )
                    for name, node in self.nodes.items()
                }
                raise TimeoutError(f"cluster did not converge: {detail}")
            await asyncio.sleep(0.02)

    def fleet_counters(self, prefix: str = "") -> dict:
        """Cluster-wide counter distributions (docs/Monitor.md "Fleet
        aggregation"): every live node's Counters snapshot folded into
        per-key cross-node min/p50/p99/max — the emulator-side twin of
        ``breeze monitor fleet``."""
        from openr_tpu.monitor.fleet import aggregate_counters

        return aggregate_counters(
            {
                name: node.counters.snapshot()
                for name, node in self.nodes.items()
            },
            prefix=prefix,
        )

    # -------------------------------------------------------------- control

    def _links_between(self, a: str, b: str) -> list[LinkSpec]:
        found = [ls for ls in self.links if {ls.a, ls.b} == {a, b}]
        if not found:
            raise ValueError(f"no link between {a!r} and {b!r}")
        return found

    def fail_link(self, a: str, b: str) -> None:
        """Silent packet loss on the (a, b) link: the hub stops
        delivering, and the adjacency dies by Spark hold-timer expiry —
        neither endpoint is told. Raises ValueError when no such link
        exists (a typo'd pair must not be a silent no-op)."""
        for ls in self._links_between(a, b):
            self.hub.set_link(ls.a, ls.a_if, up=False)
            self.hub.set_link(ls.b, ls.b_if, up=False)

    def heal_link(self, a: str, b: str) -> None:
        """Undo fail_link. Asymmetric with it by design: fail models
        silent loss (hold-timer detection, no interface event), while
        heal re-ups the hub AND re-injects interface-up events on both
        endpoints so Spark restarts fast-init discovery immediately.
        Raises ValueError when no such link exists."""
        for ls in self._links_between(a, b):
            self.hub.set_link(ls.a, ls.a_if, up=True)
            self.hub.set_link(ls.b, ls.b_if, up=True)
            if ls.a in self.nodes:
                self.nodes[ls.a].set_interface(ls.a_if, up=True)
            if ls.b in self.nodes:
                self.nodes[ls.b].set_interface(ls.b_if, up=True)

    # ------------------------------------------------------- chaos: crash/GR

    async def crash_node(self, name: str, graceful: bool = False) -> None:
        """Control-plane crash: stop every module, drop the node's
        Spark inbox, and unregister its KvStore from the in-proc
        transport so peers' floods/full_syncs to it now FAIL (exercising
        their flood-failure → full-sync repair path). The MockFibHandler
        — the emulated dataplane — survives in `self.crashed`, so a
        later restart_node exercises Fib warm boot. With graceful=True
        the node first announces a Spark graceful restart, so neighbors
        hold the adjacency for gr_time instead of withdrawing at
        hold-timer expiry."""
        node = self.nodes.pop(name)  # KeyError: unknown or already crashed
        if graceful:
            # hub delivery is synchronous, so the GR hellos sit in peer
            # inboxes when this returns; stop() follows with NO
            # intervening yield — a hello tick sneaking in between
            # would send restarting=False and cancel the GR hold on
            # the receivers
            await node.spark.announce_restart()
        await node.stop()
        self.transport.unregister(name)
        self.hub.drop_node(name)
        self.crashed[name] = (node.config, node.fib_handler)

    async def restart_node(self, name: str) -> None:
        """Rebuild a crashed node from its retained Config and start it:
        KvStore re-syncs the LSDB from peers, Decision recomputes, and
        Fib warm-boots off the surviving MockFibHandler — the first
        program pass is an incremental delta against the adopted kernel
        state, so surviving prefixes see zero route-withdrawal gap."""
        cfg, handler = self.crashed.pop(name)
        node = OpenrNode(
            cfg,
            self.hub.io_for(name),
            self._transport_for(name),
            fib_handler=handler,
            solver=self.solver,
            enable_ctrl=self.enable_ctrl,
        )
        self.transport.register(name, node.kvstore)
        self.nodes[name] = node
        await node.start()
        for ls in self.links:
            if name not in (ls.a, ls.b):
                continue
            my_if = ls.a_if if ls.a == name else ls.b_if
            if ls.metric != 1:
                # mirror Cluster.start: a restarted node must rejoin
                # with its configured link weights, not the default
                node.linkmonitor.set_link_metric(my_if, ls.metric)
            node.set_interface(my_if, up=True)

    # ------------------------------------------------------ chaos: partition

    def partition(self, groups) -> None:
        """Split the cluster: every link whose endpoints belong to
        DIFFERENT groups — including one grouped endpoint vs one
        ungrouped — goes down at the packet layer; a link between two
        ungrouped nodes is untouched. When the cluster is
        chaos-wrapped, the KvStore transport additionally refuses the
        same cross-group pairs immediately, so established kv sessions
        break like real sockets would instead of lingering until Spark
        hold expiry. Unknown names raise ValueError (same contract as
        fail_link). Repeated partitions compose; `heal_partition`
        heals them all."""
        all_names = set(self.nodes) | set(self.crashed)
        membership: dict[str, int] = {}
        for gi, group in enumerate(groups):
            for n in group:
                if n not in all_names:
                    # same contract as fail_link: a typo'd name must not
                    # silently reshape the split
                    raise ValueError(f"partition group names unknown node {n!r}")
                membership[n] = gi
        for ls in self.links:
            ga, gb = membership.get(ls.a), membership.get(ls.b)
            if ga == gb and ga is not None:
                continue
            if ga is None and gb is None:
                continue  # both outside every group: untouched
            self.hub.set_link(ls.a, ls.a_if, up=False)
            self.hub.set_link(ls.b, ls.b_if, up=False)
            self._partitioned.append(ls)
        if self.chaos is not None:
            names = sorted(all_names)
            for i, a in enumerate(names):
                for b in names[i + 1 :]:
                    ga, gb = membership.get(a), membership.get(b)
                    if ga == gb and ga is not None:
                        continue
                    if ga is None and gb is None:
                        continue
                    self.chaos.block_kv(a, b)

    def heal_partition(self) -> None:
        """Re-up every partition-downed link (and re-inject interface-up
        on live endpoints, mirroring heal_link), and lift all KvStore
        pair blocks."""
        healed, self._partitioned = self._partitioned, []
        for ls in healed:
            self.hub.set_link(ls.a, ls.a_if, up=True)
            self.hub.set_link(ls.b, ls.b_if, up=True)
            if ls.a in self.nodes:
                self.nodes[ls.a].set_interface(ls.a_if, up=True)
            if ls.b in self.nodes:
                self.nodes[ls.b].set_interface(ls.b_if, up=True)
        if self.chaos is not None:
            self.chaos.unblock_kv_all()

    # ----------------------------------------------------- chaos: flap storm

    def make_storm(
        self,
        plan,
        *,
        duration_s: float = 2.0,
        n_flaps: int = 0,
        n_crashes: int = 0,
        n_partitions: int = 0,
        heal_after_s: float = 0.6,
        n_disk_faults: int = 0,
    ):
        """Flap-storm generator: build this cluster's deterministic
        fault schedule on `plan` (a ChaosPlan) from its own link/node
        sets. Run it with chaos.run_schedule(cluster, plan)."""
        return plan.build_storm(
            [(ls.a, ls.b) for ls in self.links],
            sorted(set(self.nodes) | set(self.crashed)),
            duration_s=duration_s,
            n_flaps=n_flaps,
            n_crashes=n_crashes,
            n_partitions=n_partitions,
            heal_after_s=heal_after_s,
            n_disk_faults=n_disk_faults,
        )
