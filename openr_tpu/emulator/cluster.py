"""In-process cluster of full OpenrNodes over mock I/O.

reference: openr/tests/OpenrWrapper.{h,cpp} † + OpenrTest — the entire
module graph per simulated node, N nodes in one process, connected via
MockIoProvider + in-process peering; asserts end-to-end convergence
(neighbor up → routes appear everywhere) and churn scenarios.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field

from openr_tpu.config import Config, NodeConfig, OriginatedPrefix, SparkConfig
from openr_tpu.kvstore import InProcKvTransport
from openr_tpu.node import OpenrNode
from openr_tpu.spark import MockIoHub

log = logging.getLogger(__name__)


# fast timers so integration tests converge in fractions of a second
FAST_SPARK = SparkConfig(
    hello_time_ms=60,
    fastinit_hello_time_ms=20,
    handshake_time_ms=20,
    keepalive_time_ms=40,
    hold_time_ms=400,
    graceful_restart_time_ms=1200,
)


def scaled_spark(n_nodes: int) -> SparkConfig:
    """Spark timers scaled to the emulation's CPU oversubscription.

    N routers share one host core, so hello/keepalive SERVICE latency
    grows with N: during a convergence wave every node rebuilds
    (~10-20 ms each, serialized), and with FAST_SPARK's 400 ms hold a
    ~100-node cluster's holds expire mid-wave → neighbors withdrawn →
    re-flood → more rebuilds → a self-sustaining flap storm (observed:
    route counts oscillating 98→56→99 forever at n=100 while n=81
    converged in 6 s — congestion collapse, not a protocol bug; real
    deployments tune hold timers to platform service latency for the
    same reason †). Scale hold with N, keeping the small-cluster
    defaults untouched below 64 nodes."""
    if n_nodes <= 64:
        return FAST_SPARK
    f = FAST_SPARK  # single source of truth for the small-cluster base
    factor = n_nodes / 64
    return SparkConfig(
        hello_time_ms=int(f.hello_time_ms * factor),
        fastinit_hello_time_ms=int(f.fastinit_hello_time_ms * factor),
        handshake_time_ms=int(f.handshake_time_ms * factor),
        keepalive_time_ms=int(f.keepalive_time_ms * factor),
        hold_time_ms=int(f.hold_time_ms * factor * 2),
        graceful_restart_time_ms=int(
            f.graceful_restart_time_ms * factor * 2
        ),
    )


@dataclass
class ClusterNodeSpec:
    name: str
    loopback: str | None = None  # originated prefix, e.g. "10.0.0.1/32"
    config: NodeConfig | None = None  # full override


@dataclass
class LinkSpec:
    a: str
    b: str
    metric: int = 1  # applied symmetrically via LinkMonitor metric override
    latency_ms: float = 0.0
    a_if: str = ""
    b_if: str = ""

    def __post_init__(self):
        self.a_if = self.a_if or f"if-{self.a}-{self.b}"
        self.b_if = self.b_if or f"if-{self.b}-{self.a}"


def loopback_of(i: int) -> str:
    return f"10.{(i >> 8) & 0xFF}.{i & 0xFF}.1/32"


@dataclass
class Cluster:
    """N full nodes + links, one asyncio loop."""

    nodes: dict[str, OpenrNode] = field(default_factory=dict)
    hub: MockIoHub = field(default_factory=MockIoHub)
    transport: InProcKvTransport = field(default_factory=InProcKvTransport)
    links: list[LinkSpec] = field(default_factory=list)
    solver: str = "cpu"  # integration tests default to the oracle backend

    @staticmethod
    def build(
        node_specs: list[ClusterNodeSpec],
        link_specs: list[LinkSpec],
        solver: str = "cpu",
        debounce_ms: tuple[int, int] | None = None,
        enable_ctrl: bool = False,
    ) -> "Cluster":
        c = Cluster(solver=solver)
        spark_cfg = scaled_spark(len(node_specs))
        if debounce_ms is None:
            # Decision debounce scales with CPU oversubscription for
            # the same reason the Spark timers do (scaled_spark): in a
            # convergence wave every node receives ~N publications, and
            # a 60 ms coalescing cap on one shared core means hundreds
            # of redundant full rebuilds competing with the hello
            # service — rebuild starvation is the 256-node collapse
            # mode. Small clusters keep the responsive default.
            n = len(node_specs)
            debounce_ms = (
                (10, 60) if n <= 64 else (10, int(60 * (n / 64) * 2))
            )
        for spec in node_specs:
            ncfg = spec.config
            if (
                ncfg is not None
                and ncfg.spark.hold_time_ms < spark_cfg.hold_time_ms
            ):
                # explicit configs are honored verbatim, but a hold
                # below the oversubscription-scaled value silently
                # reintroduces the flap storm scaled_spark exists to
                # prevent — say so
                log.warning(
                    "%s: explicit spark hold %d ms is below the %d ms "
                    "scaled for a %d-node emulation; hello starvation "
                    "may flap this node's adjacencies",
                    spec.name, ncfg.spark.hold_time_ms,
                    spark_cfg.hold_time_ms, len(node_specs),
                )
            if ncfg is None:
                originated = ()
                if spec.loopback:
                    originated = (OriginatedPrefix(prefix=spec.loopback),)
                ncfg = NodeConfig(
                    node_name=spec.name,
                    spark=spark_cfg,
                    originated_prefixes=originated,
                )
            # copy-on-write: never mutate a caller-supplied NodeConfig
            from dataclasses import replace

            ncfg = replace(
                ncfg,
                decision=replace(
                    ncfg.decision,
                    debounce_min_ms=debounce_ms[0],
                    debounce_max_ms=debounce_ms[1],
                ),
            )
            cfg = Config(ncfg)
            node = OpenrNode(
                cfg,
                c.hub.io_for(spec.name),
                c.transport,
                solver=solver,
                enable_ctrl=enable_ctrl,
            )
            c.transport.register(spec.name, node.kvstore)
            c.nodes[spec.name] = node
        for ls in link_specs:
            c.links.append(ls)
        return c

    @staticmethod
    def from_edges(
        edges: list[tuple[str, str]] | list[LinkSpec],
        solver: str = "cpu",
        enable_ctrl: bool = False,
    ) -> "Cluster":
        links = [
            e if isinstance(e, LinkSpec) else LinkSpec(a=e[0], b=e[1])
            for e in edges
        ]
        names = sorted({l.a for l in links} | {l.b for l in links})
        specs = [
            ClusterNodeSpec(name=n, loopback=loopback_of(i))
            for i, n in enumerate(names)
        ]
        return Cluster.build(specs, links, solver=solver, enable_ctrl=enable_ctrl)

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> None:
        for node in self.nodes.values():
            await node.start()
        for ls in self.links:
            self.hub.link(ls.a, ls.a_if, ls.b, ls.b_if, latency_ms=ls.latency_ms)
            if ls.metric != 1:
                self.nodes[ls.a].linkmonitor.set_link_metric(ls.a_if, ls.metric)
                self.nodes[ls.b].linkmonitor.set_link_metric(ls.b_if, ls.metric)
            self.nodes[ls.a].set_interface(ls.a_if, up=True)
            self.nodes[ls.b].set_interface(ls.b_if, up=True)

    async def stop(self) -> None:
        for node in self.nodes.values():
            await node.stop()

    # ----------------------------------------------------------- assertions

    def converged(self) -> bool:
        """Every node initialized and programs a route to every other
        node's loopback."""
        n_remote = len(self.nodes) - 1
        for node in self.nodes.values():
            if not node.initialized:
                return False
            if len(node.fib.programmed_unicast) < n_remote:
                return False
        return True

    async def wait_converged(self, timeout: float = 30.0) -> None:
        t0 = asyncio.get_event_loop().time()
        while not self.converged():
            if asyncio.get_event_loop().time() - t0 > timeout:
                detail = {
                    name: (
                        node.initialized,
                        len(node.fib.programmed_unicast),
                    )
                    for name, node in self.nodes.items()
                }
                raise TimeoutError(f"cluster did not converge: {detail}")
            await asyncio.sleep(0.02)

    # -------------------------------------------------------------- control

    def fail_link(self, a: str, b: str) -> None:
        for ls in self.links:
            if {ls.a, ls.b} == {a, b}:
                self.hub.set_link(ls.a, ls.a_if, up=False)
                self.hub.set_link(ls.b, ls.b_if, up=False)

    def heal_link(self, a: str, b: str) -> None:
        for ls in self.links:
            if {ls.a, ls.b} == {a, b}:
                self.hub.set_link(ls.a, ls.a_if, up=True)
                self.hub.set_link(ls.b, ls.b_if, up=True)
                self.nodes[a].set_interface(ls.a_if, up=True)
                self.nodes[b].set_interface(ls.b_if, up=True)
