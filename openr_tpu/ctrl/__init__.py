"""Control-plane API server (reference: openr/ctrl-server/ †).

The reference exposes one thrift service — `OpenrCtrl.thrift`, implemented
by `OpenrCtrlHandler` holding handles to every module — for operator and
programmatic access: KvStore get/set/dump + streaming subscription, route
queries (computed from Decision, programmed from Fib), adjacency dumps,
overload/link-metric mutation, initialization status, counters. We expose
the same surface over the framework's line-JSON RPC (openr_tpu/rpc/) with
server-push streams standing in for thrift server-streams.
"""

from openr_tpu.ctrl.server import CtrlServer

__all__ = ["CtrlServer"]
